"""Synchronized iterator.

Reference parity: ``chainermn/iterators/_synchronized_iterator.py`` —
``create_synchronized_iterator(actual_iterator, comm)``: broadcast the RNG
seed from rank 0 so every rank draws the same shuffle order each epoch.

TPU-native form: the seed agreement rides the control plane
(``bcast_obj``); the iterator is then re-seeded identically on every
process.  Under one controller this is trivially satisfied but still
exercised so tests match multi-process behavior.
"""

from __future__ import annotations

import numpy as np


def create_synchronized_iterator(actual_iterator, comm):
    """Re-seed ``actual_iterator`` with a communicator-agreed seed."""
    seed = int(np.random.randint(0, 2**31 - 1))
    seed = comm.bcast_obj(seed, root=0)
    rng = np.random.RandomState(seed)
    # Re-seed in place: the iterator draws every epoch's order from _rng.
    if hasattr(actual_iterator, "_rng"):
        actual_iterator._rng = rng
        if hasattr(actual_iterator, "reset"):
            actual_iterator.reset()
    return actual_iterator

"""Synchronized iterator.

Reference parity: ``chainermn/iterators/_synchronized_iterator.py`` —
``create_synchronized_iterator(actual_iterator, comm)``: broadcast the RNG
seed from rank 0 so every rank draws the same shuffle order each epoch.

TPU-native form: the seed agreement rides the control plane
(``bcast_obj``); the iterator is then re-seeded identically on every
process.  Under one controller this is trivially satisfied but still
exercised so tests match multi-process behavior.
"""

from __future__ import annotations

import numpy as np


def create_synchronized_iterator(actual_iterator, comm):
    """Re-seed ``actual_iterator`` with a communicator-agreed seed.

    All ranks/processes of the same communicator agree on the seed
    (``comm.sync_seed`` is agreed once, process 0's draw winning), and a
    per-call counter keeps *different* iterators independent — calls must
    happen in the same order on every process, exactly as the reference's
    per-call MPI broadcast required.
    """
    count = getattr(comm, "_sync_iterator_calls", 0)
    comm._sync_iterator_calls = count + 1
    rng = np.random.RandomState(
        (comm.sync_seed + 0x9E3779B9 * count) % (2**31 - 1)
    )
    # Re-seed in place: the iterator draws every epoch's order from _rng.
    if hasattr(actual_iterator, "_rng"):
        actual_iterator._rng = rng
        if hasattr(actual_iterator, "reset"):
            actual_iterator.reset()
    return actual_iterator

from .serial_iterator import SerialIterator  # noqa: F401
from .multi_node_iterator import create_multi_node_iterator  # noqa: F401
from .synchronized_iterator import create_synchronized_iterator  # noqa: F401
from .device_prefetch import prefetch_to_device  # noqa: F401

__all__ = [
    "SerialIterator",
    "create_multi_node_iterator",
    "create_synchronized_iterator",
    "prefetch_to_device",
]

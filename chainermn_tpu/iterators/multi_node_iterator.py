"""Multi-node iterator.

Reference parity: ``chainermn/iterators/_multi_node_iterator.py`` —
``create_multi_node_iterator(actual_iterator, comm, rank_master=0)``: the
master rank iterates the real dataset and broadcasts each batch; slave
ranks receive, so *all* ranks see identical batches (the model-parallel
pattern where every pipeline stage needs the same input stream).

TPU-native redesign: under a single controller, every model-parallel rank
already shares the host process, so "broadcast each batch" is: master
iterator draws the batch, and it is device_put replicated (or sharded along
model axes) over the mesh.  Under multi-process, the batch is broadcast
over the control plane so all processes feed identical arrays — the
same guarantee the MPI bcast gave, then placed as a global array.
"""

from __future__ import annotations

import numpy as np


class _MultiNodeIterator:
    def __init__(self, actual_iterator, comm, rank_master: int = 0):
        self._it = actual_iterator
        self._comm = comm
        self._rank_master = rank_master

    def __iter__(self):
        return self

    def __next__(self):
        batch = next(self._it)
        if self._comm.process_count > 1:
            # Make every controller agree on the master's batch
            # (parity: per-batch MPI bcast from rank_master).
            batch = self._comm.bcast_obj(batch, root=self._rank_master)
        return batch

    next = __next__

    def __getattr__(self, name):
        return getattr(self._it, name)


def create_multi_node_iterator(actual_iterator, comm, rank_master: int = 0):
    """All ranks receive the master's batch stream (see module docstring)."""
    return _MultiNodeIterator(actual_iterator, comm, rank_master)

"""Minimal epoch-aware batch iterator.

The reference leaned on Chainer's ``SerialIterator``/``MultiprocessIterator``
(external to chainermn); this framework needs its own host-side iterator to
hang the multi-node/synchronized wrappers on.  It yields stacked NumPy
batches ready for ``jax.device_put`` onto a data-sharded mesh.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def _collate(samples):
    first = samples[0]
    if isinstance(first, tuple):
        return tuple(
            np.stack([np.asarray(s[i]) for s in samples])
            for i in range(len(first))
        )
    if isinstance(first, dict):
        return {
            k: np.stack([np.asarray(s[k]) for s in samples]) for k in first
        }
    if first is None:
        return None
    return np.stack([np.asarray(s) for s in samples])


class SerialIterator:
    def __init__(self, dataset, batch_size: int, *, repeat: bool = True,
                 shuffle: bool = True, seed: Optional[int] = None,
                 drop_last: bool = True):
        self.dataset = dataset
        self.batch_size = batch_size
        self._repeat = repeat
        self._shuffle = shuffle
        self._drop_last = drop_last
        self._rng = np.random.RandomState(seed)
        self.epoch = 0
        self.is_new_epoch = False
        self._pos = 0
        self._order = self._new_order()

    def _new_order(self):
        n = len(self.dataset)
        return self._rng.permutation(n) if self._shuffle else np.arange(n)

    def reset(self):
        self.epoch = 0
        self._pos = 0
        self.is_new_epoch = False
        self._order = self._new_order()

    @property
    def epoch_detail(self) -> float:
        return self.epoch + self._pos / max(len(self.dataset), 1)

    def __iter__(self):
        return self

    def __next__(self):
        n = len(self.dataset)
        if self._pos >= n or (self._drop_last and self._pos + self.batch_size > n):
            if not self._repeat and self.epoch >= 0 and self._pos > 0:
                raise StopIteration
            self.epoch += 1
            self.is_new_epoch = True
            self._pos = 0
            self._order = self._new_order()
        else:
            self.is_new_epoch = False
        idx = self._order[self._pos : self._pos + self.batch_size]
        self._pos += self.batch_size
        return _collate([self.dataset[int(i)] for i in idx])

    next = __next__

    def serialize(self):
        """Cheap state snapshot — called per *batch* by
        prefetch_to_device (checkpoint-rewind bookkeeping), so it must
        not do O(dataset) work: ``_order`` is returned by reference
        (``_new_order`` replaces it each epoch, never mutates in
        place), and arrays beat giant Python lists in the orbax
        checkpoint path anyway (one leaf vs one leaf per element).

        The FULL RNG state is captured so a resumed run reshuffles
        identically to the uninterrupted one — with ``shuffle=True``,
        an epoch boundary crossed after restore calls ``_new_order()``,
        which must draw the same permutation."""
        kind, keys, pos, has_gauss, cached = self._rng.get_state()
        return {
            "epoch": self.epoch,
            "pos": self._pos,
            "order": self._order,
            "rng_kind": kind,
            "rng_keys": keys.copy(),
            "rng_pos": pos,
            "rng_has_gauss": has_gauss,
            "rng_cached": cached,
        }

    def restore(self, state):
        self.epoch = int(state["epoch"])
        self._pos = int(state["pos"])
        # RNG first: an elastic world resize clears ``order`` (the
        # permutation is per-shard-width — resilience.elastic.
        # reshard_iterator_state), and the redraw below must come from
        # the RESTORED stream so the new world's shuffle is
        # deterministic.
        if "rng_keys" in state:
            self._rng.set_state((
                str(state.get("rng_kind", "MT19937")),
                np.asarray(state["rng_keys"], np.uint32),
                int(state["rng_pos"]),
                int(state.get("rng_has_gauss", 0)),
                float(state.get("rng_cached", 0.0)),
            ))
        order = state.get("order")
        self._order = (
            self._new_order() if order is None else np.asarray(order)
        )


class EpochIterator:
    """Non-repeating pass over a dataset (used by the evaluator).

    ``pad_to``: pad the final partial batch to a multiple by wrapping to
    the dataset's start — the same equalization trick the reference's
    ``scatter_dataset`` used for shards, so sharded evaluation never sees
    an indivisible batch (slight over-weighting of the first samples on
    the last batch, as in the reference).
    """

    def __init__(self, dataset, batch_size: int, pad_to: int = 1):
        self.dataset = dataset
        self.batch_size = batch_size
        self.pad_to = max(pad_to, 1)

    def __iter__(self):
        n = len(self.dataset)
        for start in range(0, n, self.batch_size):
            idx = list(range(start, min(start + self.batch_size, n)))
            if len(idx) % self.pad_to:
                pad = self.pad_to - len(idx) % self.pad_to
                idx += [i % n for i in range(pad)]
            yield _collate([self.dataset[i] for i in idx])

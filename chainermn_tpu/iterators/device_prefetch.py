"""Host->device transfer overlap for input pipelines.

Reference parity: the reference hid input latency with multiprocess
workers feeding pinned CUDA buffers (``chainer.iterators``'s prefetch +
CuPy streams).  The TPU-native equivalent exploits JAX's *asynchronous
dispatch*: ``device_put`` (and any jitted step) returns before the
transfer/compute finishes, so placing batch ``i+1`` immediately after
dispatching step ``i`` overlaps the H2D copy with device compute — no
threads, no streams, just not blocking on the next array.

``prefetch_to_device`` wraps a host-batch iterator so that ``depth``
batches are always resident (or in flight) on the device: the caller
pops a ready batch, and the wrapper tops the queue back up *before*
returning, which is when the previous step's compute is still running.

Typical wiring (the ``--native-loader`` path)::

    loader = NativeImageLoader(...)
    it = prefetch_to_device(iter(loader), step.place_batch, depth=2)
    for batch in it:            # already a placed global jax.Array
        params, opt_state, m = step(params, opt_state, batch)
"""

from __future__ import annotations

import collections
from typing import Callable, Iterator, Optional


class _DevicePrefetcher:
    def __init__(self, it: Iterator, place_fn: Callable, depth: int,
                 snapshot_states: bool = True):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._it = it
        self._place = place_fn
        self._depth = depth
        self._buf: collections.deque = collections.deque()
        # Serialize-state snapshot taken just before each buffered batch
        # was drawn, aligned 1:1 with _buf.  Checkpointing through the
        # prefetcher must not skip buffered-but-unconsumed batches: the
        # resumable position is where the *oldest unconsumed* batch was
        # fetched, not where the underlying iterator has raced ahead to.
        # COST CONTRACT: this calls the wrapped iterator's serialize()
        # once per batch drawn, so it must be O(1) (SerialIterator's is);
        # pass snapshot_states=False for iterators with an expensive
        # serialize() — checkpointing through the prefetcher is then
        # disabled rather than silently wrong (a naive passthrough would
        # serialize the raced-ahead position and drop buffered batches
        # at resume).
        self._states: collections.deque = collections.deque()
        self._can_serialize = snapshot_states and hasattr(it, "serialize")
        self._done = False

    def _top_up(self) -> None:
        while len(self._buf) < self._depth and not self._done:
            state = self._it.serialize() if self._can_serialize else None
            try:
                host = next(self._it)
            except StopIteration:
                self._done = True
                return
            # async dispatch: returns a jax.Array immediately, the copy
            # proceeds while the caller's current step computes
            self._buf.append(self._place(host))
            self._states.append(state)

    def __iter__(self):
        return self

    def __next__(self):
        self._top_up()
        if not self._buf:
            raise StopIteration
        out = self._buf.popleft()
        self._states.popleft()
        # queue the replacement transfer NOW, behind the step the caller
        # is about to dispatch with `out`
        self._top_up()
        return out

    next = __next__

    # serialize/restore are exposed via __getattr__ (not class methods)
    # so hasattr() feature detection keeps working: a wrapped iterator
    # without serialize() must leave the prefetcher without one too
    # (Trainer.state_dict treats that as "no iterator state", a
    # graceful no-op).  When the underlying iterator HAS them, ours win
    # — the naive passthrough would serialize the raced-ahead position
    # and silently drop the buffered batches at resume.
    def _serialize(self):
        if self._states:
            return self._states[0]
        return self._it.serialize()

    def _restore(self, state):
        self._it.restore(state)
        self._buf.clear()
        self._states.clear()
        self._done = False

    def __getattr__(self, name):
        it = self.__dict__.get("_it")
        if it is None:  # mid-construction / unpickling
            raise AttributeError(name)
        if name == "serialize":
            if self.__dict__.get("_can_serialize"):
                return self._serialize
            # NEVER fall through to the wrapped iterator's serialize:
            # with snapshotting disabled it would record the raced-ahead
            # position and silently drop buffered batches at resume.
            raise AttributeError(name)
        if name == "restore" and hasattr(it, "restore"):
            return self._restore
        # bookkeeping passthrough (epoch, batches_per_epoch, ...);
        # raises AttributeError naturally for absent names
        return getattr(it, name)


def prefetch_to_device(iterator: Iterator, place_fn: Callable,
                       depth: int = 2,
                       snapshot_states: bool = True) -> Iterator:
    """Wrap ``iterator`` so ``depth`` placed batches are always in
    flight.  ``place_fn`` maps one host batch to device array(s) —
    usually ``step.place_batch`` (which shards over the data mesh) or a
    ``functools.partial(jax.device_put, device=...)``.

    ``depth=2`` is classic double-buffering: one batch being consumed
    by the running step, one transferring behind it.  Larger depths only
    help when transfer time exceeds a whole step.

    The wrapped iterator must yield host data whose buffers remain valid
    until ``place_fn`` returns (``place_fn`` hands the bytes to the
    runtime); zero-copy loader views should be copied or cast (e.g. the
    bf16 host cast) before being yielded.

    ``snapshot_states``: when the wrapped iterator has ``serialize()``,
    it is called once per batch drawn so a checkpoint resumes at the
    oldest *unconsumed* batch — that call must be O(1) (SerialIterator's
    is).  Pass ``False`` for third-party iterators whose serialize is
    O(dataset): per-batch snapshotting stops, and the prefetcher exposes
    no ``serialize()`` at all (Trainer then records no iterator state)
    instead of silently recording the raced-ahead position.
    """
    return _DevicePrefetcher(iter(iterator), place_fn, depth,
                             snapshot_states=snapshot_states)

from .losses import softmax_cross_entropy, accuracy  # noqa: F401
from .attention import multi_head_attention  # noqa: F401
from .chunked_ce import (  # noqa: F401
    chunked_lm_loss,
    chunked_softmax_cross_entropy,
)

__all__ = ["softmax_cross_entropy", "accuracy", "multi_head_attention",
           "chunked_softmax_cross_entropy", "chunked_lm_loss",
           "flash_attention", "flash_attention_with_lse",
           "flash_attention_fn", "fused_cast_scale", "block_census",
           "flash_decode", "paged_decode_reference"]


def __getattr__(name):
    # Pallas kernels load lazily (experimental namespace).
    if name in ("flash_attention", "flash_attention_with_lse",
                "flash_attention_fn", "fused_cast_scale",
                "block_census", "flash_decode",
                "paged_decode_reference"):
        from . import pallas_attention

        return getattr(pallas_attention, name)
    raise AttributeError(name)

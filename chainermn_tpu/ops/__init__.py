from .losses import softmax_cross_entropy, accuracy  # noqa: F401
from .attention import multi_head_attention  # noqa: F401

__all__ = ["softmax_cross_entropy", "accuracy", "multi_head_attention"]

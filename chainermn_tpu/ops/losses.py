"""Loss / metric ops.

Small fused building blocks used by the examples and benches.  TPU notes:
logits enter in bf16 but the log-sum-exp accumulates in fp32 (bf16's 8-bit
exponent survives exp, but the 7-bit mantissa loses the softmax tail);
XLA fuses the whole loss into the preceding matmul's epilogue.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray,
                          *, z_loss: float = 0.0) -> jnp.ndarray:
    """Mean cross-entropy for integer labels, fp32 accumulation.

    ``z_loss`` adds the PaLM-style log-normalizer penalty
    (z_loss * logZ^2), which keeps logits from drifting at large scale.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(
        logits, labels[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    loss = logz - ll
    if z_loss:
        loss = loss + z_loss * jnp.square(logz)
    return loss.mean()


def accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    return (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32).mean()

"""Pallas TPU kernels: flash attention and fused cast/scale.

SURVEY.md section 2, native-code obligations: the reference's only
embedded device kernels are the fp16 cast/scale ElementwiseKernels inside
PureNcclCommunicator (#11) and the pack/unpack copy loops (#15).  The TPU
rebuild's counterparts are (a) :func:`fused_cast_scale` — one pass over a
gradient buffer instead of separate cast and divide ops — and (b)
:func:`flash_attention` — a blocked online-softmax attention kernel whose
K/V residency is one (block_k, d) tile per grid step (the S x S score
matrix never exists in HBM; MXU matmuls, fp32 accumulation).
``ulysses_attention`` accepts it through its ``attention_fn`` hook
(``ring_attention`` has its own online-merge core and takes no hook).

Kernels run compiled on TPU and fall back to interpret mode elsewhere
(tests exercise them on CPU via ``interpret=True``).  The backward pass is
a *blocked recompute* in plain JAX — chunked over queries (for dq) and
keys (for dk/dv) with ``lax.map``, so training memory stays O(s * chunk),
not O(s^2); XLA fuses each chunk's matmuls on its own.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

try:  # pallas is an experimental namespace; degrade gracefully
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    PALLAS_AVAILABLE = True
except ImportError:  # pragma: no cover
    PALLAS_AVAILABLE = False

_NEG_INF = -1e30


def _should_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ----------------------------------------------------------------------
# Flash attention — forward kernel
# ----------------------------------------------------------------------
def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                      *, s_k: int, causal: bool, scale: float,
                      block_q: int, block_k: int):
    """Grid (batch*head, q_blocks, k_blocks); the k dimension is innermost
    and sequential on TPU, so the fp32 accumulator / running max /
    denominator live in VMEM scratch across k steps.  K/V residency is one
    (block_k, d) tile per step."""
    j = pl.program_id(1)
    kb = pl.program_id(2)
    n_kb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: a k block strictly above the diagonal contributes nothing —
    # skip its matmuls entirely (static predicate per (j, kb) pair).
    first_q = j * block_q
    first_k = kb * block_k
    live = (first_k <= first_q + block_q - 1) if causal else True

    @pl.when(live)
    def _attend():
        q = q_ref[0].astype(jnp.float32) * scale  # (bq, d)
        k_blk = k_ref[0].astype(jnp.float32)      # (bk, d)
        v_blk = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bq, bk)
        q_idx = first_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_idx = first_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_idx < s_k  # padded keys never contribute
        if causal:
            mask = mask & (k_idx <= q_idx)
        s = jnp.where(mask, s, _NEG_INF)

        m_old = m_ref[:, 0:1]
        m_new = jnp.maximum(m_old, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_old - m_new)
        l_new = alpha * l_ref[:, 0:1] + jnp.sum(p, axis=-1, keepdims=True)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        acc_ref[:] = alpha * acc_ref[:] + lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kb == n_kb - 1)
    def _finalize():
        # Fully-masked rows (query padding) have l == 0.
        o_ref[0] = (
            acc_ref[:] / jnp.maximum(l_ref[:, 0:1], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "interpret"),
)
def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    bq = min(block_q, _round_up(s_q, 8))
    bk = min(block_k, _round_up(s_k, 8))

    def to_bh(x, s, blk):
        # (b, s, h, d) -> (b*h, s_padded_to_blk, d)
        x = jnp.moveaxis(x, 2, 1).reshape(b * h, s, d)
        pad = _round_up(s, blk) - s
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        return x

    qb = to_bh(q, s_q, bq)
    kb_, vb = to_bh(k, s_k, bk), to_bh(v, s_k, bk)
    s_qp, s_kp = qb.shape[1], kb_.shape[1]

    grid = (b * h, s_qp // bq, s_kp // bk)
    out = pl.pallas_call(
        functools.partial(
            _flash_fwd_kernel, s_k=s_k, causal=causal, scale=scale,
            block_q=bq, block_k=bk,
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, s_qp, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, kb: (i, kb, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, kb: (i, kb, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0)),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),    # acc
            pltpu.VMEM((bq, 128), jnp.float32),  # running max (col 0)
            pltpu.VMEM((bq, 128), jnp.float32),  # running denom (col 0)
        ],
        interpret=interpret,
    )(qb, kb_, vb)
    out = out[:, :s_q].reshape(b, h, s_q, d)
    return jnp.moveaxis(out, 1, 2)  # (b, s, h, d)


# ----------------------------------------------------------------------
# Flash attention — blocked recompute backward (plain JAX, O(s * chunk))
# ----------------------------------------------------------------------
def _chunked(x, chunk, axis=1):
    """Pad axis to a chunk multiple and reshape into (n_chunks, chunk)."""
    s = x.shape[axis]
    pad = _round_up(s, chunk) - s
    if pad:
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, pad)
        x = jnp.pad(x, widths)
    new_shape = (
        x.shape[:axis] + (x.shape[axis] // chunk, chunk)
        + x.shape[axis + 1:]
    )
    return x.reshape(new_shape)


def _blocked_attention_grads(q, k, v, o, do, causal, scale, chunk):
    """dq, dk, dv without materializing the (s_q, s_k) score matrix.

    All inputs (bh, s, d) fp32.  Two passes of ``lax.map`` over chunks:
    queries for dq (scores are (chunk, s_k) — linear in s), keys for
    dk/dv (scores are (s_q, chunk)).  The softmax statistics (lse) are
    recomputed in the first pass and reused in the second.
    """
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    D = jnp.sum(do * o, axis=-1)  # (bh, s_q)

    q_pos = jnp.arange(s_q)
    k_pos = jnp.arange(s_k)

    def mask_bias(qi, kj):
        m = jnp.ones((qi.shape[0], kj.shape[0]), bool)
        if causal:
            m = qi[:, None] >= kj[None, :]
        return jnp.where(m, 0.0, _NEG_INF)

    # -- pass 1: dq and lse, chunked over queries ----------------------
    qc = _chunked(q, chunk)            # (bh, nq, c, d)
    doc = _chunked(do, chunk)
    Dc = _chunked(D, chunk)            # (bh, nq, c)
    qic = _chunked(q_pos[None], chunk, axis=1)[0]  # (nq, c)

    def one_q_chunk(args):
        qc_i, do_i, D_i, qi = args  # (bh, c, d), (bh, c, d), (bh, c), (c,)
        s = jnp.einsum("bcd,bkd->bck", qc_i, k) * scale
        s = s + mask_bias(qi, k_pos)[None]
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]  # (bh, c)
        p = p / jnp.maximum(l, 1e-30)
        dp = jnp.einsum("bcd,bkd->bck", do_i, v)
        ds = p * (dp - D_i[..., None])
        dq_i = jnp.einsum("bck,bkd->bcd", ds, k) * scale
        return dq_i, lse

    dq_c, lse_c = lax.map(
        one_q_chunk,
        (jnp.moveaxis(qc, 1, 0), jnp.moveaxis(doc, 1, 0),
         jnp.moveaxis(Dc, 1, 0), qic),
    )  # (nq, bh, c, d), (nq, bh, c)
    dq = jnp.moveaxis(dq_c, 0, 1).reshape(bh, -1, d)[:, :s_q]
    lse = jnp.moveaxis(lse_c, 0, 1).reshape(bh, -1)[:, :s_q]

    # -- pass 2: dk / dv, chunked over keys ----------------------------
    kc = _chunked(k, chunk)            # (bh, nk, c, d)
    vc = _chunked(v, chunk)
    kjc = _chunked(k_pos[None], chunk, axis=1)[0]  # (nk, c)

    def one_k_chunk(args):
        k_j, v_j, kj = args  # (bh, c, d), (bh, c, d), (c,)
        s = jnp.einsum("bqd,bcd->bqc", q, k_j) * scale
        s = s + mask_bias(q_pos, kj)[None]
        p = jnp.exp(s - lse[..., None])  # normalized via saved lse
        dv_j = jnp.einsum("bqc,bqd->bcd", p, do)
        dp = jnp.einsum("bqd,bcd->bqc", do, v_j)
        ds = p * (dp - D[..., None])
        dk_j = jnp.einsum("bqc,bqd->bcd", ds, q) * scale
        return dk_j, dv_j

    dk_c, dv_c = lax.map(
        one_k_chunk,
        (jnp.moveaxis(kc, 1, 0), jnp.moveaxis(vc, 1, 0), kjc),
    )
    dk = jnp.moveaxis(dk_c, 0, 1).reshape(bh, -1, d)[:, :s_k]
    dv = jnp.moveaxis(dv_c, 0, 1).reshape(bh, -1, d)[:, :s_k]
    return dq, dk, dv


# ----------------------------------------------------------------------
# Public API
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=128, block_k=128, interpret=None):
    """Blocked flash attention: (b, s, h, d) x 3 -> (b, s, h, d).

    Numerics match :func:`chainermn_tpu.ops.multi_head_attention` (fp32
    online softmax).  ``interpret=None`` auto-selects: compiled on TPU,
    interpreter elsewhere.
    """
    if not PALLAS_AVAILABLE:
        raise ImportError(
            "flash_attention requires jax.experimental.pallas; use "
            "chainermn_tpu.ops.multi_head_attention on this JAX build"
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          _should_interpret(interpret))


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, scale, block_q, block_k,
                          interpret)
    return out, (q, k, v, out)


def _flash_bwd_rule(causal, scale, block_q, block_k, interpret,
                    residuals, g):
    q, k, v, out = residuals
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b, s_q, h, d = q.shape

    def to_bh(x):
        return jnp.moveaxis(x, 2, 1).reshape(
            b * h, x.shape[1], d
        ).astype(jnp.float32)

    chunk = max(block_q, 128)
    dq, dk, dv = _blocked_attention_grads(
        to_bh(q), to_bh(k), to_bh(v), to_bh(out), to_bh(g),
        causal, scale, chunk,
    )

    def from_bh(x, s, dt):
        return jnp.moveaxis(x.reshape(b, h, s, d), 1, 2).astype(dt)

    return (from_bh(dq, s_q, q.dtype), from_bh(dk, k.shape[1], k.dtype),
            from_bh(dv, v.shape[1], v.dtype))


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_fn(block_q: int = 128, block_k: int = 128,
                       interpret: Optional[bool] = None):
    """Adapter producing the ``attention_fn`` signature used by
    ``ulysses_attention``: ``(q, k, v, causal, scale)``."""

    def fn(q, k, v, causal, scale):
        return flash_attention(q, k, v, causal, scale, block_q, block_k,
                               interpret)

    return fn


# ----------------------------------------------------------------------
# Fused cast + scale (the reference's PureNccl fp16 kernels, #11)
# ----------------------------------------------------------------------
def _cast_scale_kernel(x_ref, o_ref, *, scale: float):
    o_ref[:] = (x_ref[:].astype(jnp.float32) * scale).astype(o_ref.dtype)


def fused_cast_scale(x: jnp.ndarray, scale: float, dtype,
                     interpret: Optional[bool] = None) -> jnp.ndarray:
    """``(x * scale).astype(dtype)`` in one VMEM pass.

    Parity: the cast-and-scale ElementwiseKernels PureNcclCommunicator
    launches around its fp16 allreduce (divide-by-size fused with the
    cast-back).  Any shape; internally flattened to lane-aligned tiles.
    """
    if not PALLAS_AVAILABLE or x.size == 0:
        return (x.astype(jnp.float32) * scale).astype(dtype)
    shape = x.shape
    flat = x.reshape(-1)
    n = flat.shape[0]
    lane = 128
    rows = _round_up((n + lane - 1) // lane, 8)
    pad = rows * lane - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    tiled = flat.reshape(rows, lane)
    block_rows = min(rows, 512)
    rows_p = _round_up(rows, block_rows)
    if rows_p != rows:
        tiled = jnp.pad(tiled, ((0, rows_p - rows), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_cast_scale_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((rows_p, lane), jnp.dtype(dtype)),
        grid=(rows_p // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, lane), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, lane), lambda i: (i, 0)),
        interpret=_should_interpret(interpret),
    )(tiled)
    return out.reshape(-1)[:n].reshape(shape)

"""Pallas TPU kernels: flash attention and fused cast/scale.

SURVEY.md section 2, native-code obligations: the reference's only
embedded device kernels are the fp16 cast/scale ElementwiseKernels inside
PureNcclCommunicator (#11) and the pack/unpack copy loops (#15).  The TPU
rebuild's counterparts are (a) :func:`fused_cast_scale` — one pass over a
gradient buffer instead of separate cast and divide ops — and (b)
:func:`flash_attention` — a blocked online-softmax attention kernel whose
K/V residency is one (block_k, d) tile per grid step (the S x S score
matrix never exists in HBM; MXU matmuls, fp32 accumulation).
``ulysses_attention`` accepts it through its ``attention_fn`` hook
(``ring_attention`` has its own online-merge core and takes no hook).

Kernels run compiled on TPU and fall back to interpret mode elsewhere
(tests exercise them on CPU via ``interpret=True``).  The backward pass
is a pair of Pallas kernels in the FlashAttention-2 shape: the forward
saves the per-row log-sum-exp, the dq kernel sweeps key blocks, the
dk/dv kernel sweeps query blocks, each recomputing its score tile in
VMEM — training memory stays O(s), never O(s^2), and causally-dead
blocks are skipped entirely.  Tiny compiled shapes (< one 128 lane tile)
take a dense-recompute fallback instead.

All three kernels are DIAGONAL-SPLIT (round 6): each (q block, k block)
grid point is classified dead / interior / masked, and interior blocks
(the fully-unmasked majority at long sequence) run a fast branch with
no iota/mask/select work — see the "Block taxonomy" section below and
docs/performance.md "Diagonal-split kernel".  The pre-split kernels
are kept under ``taxonomy="legacy"`` as the bit-exact reference.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

try:  # pallas is an experimental namespace; degrade gracefully
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    PALLAS_AVAILABLE = True
except ImportError:  # pragma: no cover
    PALLAS_AVAILABLE = False

_NEG_INF = -1e30


def _should_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _effective_q_block(block_q: int, s_q: int, interpret: bool) -> int:
    """Clamp the q block for the lse layout: its blocks put bq in the
    lane position, which compiled TPU requires to be a multiple of 128
    OR the full (padded) axis — so for long sequences the q block floors
    at 128 regardless of the requested size."""
    bq = min(block_q, _round_up(s_q, 8))
    if not interpret and _round_up(s_q, 8) >= 128:
        bq = max(bq, 128)
    return bq


# Default block geometry (round-4 sweep, benchmarks/longseq_tune.py).
# Public entry points take block_q/block_k=None so "caller passed
# nothing" is distinguishable from "caller asked for exactly 1024".
_DEFAULT_BLOCK = 1024
_warned_geometries: set = set()


def _clamp_blocks_for_dim(block_q, block_k, d: int, warn: bool = True,
                          _context: str = "fwd"):
    """Head-dim-aware block clamp (``None`` block = the default).  The
    backward kernel holds three (bq, bk) fp32 score tiles plus
    d-proportional operand/accumulator tiles in scoped VMEM (16 MB hard
    limit; 1024x2048 at d=128 already exceeds it — measured,
    benchmarks/longseq_tune.py).

    Threshold history: rounds 1-4 clamped every d > 128 on an
    extrapolated VMEM model; the round-5 probe COMPILED AND RAN the
    full 1024x1024 geometry (fwd and bwd) at d=192 and d=256 on v5e,
    so the measured feasibility boundary is d <= 256 and the clamp now
    engages only beyond it (ceil(d/256) shrink — still extrapolated
    out there, stated honestly).

    Explicitly requested blocks that get shrunk emit a ``UserWarning``
    (once per geometry, forward pass only — ``warn=False`` in the
    backward avoids a fwd+bwd double fire) so a tuning sweep at large
    d can see its requested geometry was overridden rather than
    silently measuring the clamp.  Defaults clamp silently."""
    explicit = block_q is not None or block_k is not None
    block_q = _DEFAULT_BLOCK if block_q is None else block_q
    block_k = _DEFAULT_BLOCK if block_k is None else block_k
    if d > 256:
        shrink = -(-d // 256)  # ceil: 384 -> /2, 512 -> /2, 1024 -> /4

        def down(b):
            return max(b // shrink // 128 * 128, 256)

        new_q, new_k = down(block_q), down(block_k)
        if warn and explicit and (new_q, new_k) != (block_q, block_k):
            # key includes the caller context: a bwd-override warning
            # must not suppress a later forward warning for the same
            # geometry (each names a different knob to fix)
            key = (_context, block_q, block_k, d)
            if key not in _warned_geometries:
                _warned_geometries.add(key)
                import warnings

                warnings.warn(
                    f"flash_attention: requested blocks "
                    f"{block_q}x{block_k} clamped to {new_q}x{new_k} "
                    f"for head dim {d} (VMEM budget extrapolated from "
                    "dh<=256 measurements; pass blocks that fit to "
                    "silence)"
                )
        block_q, block_k = new_q, new_k
    return block_q, block_k


# ----------------------------------------------------------------------
# Block taxonomy (the diagonal split)
# ----------------------------------------------------------------------
# Every (q block, k block) grid point falls into exactly one class:
#
#   dead      strictly above the causal diagonal — contributes nothing;
#             skipped entirely (no matmul, no softmax) since round 1.
#   interior  fully unmasked: every (q, k) pair in the block is causally
#             live and unpadded.  The fast branch — no iota, no mask
#             compare, no select; and at the FIRST k step (where the
#             running max is provably monotone because the running
#             state is empty) no rescale of the accumulator either.
#   masked    the diagonal-straddling blocks plus the ragged-tail
#             blocks (k or q padding) — the only blocks that pay the
#             masked online-softmax path.  Per q row this is ~1/q_blocks
#             of the live work at square geometry.
#
# ``taxonomy`` selects the kernel family:
#   "split"    (default) classify at run time, route interior blocks
#              down the fast branch — numerically EXACT vs "legacy"
#              (the mask it skips is provably all-true there).
#   "legacy"   the pre-split kernels, kept verbatim as the in-tree
#              reference: every live block runs the masked path.
#   "interior" TIMING ONLY: force every live block down the unmasked
#              fast branch.  Numerics are intentionally wrong for
#              causal/ragged inputs — this is the segment-anatomy
#              bench's per-block-type floor, never a training path.
_TAXONOMIES = ("split", "legacy", "interior")


def _resolve_taxonomy(taxonomy):
    t = "split" if taxonomy is None else taxonomy
    if t not in _TAXONOMIES:
        raise ValueError(
            f"taxonomy must be one of {_TAXONOMIES} (or None), got "
            f"{taxonomy!r}"
        )
    return t


def _when(pred):
    """``pl.when`` that folds statically-known predicates: a Python
    ``True`` emits the body unconditionally, ``False`` emits nothing
    (e.g. the masked branch of a non-causal, non-ragged launch)."""
    if isinstance(pred, bool):
        if pred:
            return lambda f: f()
        return lambda f: None
    return pl.when(pred)


def _and(a, b):
    if isinstance(a, bool):
        return b if a else False
    if isinstance(b, bool):
        return a if b else False
    return jnp.logical_and(a, b)


def _not(a):
    return (not a) if isinstance(a, bool) else jnp.logical_not(a)


def _block_class(first_q, first_k, *, s_k, s_kp, causal, block_q, block_k,
                 force_interior=False, s_q=None, s_qp=None):
    """THE taxonomy predicate: (interior, masked) for one block.

    The single source of truth for block classification — the split
    kernels evaluate it on traced program ids, :func:`block_census`
    on Python ints (``_and``/``_not`` fold either way), so the census
    cannot drift from what the kernels execute.

    The forward leaves ``s_q``/``s_qp`` unset: it never masks q
    (padded q rows are garbage the caller slices off — same contract
    as legacy).  The backward kernels pass them, so a ragged q tail
    reclassifies its whole block row as masked (its recomputed p would
    otherwise contribute to dk/dv and its garbage lse to dq).  Each
    tail predicate is emitted only when the corresponding padding
    exists (static), so an aligned launch never compares indices."""
    live = (first_k <= first_q + block_q - 1) if causal else True
    needs_mask = (first_k + block_k - 1 > first_q) if causal else False
    if s_k < s_kp:
        needs_mask = needs_mask | (first_k + block_k > s_k)
    if s_q is not None and s_q < s_qp:
        needs_mask = needs_mask | (first_q + block_q > s_q)
    if force_interior:
        return live, False
    return _and(live, _not(needs_mask)), _and(live, needs_mask)


def block_census(s_q: int, s_k: int, block_q: int, block_k: int,
                 causal: bool, kind: str = "fwd") -> dict:
    """Static census of the block taxonomy for one (batch*head) program
    — the analytic side of the segment-anatomy bench (how many blocks
    of each class a launch executes, so A/B step times divide into
    per-block-type costs).

    ``kind``: the forward kernel masks only the k axis (padded q rows
    are garbage that gets sliced off), the backward kernels mask q too
    (padded q rows would otherwise contribute to dk/dv) — so a ragged
    q tail reclassifies its row of blocks only for ``kind="bwd"``.
    Mirrors the kernels' run-time predicates exactly
    (``test_block_census_matches_brute_force``)."""
    if kind not in ("fwd", "bwd"):
        raise ValueError(f"kind must be fwd/bwd, got {kind!r}")
    s_qp, s_kp = _round_up(s_q, block_q), _round_up(s_k, block_k)
    n_q, n_k = s_qp // block_q, s_kp // block_k
    census = {"dead": 0, "interior": 0, "masked": 0,
              "n_q_blocks": n_q, "n_k_blocks": n_k}
    for j in range(n_q):
        for kb in range(n_k):
            interior, masked = _block_class(
                j * block_q, kb * block_k, s_k=s_k, s_kp=s_kp,
                causal=causal, block_q=block_q, block_k=block_k,
                s_q=s_q if kind == "bwd" else None, s_qp=s_qp,
            )
            key = "masked" if masked else (
                "interior" if interior else "dead")
            census[key] += 1
    return census


def launch_census(s_q: int, s_k: int, d: int, block_q=None, block_k=None,
                  bwd_block_q=None, bwd_block_k=None,
                  causal: bool = True, interpret: bool = False) -> dict:
    """Census of the geometry a launch will ACTUALLY run: resolves
    ``None`` blocks to the defaults, then applies every clamp the entry
    points apply — the head-dim clamp (:func:`_clamp_blocks_for_dim`),
    the q-block lane-tile floor (:func:`_effective_q_block`; compiled
    TPU floors bq at 128), and the k sequence clamp — and returns
    ``{"fwd": census, "bwd": census}``.  The bench anatomy rungs use
    this instead of calling :func:`block_census` on the *requested*
    blocks, so a clamped launch cannot print a census for a geometry
    it never ran.

    Two run-time escapes are NOT reflected (they depend on the backend,
    not the geometry): the backward's scoped-VMEM retry can ceil-shrink
    its blocks further on generations where the d-clamp is too loose
    (``_backward_with_vmem_retry`` warns when it does — a capture that
    saw that warning must not divide by this census), and sequences
    below one lane tile take the dense-recompute fallback with no
    blocks at all."""
    fbq, fbk = _clamp_blocks_for_dim(block_q, block_k, d, warn=False)
    bq = block_q if bwd_block_q is None else bwd_block_q
    bk = block_k if bwd_block_k is None else bwd_block_k
    bbq, bbk = _clamp_blocks_for_dim(bq, bk, d, warn=False)

    def eff(b_q, b_k):
        # exactly _flash_forward/_flash_backward's block resolution
        return (_effective_q_block(b_q, s_q, interpret),
                min(b_k, _round_up(s_k, 8)))

    return {
        "fwd": block_census(s_q, s_k, *eff(fbq, fbk), causal, "fwd"),
        "bwd": block_census(s_q, s_k, *eff(bbq, bbk), causal, "bwd"),
    }


# ----------------------------------------------------------------------
# Flash attention — forward kernel
# ----------------------------------------------------------------------
def _flash_fwd_kernel_legacy(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref,
                             m_ref, l_ref, *, s_k: int, causal: bool,
                             scale: float, block_q: int, block_k: int):
    """The PRE-SPLIT forward kernel, kept verbatim (``taxonomy="legacy"``)
    as the numerics/timing reference for the diagonal split: every live
    block pays the iota/mask/select online-softmax path.

    Grid (batch*head, q_blocks, k_blocks); the k dimension is innermost
    and sequential on TPU, so the fp32 accumulator / running max /
    denominator live in VMEM scratch across k steps.  K/V residency is one
    (block_k, d) tile per step."""
    j = pl.program_id(1)
    kb = pl.program_id(2)
    n_kb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: a k block strictly above the diagonal contributes nothing —
    # skip its matmuls entirely (static predicate per (j, kb) pair).
    first_q = j * block_q
    first_k = kb * block_k
    live = (first_k <= first_q + block_q - 1) if causal else True

    @pl.when(live)
    def _attend():
        q = q_ref[0].astype(jnp.float32) * scale  # (bq, d)
        k_blk = k_ref[0].astype(jnp.float32)      # (bk, d)
        v_blk = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bq, bk)
        q_idx = first_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_idx = first_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_idx < s_k  # padded keys never contribute
        if causal:
            mask = mask & (k_idx <= q_idx)
        s = jnp.where(mask, s, _NEG_INF)

        m_old = m_ref[:, 0:1]
        m_new = jnp.maximum(m_old, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_old - m_new)
        l_new = alpha * l_ref[:, 0:1] + jnp.sum(p, axis=-1, keepdims=True)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        acc_ref[:] = alpha * acc_ref[:] + lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kb == n_kb - 1)
    def _finalize():
        # Fully-masked rows (query padding) have l == 0.
        o_ref[0] = (
            acc_ref[:] / jnp.maximum(l_ref[:, 0:1], 1e-30)
        ).astype(o_ref.dtype)
        # Per-row log-sum-exp of the (scaled) scores — the backward's
        # softmax statistic.  Stored broadcast over 8 sublanes because a
        # TPU block's second-to-last dim must be a multiple of 8.
        # Garbage on padded rows; the backward masks those by q index.
        lse_ref[0] = jnp.broadcast_to(
            (m_ref[:, 0] + jnp.log(jnp.maximum(l_ref[:, 0], 1e-30)))[
                None, :
            ],
            lse_ref.shape[1:],
        )


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                      l_ref, *, s_k: int, s_kp: int, causal: bool,
                      scale: float, block_q: int, block_k: int,
                      force_interior: bool = False):
    """Diagonal-split forward kernel (``taxonomy="split"``).

    Same grid/scratch contract as the legacy kernel; each (j, kb) grid
    point routes to one of the taxonomy branches (see module section
    "Block taxonomy").  The interior branch carries no iota/mask/select,
    and the first k step (kb == 0, always live) writes the running
    state directly instead of rescaling an empty accumulator — with
    m_old = -inf the rescale factor exp(m_old - m_new) is exactly 0 in
    fp32, so skipping it is bit-identical, and it removes the separate
    init pass plus one (bq, d) multiply-add per q row.

    Exactness vs legacy: on an interior block the legacy mask is
    provably all-true, so ``where(mask, s, -inf)`` is the identity and
    both branches compute the same fp32 expression tree
    (``test_split_matches_legacy_exactly``)."""
    j = pl.program_id(1)
    kb = pl.program_id(2)
    n_kb = pl.num_programs(2)

    first_q = j * block_q
    first_k = kb * block_k
    interior, masked = _block_class(
        first_q, first_k, s_k=s_k, s_kp=s_kp, causal=causal,
        block_q=block_q, block_k=block_k,
        force_interior=force_interior,
    )

    def _attend(with_mask):
        q = q_ref[0].astype(jnp.float32) * scale  # (bq, d)
        k_blk = k_ref[0].astype(jnp.float32)      # (bk, d)
        v_blk = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bq, bk)
        if with_mask:
            mask = _tail_mask(
                first_q, first_k, s_k=s_k, s_kp=s_kp, causal=causal,
                block_q=block_q, block_k=block_k,
            )
            s = jnp.where(mask, s, _NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)

        @pl.when(kb == 0)
        def _first():
            # First k block (always live): the running state is empty,
            # so the online-softmax rescale is provably a no-op — write
            # the block statistics directly.
            p = jnp.exp(s - m_blk)
            m_ref[:] = jnp.broadcast_to(m_blk, m_ref.shape)
            l_ref[:] = jnp.broadcast_to(
                jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
            )
            acc_ref[:] = lax.dot_general(
                p, v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        @pl.when(kb != 0)
        def _rest():
            m_old = m_ref[:, 0:1]
            m_new = jnp.maximum(m_old, m_blk)
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_old - m_new)
            l_new = alpha * l_ref[:, 0:1] + jnp.sum(
                p, axis=-1, keepdims=True
            )
            l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
            m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
            acc_ref[:] = alpha * acc_ref[:] + lax.dot_general(
                p, v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    @_when(interior)
    def _fast():
        _attend(with_mask=False)

    @_when(masked)
    def _slow():
        _attend(with_mask=True)

    @pl.when(kb == n_kb - 1)
    def _finalize():
        o_ref[0] = (
            acc_ref[:] / jnp.maximum(l_ref[:, 0:1], 1e-30)
        ).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(
            (m_ref[:, 0] + jnp.log(jnp.maximum(l_ref[:, 0], 1e-30)))[
                None, :
            ],
            lse_ref.shape[1:],
        )


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "interpret",
                     "taxonomy"),
)
def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret,
                   taxonomy="split"):
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    block_q, block_k = _clamp_blocks_for_dim(block_q, block_k, d)
    bq = _effective_q_block(block_q, s_q, interpret)
    bk = min(block_k, _round_up(s_k, 8))

    def to_bh(x, s, blk):
        # (b, s, h, d) -> (b*h, s_padded_to_blk, d) [fwd]
        x = jnp.moveaxis(x, 2, 1).reshape(b * h, s, d)
        pad = _round_up(s, blk) - s
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        return x

    qb = to_bh(q, s_q, bq)
    kb_, vb = to_bh(k, s_k, bk), to_bh(v, s_k, bk)
    s_qp, s_kp = qb.shape[1], kb_.shape[1]

    if taxonomy == "legacy":
        kernel = functools.partial(
            _flash_fwd_kernel_legacy, s_k=s_k, causal=causal,
            scale=scale, block_q=bq, block_k=bk,
        )
    else:
        kernel = functools.partial(
            _flash_fwd_kernel, s_k=s_k, s_kp=s_kp, causal=causal,
            scale=scale, block_q=bq, block_k=bk,
            force_interior=(taxonomy == "interior"),
        )
    grid = (b * h, s_qp // bq, s_kp // bk)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s_qp, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 8, s_qp), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, kb: (i, kb, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, kb: (i, kb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, 8, bq), lambda i, j, kb: (i, 0, j)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),    # acc
            pltpu.VMEM((bq, 128), jnp.float32),  # running max (col 0)
            pltpu.VMEM((bq, 128), jnp.float32),  # running denom (col 0)
        ],
        interpret=interpret,
    )(qb, kb_, vb)
    out = out[:, :s_q].reshape(b, h, s_q, d)
    return jnp.moveaxis(out, 1, 2), lse[:, 0, :s_q]  # (b,s,h,d), (bh,s_q)


# ----------------------------------------------------------------------
# Flash attention — backward kernels (FlashAttention-2 shape)
# ----------------------------------------------------------------------
def _flash_bwd_dq_kernel_legacy(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                delta_ref, dq_ref, dq_acc, *, s_q: int,
                                s_k: int, causal: bool, scale: float,
                                block_q: int, block_k: int):
    """Pre-split dq kernel (``taxonomy="legacy"`` reference).

    Grid (batch*head, q_blocks, k_blocks); k innermost/sequential.
    Recomputes the (bq, bk) probability tile from q, k and the saved
    row log-sum-exp, accumulates dq in VMEM."""
    j = pl.program_id(1)
    kb = pl.program_id(2)
    n_kb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    first_q = j * block_q
    first_k = kb * block_k
    live = (first_k <= first_q + block_q - 1) if causal else True

    @pl.when(live)
    def _accum():
        q = q_ref[0].astype(jnp.float32)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        q_idx = first_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_idx = first_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = (k_idx < s_k) & (q_idx < s_q)
        if causal:
            mask = mask & (k_idx <= q_idx)
        # p from the saved statistic; explicit zeroing (padded rows carry
        # garbage lse, so exp(s - lse) alone is not safe there)
        p = jnp.where(mask, jnp.exp(s - lse_ref[0, 0][:, None]), 0.0)
        dp = lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0, 0][:, None])
        dq_acc[:] += lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(kb == n_kb - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _tail_mask(first_q, first_k, *, s_k, s_kp, causal, block_q, block_k,
               s_q=None, s_qp=None):
    """THE masked-branch mask, statically thinned: each padding compare
    exists only when that padding exists (s < s_padded, static), so an
    aligned causal launch's diagonal blocks pay only the causal
    compare.  Dropped compares are provably all-true there, so the
    thinning is bit-identical to the legacy full mask.  Same
    ``s_q``/``s_qp`` convention as :func:`_block_class`: the forward
    leaves them unset (it never masks q), the backward passes them."""
    mask_q = s_q is not None and s_q < s_qp
    need_q = causal or mask_q
    need_k = causal or s_k < s_kp
    q_idx = first_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    ) if need_q else None
    k_idx = first_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    ) if need_k else None
    mask = True
    if s_k < s_kp:
        mask = _and(mask, k_idx < s_k)
    if mask_q:
        mask = _and(mask, q_idx < s_q)
    if causal:
        mask = _and(mask, k_idx <= q_idx)
    return mask


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_acc, *, s_q: int, s_qp: int,
                         s_k: int, s_kp: int, causal: bool, scale: float,
                         block_q: int, block_k: int,
                         force_interior: bool = False):
    """Diagonal-split dq kernel: interior blocks recompute p straight
    from the saved log-sum-exp with no iota/mask/select work; only the
    diagonal/tail blocks pay the masked path.  Same grid and numerics
    as the legacy kernel (on interior blocks the legacy mask is all-
    true, so ``where(mask, p, 0)`` is the identity)."""
    j = pl.program_id(1)
    kb = pl.program_id(2)
    n_kb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    first_q = j * block_q
    first_k = kb * block_k
    interior, masked = _block_class(
        first_q, first_k, s_q=s_q, s_qp=s_qp, s_k=s_k, s_kp=s_kp,
        causal=causal, block_q=block_q, block_k=block_k,
        force_interior=force_interior,
    )

    def _accum(with_mask):
        q = q_ref[0].astype(jnp.float32)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        p = jnp.exp(s - lse_ref[0, 0][:, None])
        if with_mask:
            mask = _tail_mask(
                first_q, first_k, s_q=s_q, s_qp=s_qp, s_k=s_k,
                s_kp=s_kp, causal=causal, block_q=block_q,
                block_k=block_k,
            )
            p = jnp.where(mask, p, 0.0)
        dp = lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0, 0][:, None])
        dq_acc[:] += lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @_when(interior)
    def _fast():
        _accum(with_mask=False)

    @_when(masked)
    def _slow():
        _accum(with_mask=True)

    @pl.when(kb == n_kb - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel_legacy(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                 delta_ref, dk_ref, dv_ref, dk_acc,
                                 dv_acc, *, s_q: int, s_k: int,
                                 causal: bool, scale: float,
                                 block_q: int, block_k: int):
    """Pre-split dk/dv kernel (``taxonomy="legacy"`` reference).

    Grid (batch*head, k_blocks, q_blocks); q innermost/sequential.
    Accumulates dk and dv for one key block across all query blocks."""
    kb = pl.program_id(1)
    j = pl.program_id(2)
    n_j = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    first_q = j * block_q
    first_k = kb * block_k
    live = (first_q + block_q - 1 >= first_k) if causal else True

    @pl.when(live)
    def _accum():
        q = q_ref[0].astype(jnp.float32)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        q_idx = first_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_idx = first_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = (k_idx < s_k) & (q_idx < s_q)
        if causal:
            mask = mask & (k_idx <= q_idx)
        p = jnp.where(mask, jnp.exp(s - lse_ref[0, 0][:, None]), 0.0)
        dv_acc[:] += lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0, 0][:, None])
        dk_acc[:] += lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(j == n_j - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, s_q: int,
                          s_qp: int, s_k: int, s_kp: int, causal: bool,
                          scale: float, block_q: int, block_k: int,
                          force_interior: bool = False):
    """Diagonal-split dk/dv kernel (grid (batch*head, k_blocks,
    q_blocks); q innermost/sequential) — same taxonomy routing as the
    split dq kernel."""
    kb = pl.program_id(1)
    j = pl.program_id(2)
    n_j = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    first_q = j * block_q
    first_k = kb * block_k
    interior, masked = _block_class(
        first_q, first_k, s_q=s_q, s_qp=s_qp, s_k=s_k, s_kp=s_kp,
        causal=causal, block_q=block_q, block_k=block_k,
        force_interior=force_interior,
    )

    def _accum(with_mask):
        q = q_ref[0].astype(jnp.float32)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        p = jnp.exp(s - lse_ref[0, 0][:, None])
        if with_mask:
            mask = _tail_mask(
                first_q, first_k, s_q=s_q, s_qp=s_qp, s_k=s_k,
                s_kp=s_kp, causal=causal, block_q=block_q,
                block_k=block_k,
            )
            p = jnp.where(mask, p, 0.0)
        dv_acc[:] += lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0, 0][:, None])
        dk_acc[:] += lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @_when(interior)
    def _fast():
        _accum(with_mask=False)

    @_when(masked)
    def _slow():
        _accum(with_mask=True)

    @pl.when(j == n_j - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "interpret",
                     "taxonomy"),
)
def _flash_backward(q, k, v, out, lse, g, causal, scale, block_q, block_k,
                    interpret, taxonomy="split", g_lse=None):
    """(b, s, h, d)-layout backward via the two kernels above.

    ``g_lse``: optional (b*h, s_q) cotangent of the log-sum-exp output
    (for :func:`flash_attention_with_lse`).  Since
    ``d lse_i / d s_ij = p_ij``, the lse cotangent enters the score
    gradient as ``ds += p * g_lse`` — algebraically identical to
    replacing ``delta`` with ``delta - g_lse``, so the kernels are
    reused unchanged."""
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    block_q, block_k = _clamp_blocks_for_dim(block_q, block_k, d,
                                             warn=False)
    bq = _effective_q_block(block_q, s_q, interpret)
    bk = min(block_k, _round_up(s_k, 8))

    def to_bh(x, s, blk):
        x = jnp.moveaxis(x, 2, 1).reshape(b * h, s, d)
        pad = _round_up(s, blk) - s
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        return x

    qb = to_bh(q, s_q, bq)
    dob = to_bh(g, s_q, bq)
    ob = to_bh(out, s_q, bq)
    kb_, vb = to_bh(k, s_k, bk), to_bh(v, s_k, bk)
    s_qp, s_kp = qb.shape[1], kb_.shape[1]

    delta = jnp.sum(
        dob.astype(jnp.float32) * ob.astype(jnp.float32), axis=-1
    )  # (bh, s_qp)
    if g_lse is not None:
        pad_d = s_qp - s_q
        gl = jnp.pad(g_lse, ((0, 0), (0, pad_d))) if pad_d else g_lse
        delta = delta - gl.astype(jnp.float32)
    pad_q = s_qp - s_q
    lse_p = jnp.pad(lse, ((0, 0), (0, pad_q))) if pad_q else lse
    # 8-sublane broadcast layout (TPU blocks need sublane-dim % 8 == 0)
    bh = b * h
    delta = jnp.broadcast_to(delta[:, None], (bh, 8, s_qp))
    lse_p = jnp.broadcast_to(lse_p[:, None], (bh, 8, s_qp))

    n_q, n_k = s_qp // bq, s_kp // bk
    if taxonomy == "legacy":
        dq_kernel, dkv_kernel = (_flash_bwd_dq_kernel_legacy,
                                 _flash_bwd_dkv_kernel_legacy)
        kwargs = dict(s_q=s_q, s_k=s_k, causal=causal, scale=scale,
                      block_q=bq, block_k=bk)
    else:
        dq_kernel, dkv_kernel = _flash_bwd_dq_kernel, _flash_bwd_dkv_kernel
        kwargs = dict(s_q=s_q, s_qp=s_qp, s_k=s_k, s_kp=s_kp,
                      causal=causal, scale=scale, block_q=bq, block_k=bk,
                      force_interior=(taxonomy == "interior"))

    dq = pl.pallas_call(
        functools.partial(dq_kernel, **kwargs),
        out_shape=jax.ShapeDtypeStruct((b * h, s_qp, d), q.dtype),
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0)),   # q
            pl.BlockSpec((1, bk, d), lambda i, j, kb: (i, kb, 0)),  # k
            pl.BlockSpec((1, bk, d), lambda i, j, kb: (i, kb, 0)),  # v
            pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0)),   # do
            pl.BlockSpec((1, 8, bq), lambda i, j, kb: (i, 0, j)),   # lse
            pl.BlockSpec((1, 8, bq), lambda i, j, kb: (i, 0, j)),   # delta
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0)),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qb, kb_, vb, dob, lse_p, delta)

    dk, dv = pl.pallas_call(
        functools.partial(dkv_kernel, **kwargs),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s_kp, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, s_kp, d), v.dtype),
        ],
        grid=(b * h, n_k, n_q),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, kb, j: (i, j, 0)),   # q
            pl.BlockSpec((1, bk, d), lambda i, kb, j: (i, kb, 0)),  # k
            pl.BlockSpec((1, bk, d), lambda i, kb, j: (i, kb, 0)),  # v
            pl.BlockSpec((1, bq, d), lambda i, kb, j: (i, j, 0)),   # do
            pl.BlockSpec((1, 8, bq), lambda i, kb, j: (i, 0, j)),   # lse
            pl.BlockSpec((1, 8, bq), lambda i, kb, j: (i, 0, j)),   # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda i, kb, j: (i, kb, 0)),
            pl.BlockSpec((1, bk, d), lambda i, kb, j: (i, kb, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb_, vb, dob, lse_p, delta)

    def from_bh(x, s):
        return jnp.moveaxis(x[:, :s].reshape(b, h, s, d), 1, 2)

    return from_bh(dq, s_q), from_bh(dk, s_k), from_bh(dv, s_k)


# ----------------------------------------------------------------------
# Public API
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=None, block_k=None, interpret=None,
                    bwd_block_q=None, bwd_block_k=None, taxonomy=None):
    """Blocked flash attention: (b, s, h, d) x 3 -> (b, s, h, d).

    Numerics match :func:`chainermn_tpu.ops.multi_head_attention` (fp32
    online softmax).  ``interpret=None`` auto-selects: compiled on TPU,
    interpreter elsewhere.

    Default blocks (``None``) resolve to 1024x1024 (round-4 sweep,
    benchmarks/longseq_tune.py at dh=128 on v5e: vs the old 256x512
    defaults this measured +7.5 % end-to-end at seq 2048 b8 and +24 %
    at seq 8192 b1; 1024x2048 exceeds the 16 MB scoped-vmem limit in
    the backward).  Blocks are clamped to the (padded) sequence length,
    so short sequences are unaffected, and shrunk for head dims beyond
    the measured d <= 256 feasibility boundary
    (``_clamp_blocks_for_dim``) so the backward stays inside scoped
    VMEM at geometries no sweep has covered — explicitly passed blocks
    warn when shrunk; defaults clamp silently.

    ``bwd_block_q`` / ``bwd_block_k``: SEPARATE backward block
    geometry (``None`` = inherit the forward's).  The scoped-VMEM
    limit binds only the backward (it holds three (bq, bk) fp32 score
    tiles; the forward holds one), so the forward can stream wider K/V
    blocks than the backward survives — e.g. fwd 1024x2048 with bwd
    1024x1024 (measured: benchmarks/longseq_tune.py round-5 rows).

    ``taxonomy``: block-classification mode (``None`` = ``"split"``,
    the diagonal-split kernels).  ``"legacy"`` runs the pre-split
    kernels (every live block masked — the in-tree A/B reference);
    ``"interior"`` is TIMING ONLY for the segment-anatomy bench (forces
    every live block down the unmasked fast branch; numerically wrong
    for causal/ragged inputs).  Split and legacy are bit-identical
    (``test_split_matches_legacy_exactly``).
    """
    if not PALLAS_AVAILABLE:
        raise ImportError(
            "flash_attention requires jax.experimental.pallas; use "
            "chainermn_tpu.ops.multi_head_attention on this JAX build"
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    out, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                            _should_interpret(interpret),
                            _resolve_taxonomy(taxonomy))
    return out


def _is_vmem_oom(e: Exception) -> bool:
    """Recognize a scoped-VMEM budget failure from the Mosaic compiler
    (the backward holds three (bq, bk) fp32 score tiles; on hardware
    generations beyond the measured v5e d<=256 boundary the default
    geometry can exceed the 16 MB scoped limit at compile time)."""
    s = str(e).lower()
    return "vmem" in s and any(
        m in s for m in ("scoped", "exceed", "limit", "budget")
    )


def _shrink_blocks(bq: int, bk: int):
    """One retry notch: halve both blocks, floored at the 128 lane tile
    (blocks already at or below the floor stay put — shrinking cannot
    GROW a sub-tile block).  Returns None when the geometry cannot
    shrink further."""
    def down(b):
        return min(max(b // 2 // 128 * 128, 128), b)

    nq, nk = down(bq), down(bk)
    return None if (nq, nk) == (bq, bk) else (nq, nk)


_bwd_probe_cache: dict = {}


def _bwd_compile_blocked(arrays, causal, scale, bq, bk,
                         taxonomy="split") -> bool:
    """AOT-compile probe: does the backward at this geometry compile on
    the real backend?  Needed because the production path wraps the step
    in an outer ``jax.jit`` — there the Mosaic compile error would
    surface during the STEP's compilation, after the vjp rule returned,
    where no try/except can reach it.  Probing via
    ``_flash_backward.lower(...).compile()`` with abstract shapes raises
    the scoped-VMEM failure at trace time instead, where the shrink loop
    can act.  Cached per (shapes, geometry); any probe *infrastructure*
    error counts as "not blocked" — the probe must never break a path
    that would have run."""
    key = (
        tuple((tuple(a.shape), str(a.dtype)) for a in arrays),
        causal, scale, bq, bk, taxonomy,
    )
    if key in _bwd_probe_cache:
        return _bwd_probe_cache[key]
    blocked = False
    try:
        sds = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in arrays]
        _flash_backward.lower(
            *sds, causal, scale, bq, bk, False, taxonomy
        ).compile()
    except Exception as e:
        blocked = _is_vmem_oom(e)
    _bwd_probe_cache[key] = blocked
    return blocked


def _backward_with_vmem_retry(q, k, v, out, lse, g, causal, scale,
                              block_q, block_k, interp, g_lse=None,
                              taxonomy="split"):
    """Run the backward kernels; on a scoped-VMEM compile failure retry
    with progressively ceil-shrunk block geometry (ADVICE round-5: the
    d<=256 clamp boundary was measured on v5e only — other generations
    may reject the default 1024x1024 backward at compile time).  The
    measured fast path is untouched: the first attempt is exactly the
    requested/default geometry, and the shrink loop only runs after a
    recognized VMEM failure — caught directly on the eager path, or via
    the AOT compile probe (:func:`_bwd_compile_blocked`) on the
    compiled-TPU path where the failure would otherwise surface outside
    this frame.  Same retry-on-failure shape as the resilience layer's
    transport retries, applied to kernel compilation.
    """
    d = q.shape[-1]
    bq = _DEFAULT_BLOCK if block_q is None else block_q
    bk = _DEFAULT_BLOCK if block_k is None else block_k
    probe = not interp and jax.default_backend() == "tpu"
    tried = set()
    while True:
        # the geometry that will actually run (post head-dim clamp) —
        # dedupe on it so a shrink that clamps to the same program
        # doesn't loop forever
        eff = _clamp_blocks_for_dim(bq, bk, d, warn=False)
        tried.add(eff)
        try:
            if probe and _bwd_compile_blocked(
                (q, k, v, out, lse, g), causal, scale, bq, bk, taxonomy
            ):
                raise RuntimeError(
                    f"scoped vmem limit exceeded at {eff[0]}x{eff[1]} "
                    "(AOT compile probe)"
                )
            return _flash_backward(q, k, v, out, lse, g, causal, scale,
                                   bq, bk, interp, taxonomy=taxonomy,
                                   g_lse=g_lse)
        except Exception as e:
            if not _is_vmem_oom(e):
                raise
            shrunk = _shrink_blocks(*eff)
            if shrunk is None or shrunk in tried:
                raise
            import warnings

            warnings.warn(
                f"flash_attention backward: geometry {eff[0]}x{eff[1]} "
                f"exceeded scoped VMEM on this device; retrying with "
                f"{shrunk[0]}x{shrunk[1]}"
            )
            try:  # observable on any attached resilience log
                from ..resilience.log import emit

                emit("kernel_retry", "pallas.flash_backward",
                     from_blocks=eff, to_blocks=shrunk)
            except Exception:
                pass
            bq, bk = shrunk


def _resolve_bwd_blocks(block_q, block_k, bwd_block_q, bwd_block_k, d):
    """Backward block geometry: inherit the forward's unless
    overridden.  EXPLICIT bwd overrides get the clamp WARNING here
    (inside ``_flash_backward`` the clamp is warn=False, tuned for the
    shared case where the forward already warned) — but the returned
    blocks stay UNCLAMPED: ``_flash_backward`` applies the one real
    clamp, so the geometry that runs is exactly the geometry the
    warning names (a clamp here too would shrink twice — the clamp is
    not idempotent: 1024 -> 512 -> 256 at d=384).  Shared by both
    backward rules so the policy cannot diverge between entry points."""
    explicit_bwd = bwd_block_q is not None or bwd_block_k is not None
    bq = block_q if bwd_block_q is None else bwd_block_q
    bk = block_k if bwd_block_k is None else bwd_block_k
    if explicit_bwd:
        _clamp_blocks_for_dim(bq, bk, d, warn=True,
                              _context="bwd")  # warning only
    return bq, bk


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret,
                    bwd_block_q=None, bwd_block_k=None, taxonomy=None):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              _should_interpret(interpret),
                              _resolve_taxonomy(taxonomy))
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, scale, block_q, block_k, interpret,
                    bwd_block_q, bwd_block_k, taxonomy, residuals, g):
    q, k, v, out, lse = residuals
    if scale is None:
        scale = q.shape[-1] ** -0.5
    interp = _should_interpret(interpret)
    if not interp and (q.shape[1] < 128 or k.shape[1] < 128):
        # Compiled path with sub-lane-tile sequences (explicit small
        # *blocks* are clamped by _effective_q_block, but a sequence
        # shorter than a lane tile cannot be): a dense recompute is both
        # safe and cheap at these sizes.
        from .attention import multi_head_attention

        _, vjp = jax.vjp(
            lambda q, k, v: multi_head_attention(
                q, k, v, causal=causal, scale=scale
            ),
            q, k, v,
        )
        return vjp(g)
    bq, bk = _resolve_bwd_blocks(block_q, block_k, bwd_block_q,
                                 bwd_block_k, q.shape[-1])
    return _backward_with_vmem_retry(q, k, v, out, lse, g, causal,
                                     scale, bq, bk, interp,
                                     taxonomy=_resolve_taxonomy(taxonomy))


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _dense_attention_with_lse(q, k, v, causal, scale):
    """Plain-JAX (out, lse) attention — the differentiable small-shape
    fallback for :func:`flash_attention_with_lse` (fp32 softmax)."""
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        qi = lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
        kj = lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1)
        s = jnp.where((kj <= qi)[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    den = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd", p / den, v.astype(jnp.float32))
    lse = (m + jnp.log(den))[..., 0]  # (b, h, s_q)
    return out.astype(q.dtype), jnp.moveaxis(lse, 1, 2)  # lse (b, s_q, h)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def flash_attention_with_lse(q, k, v, causal=False, scale=None,
                             block_q=None, block_k=None, interpret=None,
                             bwd_block_q=None, bwd_block_k=None,
                             taxonomy=None):
    """Flash attention returning ``(out, lse)`` with BOTH outputs
    differentiable — ``lse`` is the per-row log-sum-exp of the scaled
    scores, shaped (b, s_q, h).

    This is the building block for blockwise/ring composition
    (:func:`chainermn_tpu.parallel.ring_attention` with
    ``use_flash=True``): partial outputs over K/V blocks merge exactly
    via their lse, and gradients flow through the merge weights because
    the lse VJP is folded into the same backward kernels (see
    ``_flash_backward``'s ``g_lse``)."""
    out, lse = _flash_with_lse_fwd_rule(
        q, k, v, causal, scale, block_q, block_k, interpret,
        taxonomy=taxonomy,
    )[0]
    return out, lse


def _flash_with_lse_fwd_rule(q, k, v, causal, scale, block_q, block_k,
                             interpret, bwd_block_q=None,
                             bwd_block_k=None, taxonomy=None):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    interp = _should_interpret(interpret)
    if not PALLAS_AVAILABLE or (
        not interp and (q.shape[1] < 128 or k.shape[1] < 128)
    ):
        # Sub-lane-tile compiled shapes: dense path for value AND grads.
        out, lse = _dense_attention_with_lse(q, k, v, causal, scale)
        return (out, lse), (q, k, v, None, None)
    out, lse_bh = _flash_forward(q, k, v, causal, scale, block_q,
                                 block_k, interp,
                                 _resolve_taxonomy(taxonomy))
    b, s_q, h, _ = q.shape
    lse = jnp.moveaxis(lse_bh.reshape(b, h, s_q), 1, 2)  # (b, s_q, h)
    return (out, lse), (q, k, v, out, lse_bh)


def _flash_with_lse_bwd_rule(causal, scale, block_q, block_k, interpret,
                             bwd_block_q, bwd_block_k, taxonomy,
                             residuals, g):
    q, k, v, out, lse_bh = residuals
    g_out, g_lse = g
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if out is None:  # dense fallback residuals
        _, vjp = jax.vjp(
            lambda q, k, v: _dense_attention_with_lse(
                q, k, v, causal, scale
            ),
            q, k, v,
        )
        return vjp((g_out, g_lse))
    b, s_q, h, _ = q.shape
    g_lse_bh = jnp.moveaxis(g_lse, 1, 2).reshape(b * h, s_q)
    bq, bk = _resolve_bwd_blocks(block_q, block_k, bwd_block_q,
                                 bwd_block_k, q.shape[-1])
    return _backward_with_vmem_retry(
        q, k, v, out, lse_bh, g_out, causal, scale, bq, bk,
        _should_interpret(interpret), g_lse=g_lse_bh,
        taxonomy=_resolve_taxonomy(taxonomy),
    )


flash_attention_with_lse.defvjp(
    _flash_with_lse_fwd_rule, _flash_with_lse_bwd_rule
)


def flash_attention_fn(block_q: Optional[int] = None,
                       block_k: Optional[int] = None,
                       interpret: Optional[bool] = None,
                       bwd_block_q: Optional[int] = None,
                       bwd_block_k: Optional[int] = None,
                       taxonomy: Optional[str] = None):
    """Adapter producing the ``attention_fn`` signature used by
    ``ulysses_attention``: ``(q, k, v, causal, scale)``.  ``taxonomy``
    passes through to :func:`flash_attention` (the segment-anatomy
    bench's knob)."""

    def fn(q, k, v, causal, scale):
        return flash_attention(q, k, v, causal, scale, block_q, block_k,
                               interpret, bwd_block_q, bwd_block_k,
                               taxonomy)

    return fn


# ----------------------------------------------------------------------
# Flash decode — q_len=1 against a PAGED KV cache (serving tier)
# ----------------------------------------------------------------------
def _flash_decode_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *, n_heads: int,
                         page_size: int, scale: float):
    """Decode-geometry flash kernel: ONE query row per (batch, head)
    program against that request's pages, walked page-by-page through
    the block table (scalar-prefetched — the index map reads it, so
    only the request's OWN pages are ever fetched into VMEM).

    Grid (batch*heads, pages_per_slot); the page dimension is innermost
    and sequential, so the online-softmax running state lives in VMEM
    scratch exactly like the training kernel's k loop.  The query is
    pre-broadcast to 8 sublanes (TPU tile floor — same trick as the
    forward kernel's lse layout); row 0 of the output block is the
    answer.  Pages past the request's length are dead (skipped
    entirely); the partial tail page masks by position.  A length-0
    slot (padded batch slot) has no live pages — finalize writes zeros.
    """
    i = pl.program_id(0)
    j = pl.program_id(1)
    n_j = pl.num_programs(1)
    b = i // n_heads

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    length = len_ref[b]
    live = j * page_size < length

    @pl.when(live)
    def _attend():
        q = q_ref[0].astype(jnp.float32) * scale      # (8, d)
        k_blk = k_ref[0, 0].astype(jnp.float32)        # (bs, d)
        v_blk = v_ref[0, 0].astype(jnp.float32)
        s = lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (8, bs)
        pos = j * page_size + lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        s = jnp.where(pos < length, s, _NEG_INF)
        m_old = m_ref[:, 0:1]
        m_new = jnp.maximum(m_old, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_old - m_new)
        l_new = alpha * l_ref[:, 0:1] + jnp.sum(p, axis=-1, keepdims=True)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        acc_ref[:] = alpha * acc_ref[:] + lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == n_j - 1)
    def _finalize():
        o_ref[0] = (
            acc_ref[:] / jnp.maximum(l_ref[:, 0:1], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def _flash_decode(q, k_pages, v_pages, block_tables, lengths, scale,
                  interpret):
    b, h, d = q.shape
    n_pages, page_size = k_pages.shape[0], k_pages.shape[1]
    pages_per_slot = block_tables.shape[1]
    # head-major page layout so the kernel block's trailing dims are
    # (page_size, d) — the sublane/lane tile the hardware wants
    kh = jnp.moveaxis(k_pages, 2, 0)  # (h, n_pages, bs, d)
    vh = jnp.moveaxis(v_pages, 2, 0)
    # 8-sublane broadcast of the single query row (TPU tile floor)
    q8 = jnp.broadcast_to(
        q.reshape(b * h, 1, d), (b * h, 8, d)
    )
    grid = (b * h, pages_per_slot)
    kernel = functools.partial(
        _flash_decode_kernel, n_heads=h, page_size=page_size,
        scale=scale,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 8, d), lambda i, j, bt, ln: (i, 0, 0)),
                pl.BlockSpec(
                    (1, 1, page_size, d),
                    lambda i, j, bt, ln, h=h: (i % h, bt[i // h, j], 0, 0),
                ),
                pl.BlockSpec(
                    (1, 1, page_size, d),
                    lambda i, j, bt, ln, h=h: (i % h, bt[i // h, j], 0, 0),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, 8, d), lambda i, j, bt, ln: (i, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((8, d), jnp.float32),    # acc
                pltpu.VMEM((8, 128), jnp.float32),  # running max (col 0)
                pltpu.VMEM((8, 128), jnp.float32),  # running denom
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, 8, d), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      q8, kh, vh)
    return out[:, 0].reshape(b, h, d)


def flash_decode(q, k_pages, v_pages, block_tables, lengths,
                 scale: Optional[float] = None,
                 interpret: Optional[bool] = None) -> jnp.ndarray:
    """Single-token paged-cache attention (the serving tier's decode
    geometry): each batch slot's one query attends against the pages
    its block table names.

    Args:
      q: (batch, heads, d) — one query per decode slot.
      k_pages / v_pages: (num_pages, page_size, heads, d) — the shared
        page pool (``serving.kv_cache.PagedKVCache`` layout for one
        layer).
      block_tables: (batch, pages_per_slot) int32 page ids per slot.
      lengths: (batch,) int32 — live cache positions per slot (the new
        token's k/v already written, so a decoding slot passes
        ``cached + 1``).  Length-0 slots (padding) return zeros.
    Returns:
      (batch, heads, d) in ``q.dtype``.

    Numerics: fp32 online softmax over pages, like the training
    kernel — agrees with :func:`paged_decode_reference` (one exact fp32
    softmax over the gathered cache) to float roundoff, and exactly
    when a request fits one page (single-block online softmax is the
    dense computation).  The serving decode *step* uses the dense
    paged attend for its bit-exactness contract; this kernel is the
    TPU fast path (``DecodeEngine(attention_impl="flash")``).
    """
    if not PALLAS_AVAILABLE:
        return paged_decode_reference(
            q, k_pages, v_pages, block_tables, lengths, scale
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash_decode(q, k_pages, v_pages, block_tables, lengths,
                         float(scale), _should_interpret(interpret))


def paged_decode_reference(q, k_pages, v_pages, block_tables, lengths,
                           scale: Optional[float] = None) -> jnp.ndarray:
    """Dense oracle for :func:`flash_decode`: gather every slot's pages
    into a contiguous buffer and run one exact fp32 softmax.  Same
    masking contract (positions >= length dead; length 0 -> zeros)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b = q.shape[0]
    page_size = k_pages.shape[1]
    kg = k_pages[block_tables]  # (b, n, bs, h, d)
    vg = v_pages[block_tables]
    n_tot = kg.shape[1] * page_size
    kg = kg.reshape(b, n_tot, *kg.shape[3:])
    vg = vg.reshape(b, n_tot, *vg.shape[3:])
    s = jnp.einsum(
        "bhd,bkhd->bhk", q.astype(jnp.float32) * scale,
        kg.astype(jnp.float32),
    )
    pos = jnp.arange(n_tot)[None, None, :]
    mask = pos < lengths[:, None, None]
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    den = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    # divide AFTER the PV product — the kernel's finalize order, so a
    # single-page request (where online softmax IS the dense softmax)
    # matches bit for bit (pinned by test)
    out = jnp.einsum(
        "bhk,bkhd->bhd", p, vg.astype(jnp.float32)
    ) / den
    # length-0 (padded) slots: with EVERY position masked the max IS
    # the mask value, so exp(s - m) == 1 everywhere and the softmax
    # degenerates to a mean of garbage — zero them explicitly, matching
    # the kernel (whose pages are all dead there, acc == 0)
    out = jnp.where(lengths[:, None, None] > 0, out, 0.0)
    return out.astype(q.dtype)


# ----------------------------------------------------------------------
# Fused cast + scale (the reference's PureNccl fp16 kernels, #11)
# ----------------------------------------------------------------------
def _cast_scale_kernel(x_ref, o_ref, *, scale: float):
    o_ref[:] = (x_ref[:].astype(jnp.float32) * scale).astype(o_ref.dtype)


def fused_cast_scale(x: jnp.ndarray, scale: float, dtype,
                     interpret: Optional[bool] = None) -> jnp.ndarray:
    """``(x * scale).astype(dtype)`` in one VMEM pass.

    Parity: the cast-and-scale ElementwiseKernels PureNcclCommunicator
    launches around its fp16 allreduce (divide-by-size fused with the
    cast-back).  Any shape; internally flattened to lane-aligned tiles.
    """
    if not PALLAS_AVAILABLE or x.size == 0:
        return (x.astype(jnp.float32) * scale).astype(dtype)
    shape = x.shape
    flat = x.reshape(-1)
    n = flat.shape[0]
    lane = 128
    rows = _round_up((n + lane - 1) // lane, 8)
    pad = rows * lane - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    tiled = flat.reshape(rows, lane)
    block_rows = min(rows, 512)
    rows_p = _round_up(rows, block_rows)
    if rows_p != rows:
        tiled = jnp.pad(tiled, ((0, rows_p - rows), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_cast_scale_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((rows_p, lane), jnp.dtype(dtype)),
        grid=(rows_p // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, lane), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, lane), lambda i: (i, 0)),
        interpret=_should_interpret(interpret),
    )(tiled)
    return out.reshape(-1)[:n].reshape(shape)

"""Attention core.

Single-device reference attention used as the numerics oracle for
``parallel.ring_attention`` / ``parallel.ulysses_attention`` tests, and as
the default core those wrap.  A Pallas flash-attention kernel can be
slotted in via the ``attention_fn`` hooks once profiling justifies it
(SURVEY.md section 2 native-code obligations: only hand-write what XLA
doesn't already fuse).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def multi_head_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                         *, causal: bool = False,
                         scale: Optional[float] = None) -> jnp.ndarray:
    """(b, s, h, d) x 3 -> (b, s, h, d), fp32 softmax accumulation."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        S_q, S_k = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((S_q, S_k), bool))
        s = jnp.where(mask[None, None], s, jnp.finfo(jnp.float32).min)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)

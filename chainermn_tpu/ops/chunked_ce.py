"""Chunked fused linear + softmax cross-entropy (single chip).

The LM loss `lm_loss(model.apply(p, toks), toks)` materializes the full
(batch, seq, vocab) fp32 logits tensor — 2.1 GB for the bench config
(8x2048x32768) and the reason batch 16 OOMs even under remat.  This op
computes the SAME next-token cross entropy by scanning the tied
embedding table in vocab chunks with an online-softmax merge (the
flash-attention recipe applied to the classifier head):

  forward:  per chunk, logits_c = h @ E_c^T (bf16 MXU, fp32 accum),
            running (max, sumexp) merge + target-logit gather —
            peak extra memory is one (N, V/chunks) block.
  backward: recomputes each chunk's probabilities from the saved
            per-position (max + log-sumexp) — dh accumulates
            sum_c P_c @ E_c − E[target], dE accumulates
            P_c^T h − scatter(target, h) — again one block at a time.

This is the single-chip sibling of
:func:`~chainermn_tpu.parallel.vocab_parallel_cross_entropy` (which
avoids the full-vocab row by sharding it over chips; here it is chunked
in time instead).  Numerics note: the chunk matmuls run in bf16 with
fp32 accumulation (`preferred_element_type`), whereas the dense path
upcasts hidden states to fp32 first — losses agree to ~1e-2 relative,
gradients to bf16 tolerance (pinned in tests/test_chunked_ce.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _chunk_logits(h, e_chunk):
    """(N, d) x (Vc, d) -> (N, Vc) in bf16 with fp32 accumulation."""
    return lax.dot_general(
        h.astype(jnp.bfloat16), e_chunk.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def chunked_softmax_cross_entropy(h, table, targets, n_chunks=16):
    """Per-position CE of softmax(h @ table.T) against ``targets``.

    Args:
      h: (N, d) hidden states (any float dtype; matmuls run bf16).
      table: (V, d) classifier/embedding table; V % n_chunks == 0.
      targets: (N,) int32 class ids.
      n_chunks: vocab chunks; peak memory ~ N * V / n_chunks floats.
    Returns:
      (N,) fp32 cross-entropy per position.
    """
    ce, _ = _ce_fwd_impl(h, table, targets, n_chunks)
    return ce


def _ce_fwd_impl(h, table, targets, n_chunks):
    n, d = h.shape
    v = table.shape[0]
    if v % n_chunks:
        raise ValueError(f"vocab {v} % n_chunks {n_chunks} != 0")
    vc = v // n_chunks
    e = table.reshape(n_chunks, vc, d)
    chunk_ids = jnp.arange(n_chunks)

    def body(carry, ec_i):
        m, s, tl = carry
        ec, i = ec_i
        logits = _chunk_logits(h, ec)  # (N, Vc) fp32
        cm = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - cm) + jnp.sum(
            jnp.exp(logits - cm[:, None]), axis=-1
        )
        in_c = targets // vc == i
        idx = jnp.clip(targets - i * vc, 0, vc - 1)
        picked = jnp.take_along_axis(
            logits, idx[:, None], axis=1
        )[:, 0]
        tl = tl + jnp.where(in_c, picked, 0.0)
        return (cm, s, tl), None

    init = (
        jnp.full((n,), -jnp.inf, jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32),
    )
    (m, s, tl), _ = lax.scan(body, init, (e, chunk_ids))
    lse = m + jnp.log(s)
    return lse - tl, (h, table, targets, lse)


def _ce_fwd(h, table, targets, n_chunks):
    return _ce_fwd_impl(h, table, targets, n_chunks)


def _ce_bwd(n_chunks, res, g):
    h, table, targets, lse = res
    n, d = h.shape
    v = table.shape[0]
    vc = v // n_chunks
    e = table.reshape(n_chunks, vc, d)
    g = g.astype(jnp.float32)
    gh = (g[:, None] * h.astype(jnp.float32)).astype(jnp.float32)

    def bf16_mm(a, b_mat, dims):
        return lax.dot_general(
            a.astype(jnp.bfloat16), b_mat.astype(jnp.bfloat16), dims,
            preferred_element_type=jnp.float32,
        )

    def body(dh, ec_i):
        ec, i = ec_i
        logits = _chunk_logits(h, ec)
        # d(lse)/dlogits = softmax; scaled by the upstream cotangent
        p = jnp.exp(logits - lse[:, None]) * g[:, None]  # (N, Vc)
        # both accumulation matmuls run bf16 on the MXU (fp32 accum) —
        # the same precision class as the forward chunk matmul
        dh = dh + bf16_mm(p, ec, (((1,), (0,)), ((), ())))
        de_c = bf16_mm(p, h, (((0,), (0,)), ((), ())))   # (Vc, d)
        # −target_logit term: subtract where the target is in this chunk
        in_c = targets // vc == i
        idx = jnp.clip(targets - i * vc, 0, vc - 1)
        sel = jnp.where(in_c, 1.0, 0.0)[:, None]
        de_c = de_c.at[idx].add(-sel * gh)
        dh = dh - sel * jnp.take(ec, idx, axis=0).astype(jnp.float32) * \
            g[:, None]
        return dh, de_c

    dh, de = lax.scan(body, jnp.zeros((n, d), jnp.float32),
                      (e, jnp.arange(n_chunks)))
    return (
        dh.astype(h.dtype),
        de.reshape(v, d).astype(table.dtype),
        None,
    )


chunked_softmax_cross_entropy.defvjp(_ce_fwd, _ce_bwd)


def chunked_lm_loss(model, params, tokens, n_chunks=16):
    """Next-token CE for a dense ``TransformerLM`` WITHOUT materializing
    the (batch, seq, vocab) logits: runs the model to hidden states
    (``return_hidden=True`` twin) and feeds the weight-tied table
    through :func:`chunked_softmax_cross_entropy`.

    Drop-in for ``lm_loss(model.apply(p, b), b)`` on the single-chip /
    pure-DP path; for vocab-sharded models use ``vp_lm_loss`` (the
    cross-chip form of the same idea).
    """
    if getattr(model, "vocab_parallel", False):
        raise ValueError("chunked_lm_loss is the single-chip tier; "
                         "vocab-parallel models use vp_lm_loss")
    twin = model.clone(return_hidden=True)
    hidden = twin.apply(params, tokens)          # (b, s, d) fp32
    table = params["params"]["embed"]["embedding"]
    b, s, d = hidden.shape
    h = hidden[:, :-1].reshape(-1, d)
    targets = tokens[:, 1:].reshape(-1)
    ce = chunked_softmax_cross_entropy(h, table, targets, n_chunks)
    return ce.mean()

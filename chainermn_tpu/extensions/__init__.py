from .evaluator import create_multi_node_evaluator, Evaluator  # noqa: F401
from .checkpoint import create_multi_node_checkpointer  # noqa: F401
from .allreduce_persistent import AllreducePersistent  # noqa: F401

__all__ = [
    "create_multi_node_evaluator",
    "Evaluator",
    "create_multi_node_checkpointer",
    "AllreducePersistent",
]

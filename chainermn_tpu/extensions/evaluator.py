"""Multi-node evaluator.

Reference parity: ``create_multi_node_evaluator`` in
``chainermn/extensions/`` — wrap an evaluator so each rank evaluates its
local validation shard and the result dict is allreduce-averaged, making
every rank report *global* validation metrics.

TPU-native redesign: the evaluation step is a jitted SPMD function over the
communicator's mesh (batch sharded over all mesh axes, metrics pmean-ed
inside the program), so "run local shard then average the dicts" becomes a
single compiled pass over a globally-sharded eval set.  An eager dict
reduction (``allreduce_obj``-style) is kept for custom host-side metrics.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from chainermn_tpu.functions import collectives as _cc


class Evaluator:
    """Runs ``metric_fn(params, batch) -> dict`` over an iterator and
    reports the global mean of each metric.

    ``metric_fn`` is written per-shard (local batch); the evaluator builds
    one jitted SPMD program in which the batch is sharded across the mesh
    and every metric is ``pmean``-ed over the communicator axes.
    """

    trigger = (1, "epoch")
    priority = 300
    name = "validation"

    def __init__(self, iterator_factory, metric_fn: Callable, comm,
                 params_getter: Optional[Callable] = None,
                 prefix: str = "val/"):
        self._make_iterator = iterator_factory
        self._comm = comm
        self._prefix = prefix
        self._params_getter = params_getter
        mesh = comm.mesh
        axes = comm.axis_names
        spec = P(axes)

        def _step(params, batch):
            metrics = metric_fn(params, batch)
            return {k: _cc.pmean(v, axes) for k, v in metrics.items()}

        self._step = jax.jit(
            jax.shard_map(
                _step, mesh=mesh, in_specs=(P(), spec), out_specs=P(),
                check_vma=False,
            )
        )
        self._batch_sharding = NamedSharding(mesh, spec)
        self._rep = NamedSharding(mesh, P())

    def evaluate(self, params) -> Dict[str, float]:
        params = jax.device_put(params, self._rep)
        n_chips = self._comm.size
        totals: Dict[str, float] = {}
        count = 0
        for batch in self._make_iterator():
            leaves = jax.tree_util.tree_leaves(batch)
            if leaves and leaves[0].shape[0] % n_chips:
                raise ValueError(
                    f"evaluation batch of {leaves[0].shape[0]} rows is not "
                    f"divisible by {n_chips} chips; use EpochIterator("
                    "..., pad_to=comm.size)"
                )
            batch = jax.device_put(batch, self._batch_sharding)
            out = self._step(params, batch)
            for k, v in out.items():
                totals[k] = totals.get(k, 0.0) + float(v)
            count += 1
        return {
            self._prefix + k: v / max(count, 1) for k, v in totals.items()
        }

    # Trainer-extension protocol
    def __call__(self, trainer):
        params = (
            self._params_getter() if self._params_getter
            else trainer.updater.params
        )
        result = self.evaluate(params)
        # Surface the trainer's resilience counters next to the val
        # metrics: a validation score is only interpretable alongside
        # how many steps were skipped / restarted to reach it.
        rlog = getattr(trainer, "resilience_log", None)
        if rlog is not None:
            for kind, n in rlog.counts.items():
                result[f"resilience/{kind}"] = n
        trainer.observation.update(result)
        return result


def create_multi_node_evaluator(actual_evaluator, communicator):
    """Make an evaluator report communicator-global averaged metrics.

    Parity: ``chainermn.create_multi_node_evaluator(evaluator, comm)``.
    Accepts either this module's :class:`Evaluator` (returned as-is — it is
    already communicator-aware) or any object with an ``evaluate()``
    returning a metrics dict, which gets wrapped so the dict is averaged
    across processes via the control plane.
    """
    if isinstance(actual_evaluator, Evaluator):
        return actual_evaluator

    class _Wrapped:
        def __init__(self, ev, comm):
            self._ev = ev
            self._comm = comm

        def evaluate(self, *a, **kw):
            from ..resilience.retry import lockstep_allgather

            local = self._ev.evaluate(*a, **kw)
            # agreement-shaped: every rank folds every rank's metrics,
            # so a torn payload must retry on all ranks together
            # (proto-raw-allgather)
            gathered = lockstep_allgather(
                self._comm, local, site="evaluator.aggregate"
            )
            keys = gathered[0].keys()
            return {
                k: float(np.mean([g[k] for g in gathered])) for k in keys
            }

        def __call__(self, trainer):
            res = self.evaluate(trainer.updater.params)
            trainer.observation.update(res)
            return res

        def __getattr__(self, name):
            return getattr(self._ev, name)

    return _Wrapped(actual_evaluator, communicator)

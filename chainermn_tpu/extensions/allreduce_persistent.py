"""Allreduce of persistent (non-gradient) state.

Reference parity: ``chainermn/extensions/allreduce_persistent.py`` —
``AllreducePersistent(model, comm)``: allreduce-average persistent arrays
(BatchNorm running mean/var) so ranks agree before snapshot/eval.

TPU-native form: persistent state is the flax ``batch_stats`` collection.
Three situations, three paths:

* **Replicated global arrays** (the compiled ``build_train_step`` tier,
  which already pmean-s aux state): nothing to do — identity.
* **Stacked per-rank stats** (the eager tier: leading axis == comm.size,
  one slice per rank, exactly the reference's per-rank BN buffers): pass
  ``stacked=True`` — the reduce is ``comm.allreduce(mean)`` over the mesh
  axes, riding ICI.
* **Multi-controller drift** (per-process host state, e.g. from
  non-deterministic input orders): a host allreduce across processes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class AllreducePersistent:
    priority = 250
    trigger = (1, "epoch")
    name = "allreduce_persistent"

    def __init__(self, comm, stats_getter=None, stats_setter=None,
                 stacked: bool = False):
        self._comm = comm
        self._get = stats_getter
        self._set = stats_setter
        self._stacked = stacked

    def reduce(self, stats):
        """Average a pytree of persistent arrays so every rank/process
        agrees (parity: AllreducePersistent.__call__'s allreduce)."""
        if self._stacked:
            # per-rank stacked stats -> every slice = mean over ranks, via
            # the communicator's XLA (ICI) allreduce.  The stacked array is
            # global over every process's devices, so this path alone
            # already makes all controllers agree.
            return jax.tree_util.tree_map(
                lambda x: self._comm.allreduce(x, op="mean"), stats
            )
        if self._comm.process_count > 1:
            from jax.experimental import multihost_utils

            def mean_across(x):
                g = multihost_utils.process_allgather(jnp.asarray(x))
                return jnp.mean(g, axis=0)

            return jax.tree_util.tree_map(mean_across, stats)
        # Replicated single-controller state is already consistent.
        return stats

    def __call__(self, trainer):
        if self._get and self._set:
            self._set(self.reduce(self._get()))

"""Allreduce of persistent (non-gradient) state.

Reference parity: ``chainermn/extensions/allreduce_persistent.py`` —
``AllreducePersistent(model, comm)``: allreduce-average persistent arrays
(BatchNorm running mean/var) so ranks agree before snapshot/eval.

TPU-native form: persistent state is the flax ``batch_stats`` collection.
Under GSPMD these are already replicated global arrays *within* one
controller; cross-process agreement (multi-controller drift, e.g. from
non-deterministic host input orders) is restored by a pmean over the mesh
axes when the stats were computed per-shard, or a host allreduce across
processes otherwise.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class AllreducePersistent:
    priority = 250
    trigger = (1, "epoch")
    name = "allreduce_persistent"

    def __init__(self, comm, stats_getter=None, stats_setter=None):
        self._comm = comm
        self._get = stats_getter
        self._set = stats_setter

    def reduce(self, stats):
        """Average a pytree of persistent arrays across processes."""
        if self._comm.process_count > 1:
            from jax.experimental import multihost_utils

            def mean_across(x):
                g = multihost_utils.process_allgather(jnp.asarray(x))
                return jnp.mean(g, axis=0)

            return jax.tree_util.tree_map(mean_across, stats)
        # Single controller: stats are already globally consistent.
        return stats

    def __call__(self, trainer):
        if self._get and self._set:
            self._set(self.reduce(self._get()))

"""Distributed checkpoint / resume with newest-common-step agreement.

Reference parity: ``chainermn/extensions/checkpoint.py`` —
``create_multi_node_checkpointer(name, comm, ...)``: every rank snapshots
its local state at an interval; ranks allgather their snapshot inventories
and agree on the newest iteration present on *all* ranks; stale files are
garbage-collected; resume loads the newest common snapshot — fault-tolerant
restart under a batch scheduler (and, on TPU, under preemption).

TPU-native redesign: arrays are *global* (sharded over the mesh), so the
storage layer is orbax/tensorstore — each process writes exactly its
addressable shards of one logical checkpoint instead of one npz per rank.
The agreement protocol survives unchanged, but it agrees on complete
*global* checkpoints (a step counts only if every process finished its
shards — orbax's commit semantics make partial writes invisible, which is
strictly stronger than the reference's per-rank npz inventory).
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Any, Dict, Optional

import jax
import numpy as np


def _flatten_state(tree) -> Dict[str, np.ndarray]:
    flat = {}
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in leaves:
        key = "/".join(str(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


class _MultiNodeCheckpointer:
    """Trainer extension; also usable standalone via save()/resume()."""

    priority = 200
    name = "checkpointer"

    def __init__(self, name: str, comm, path: str = "checkpoints",
                 trigger=(1, "epoch"), keep: int = 3,
                 use_orbax: bool = True):
        self._name = name
        self._comm = comm
        self._root = os.path.join(path, name)
        self.trigger = trigger
        self._keep = keep
        self._use_orbax = use_orbax
        self._ckptr = None
        os.makedirs(self._root, exist_ok=True)

    # -- storage backends ----------------------------------------------
    def _orbax(self):
        if self._ckptr is None:
            import orbax.checkpoint as ocp

            self._ckptr = ocp.PyTreeCheckpointer()
        return self._ckptr

    def _step_dir(self, step: int) -> str:
        return os.path.join(self._root, f"step_{step:012d}")

    def _available_steps(self) -> list:
        steps = []
        if os.path.isdir(self._root):
            for d in os.listdir(self._root):
                m = re.fullmatch(r"step_(\d+)", d)
                if m and self._is_complete(os.path.join(self._root, d)):
                    steps.append(int(m.group(1)))
        return sorted(steps)

    def _is_complete(self, path: str) -> bool:
        # orbax writes atomically (tmp dir + rename); presence of the final
        # dir (with no orbax tmp marker) means commit finished.
        return os.path.isdir(path) and not path.endswith(".tmp")

    # -- save ----------------------------------------------------------
    def save(self, step: int, state: Dict[str, Any]) -> None:
        """Snapshot ``state`` (a pytree of global arrays + metadata)."""
        target = self._step_dir(step)
        if os.path.exists(target):
            shutil.rmtree(target)
        if self._use_orbax:
            try:
                self._orbax().save(os.path.abspath(target), state)
            except Exception:
                self._save_np(target, state)
        else:
            self._save_np(target, state)
        self._gc()

    def _save_np(self, target: str, state) -> None:
        os.makedirs(target, exist_ok=True)
        np.savez(os.path.join(target, "state.npz"), **_flatten_state(state))

    # -- agreement + resume --------------------------------------------
    def newest_common_step(self) -> Optional[int]:
        """The newest step every process has on disk (parity: the allgather
        of snapshot inventories + max-common computation)."""
        local = self._available_steps()
        inventories = self._comm.allgather_obj(local)
        common = set(inventories[0])
        for inv in inventories[1:]:
            common &= set(inv)
        return max(common) if common else None

    def resume(self, like: Optional[Dict[str, Any]] = None):
        """Load the newest common snapshot; returns (step, state) or
        (None, None) when no checkpoint exists."""
        step = self.newest_common_step()
        if step is None:
            return None, None
        target = self._step_dir(step)
        npz = os.path.join(target, "state.npz")
        if os.path.exists(npz):
            data = np.load(npz, allow_pickle=True)
            return step, dict(data)
        state = self._orbax().restore(
            os.path.abspath(target), item=like
        )
        return step, state

    def _gc(self) -> None:
        steps = self._available_steps()
        for s in steps[: -self._keep] if self._keep else []:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def finalize(self, trainer=None) -> None:
        """Parity: the reference's finalize/GC of stale snapshots."""
        self._gc()

    # -- trainer-extension protocol ------------------------------------
    def __call__(self, trainer) -> None:
        state = {
            "params": trainer.updater.params,
            "opt_state": trainer.updater.opt_state,
            "trainer": trainer.state_dict(),
        }
        self.save(trainer.iteration, state)

    def restore_trainer(self, trainer) -> Optional[int]:
        step, state = self.resume(
            like={
                "params": trainer.updater.params,
                "opt_state": trainer.updater.opt_state,
                "trainer": trainer.state_dict(),
            }
        )
        if step is None:
            return None
        trainer.updater.params = state["params"]
        trainer.updater.opt_state = state["opt_state"]
        trainer.load_state_dict(state["trainer"])
        return step


def create_multi_node_checkpointer(name: str, comm, path: str = "checkpoints",
                                   trigger=(1, "epoch"), keep: int = 3,
                                   **kw) -> _MultiNodeCheckpointer:
    """Parity: ``chainermn.create_multi_node_checkpointer(name, comm)``."""
    return _MultiNodeCheckpointer(name, comm, path=path, trigger=trigger,
                                  keep=keep, **kw)

"""Distributed checkpoint / resume with newest-common-step agreement.

Reference parity: ``chainermn/extensions/checkpoint.py`` —
``create_multi_node_checkpointer(name, comm, ...)``: every rank snapshots
its local state at an interval; ranks allgather their snapshot inventories
and agree on the newest iteration present on *all* ranks; stale files are
garbage-collected; resume loads the newest common snapshot — fault-tolerant
restart under a batch scheduler (and, on TPU, under preemption).

TPU-native redesign: arrays are *global* (sharded over the mesh), so the
storage layer is orbax/tensorstore — each process writes exactly its
addressable shards of one logical checkpoint instead of one npz per rank.
The agreement protocol survives unchanged, but it agrees on complete
*global* checkpoints (a step counts only if every process finished its
shards — orbax's commit semantics make partial writes invisible, which is
strictly stronger than the reference's per-rank npz inventory).

Elastic restart (``resilience.elastic``): every snapshot carries a world
manifest (world size, process count, mesh axes; the npz tier adds
per-file integrity digests the inventory verifies).  ``resume()`` in a
world whose size differs from the manifest routes the state through the
checkpoint resharder instead of failing — see :meth:`resume`.
"""

from __future__ import annotations

import os
import pickle
import re
import shutil
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..observability import timeline as _obs
from ..resilience import elastic as _elastic
from ..resilience import fault_injection as _fi
from ..resilience.log import emit as _emit


class _MultiNodeCheckpointer:
    """Trainer extension; also usable standalone via save()/resume()."""

    priority = 200
    name = "checkpointer"

    def __init__(self, name: str, comm, path: str = "checkpoints",
                 trigger=(1, "epoch"), keep: int = 3,
                 use_orbax: bool = True, use_async: bool = False):
        """``use_async``: snapshot through ``ocp.AsyncCheckpointer`` —
        ``save()`` returns once the arrays are copied to host and the
        serialization/write continues on a background thread, so a
        snapshot does not stall training (measured:
        benchmarks/checkpoint_bench.py; docs/performance.md "Checkpoint
        performance").  Commit stays atomic (tmp dir + rename), so the
        agreement protocol is unaffected: an in-flight save is simply
        not visible yet.  Call :meth:`wait_until_finished` (or
        ``finalize``) before reading the snapshot back or exiting."""
        if use_async and not use_orbax:
            raise ValueError(
                "use_async=True requires the orbax tier: the npz "
                "backend writes synchronously, which would silently "
                "break the non-stalling-save contract async promises"
            )
        self._name = name
        self._comm = comm
        self._root = os.path.join(path, name)
        if comm.process_count > 1 and not use_orbax:
            # Per-rank local-npz tier: every process writes its OWN
            # snapshots (the reference's per-rank storage model).  The
            # root is namespaced by process index so a path that happens
            # to be on a shared filesystem can never make two ranks race
            # on the same state.npz; on genuinely rank-local disks the
            # extra directory level is harmless.
            self._root = os.path.join(
                self._root, f"rank_{comm.process_index}"
            )
        self.trigger = trigger
        self._keep = keep
        self._use_orbax = use_orbax
        self._use_async = use_async
        self._ckptr = None
        # (old_world, new_world) of the last resume that routed through
        # the elastic resharder; None when the worlds matched.
        # last_manifest: the elected snapshot's world manifest.
        self.last_resize = None
        self.last_manifest = None
        # integrity-verification memo: path -> (stat signature, ok).
        # Committed snapshots never change, so one full-content hash per
        # directory state is enough; the inventory scan re-stats only.
        self._verified: dict = {}
        os.makedirs(self._root, exist_ok=True)

    # -- storage backends ----------------------------------------------
    def _orbax(self):
        if self._ckptr is None:
            import orbax.checkpoint as ocp

            if self._use_async:
                self._ckptr = ocp.AsyncCheckpointer(
                    ocp.PyTreeCheckpointHandler()
                )
            else:
                self._ckptr = ocp.PyTreeCheckpointer()
        return self._ckptr

    def wait_until_finished(self) -> None:
        """Block until any in-flight async save has committed (no-op for
        the sync checkpointer or before the first save)."""
        if self._ckptr is not None and hasattr(
            self._ckptr, "wait_until_finished"
        ):
            self._ckptr.wait_until_finished()

    def _step_dir(self, step: int) -> str:
        return os.path.join(self._root, f"step_{step:012d}")

    def _available_steps(self) -> list:
        steps = []
        if os.path.isdir(self._root):
            # sorted: the step inventory feeds newest_common_step's
            # cross-rank agreement; listdir order must not differ per
            # host (spmd-unsorted-scan)
            for d in sorted(os.listdir(self._root)):
                m = re.fullmatch(r"step_(\d+)", d)
                if m and self._is_complete(os.path.join(self._root, d)):
                    steps.append(int(m.group(1)))
        return sorted(steps)

    def _is_complete(self, path: str) -> bool:
        # orbax writes atomically (tmp dir + rename); presence of the final
        # dir (with no orbax tmp marker) means commit finished.  On top of
        # presence, the integrity manifest (written at save by the npz
        # tier) is verified: a torn/corrupt snapshot (truncated npz,
        # flipped byte) is EXCLUDED from the inventory, so the agreement
        # protocol degrades to the previous step instead of electing a
        # snapshot that raises at load.
        if not os.path.isdir(path) or path.endswith(".tmp"):
            return False
        sig = _elastic.snapshot_signature(path)
        cached = self._verified.get(path)
        if cached is not None and cached[0] == sig:
            return cached[1]
        ok = _elastic.verify_snapshot(path)
        self._verified[path] = (sig, ok)
        if not ok:
            _emit("snapshot_corrupt", "checkpoint.inventory", path=path)
        return ok

    @property
    def _is_chief(self) -> bool:
        return self._comm.process_index == 0

    @property
    def _multiproc(self) -> bool:
        return self._comm.process_count > 1

    # -- save ----------------------------------------------------------
    def save(self, step: int, state: Dict[str, Any]) -> None:
        """Snapshot ``state`` (a pytree of global arrays + metadata).

        Under multi-process this is a collective: every process must call
        it (orbax writes each process's addressable shards); filesystem
        mutations of shared directories are chief-only with barriers.
        """
        with _obs.span("checkpoint.save", step=int(step)):
            self._save(step, state)

    def _save(self, step: int, state: Dict[str, Any]) -> None:
        # resilience site: rank-death / slice-loss rehearsal point for
        # the elastic mp tier (a `die` spec targeted at one process is a
        # spot reclaim mid-snapshot); no-op when no injector is active
        _fi.fire("checkpoint.save")
        target = self._step_dir(step)
        # Back-to-back saves serialize here (an in-flight async write of
        # an older step must commit before we mutate the directory
        # listing); save-vs-TRAINING overlap is unaffected.
        self.wait_until_finished()
        if self._multiproc and not self._use_orbax:
            # The reference's own storage model: each rank snapshots
            # PROCESS-LOCAL state to LOCAL disk (per-rank npz), and the
            # agreement protocol reconciles divergent inventories at
            # resume.  Valid only for fully-addressable leaves — a
            # cross-process global array cannot materialize here.
            for leaf in jax.tree_util.tree_leaves(state):
                if hasattr(leaf, "is_fully_addressable") and \
                        not leaf.is_fully_addressable:
                    raise ValueError(
                        "use_orbax=False under multi-process requires "
                        "process-local (fully addressable) state; leaf "
                        f"with sharding {leaf.sharding} spans processes "
                        "— use the orbax tier for global arrays"
                    )
            # no pre-delete: _save_np writes to a tmp dir and atomically
            # renames over target, so the PREVIOUS snapshot stays
            # electable until the instant of the swap — a crash during
            # the write must not leave the step with no snapshot at all
            self._save_np(target, state)
            self._gc_local()
            return
        if self._multiproc:
            if self._is_chief and os.path.exists(target):
                shutil.rmtree(target)
            self._comm.barrier()
            self._orbax().save(os.path.abspath(target), state)
            # world manifest (elastic restart contract): chief-written
            # SIBLING file — the orbax dir's contents belong to orbax.
            # Atomic (tmp + rename); for async saves it may precede the
            # data commit, which is safe: the step is only electable
            # once the directory itself exists.
            if self._is_chief:
                _elastic.write_manifest(
                    _elastic.world_manifest(self._comm),
                    _elastic.manifest_sibling(target),
                )
            if not self._use_async:
                self._comm.barrier()
        else:
            if os.path.exists(target):
                shutil.rmtree(target)
            if self._use_orbax:
                try:
                    self._orbax().save(os.path.abspath(target), state)
                    _elastic.write_manifest(
                        _elastic.world_manifest(self._comm),
                        _elastic.manifest_sibling(target),
                    )
                except Exception:
                    if self._use_async:
                        raise  # async failures must not silently degrade
                    # Degraded single-controller path; see _save_np.
                    self._save_np(target, state)
            else:
                self._save_np(target, state)
        self._gc()

    def _save_np(self, target: str, state) -> None:
        """Degraded (orbax-less) backend.

        Must satisfy the same contract as the orbax path: ``resume`` returns
        the *original pytree structure* so ``restore_trainer`` can index
        ``state["params"]`` etc.  Leaves are stored as indexed npz entries
        and the treedef is pickled alongside (treedefs of standard
        containers and NamedTuples pickle fine).  Leaves are materialized
        via ``np.asarray`` (process-local state only).

        Commit is ATOMIC (tmp dir + rename), matching what
        ``_is_complete`` assumes: a rank killed mid-write leaves only a
        tmp dir the step scan ignores, so the agreement protocol can
        never elect a torn snapshot.
        """
        import glob as _glob

        tmp = f"{target}.tmp{os.getpid()}"
        # glob.escape: a checkpoint path containing [ ? * is legal and
        # must not silently skip the stale-dir sweep
        for stale in sorted(_glob.glob(f"{_glob.escape(target)}.tmp*")):
            shutil.rmtree(stale, ignore_errors=True)  # crashed saves
        os.makedirs(tmp)
        leaves, treedef = jax.tree_util.tree_flatten(state)
        np.savez(
            os.path.join(tmp, "state.npz"),
            **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)},
        )
        with open(os.path.join(tmp, "treedef.pkl"), "wb") as f:
            pickle.dump(treedef, f)
        # world manifest + per-file integrity digests, written INSIDE the
        # tmp dir so manifest and payload commit in the same rename: a
        # snapshot that later fails digest verification (torn write,
        # bit rot) is excluded from the inventory by _is_complete and
        # the agreement degrades to the previous step.
        _elastic.write_manifest(
            _elastic.world_manifest(
                self._comm, files=_elastic.file_digests(tmp)
            ),
            os.path.join(tmp, _elastic.MANIFEST_NAME),
        )
        # os.rename cannot replace a non-empty dir, so an existing
        # target (a re-save, or a failed orbax attempt's droppings) is
        # renamed ASIDE first.  The old snapshot survives until the new
        # one is fully written; the residual risk is a kill in the
        # instants BETWEEN the two renames, which loses only this
        # step's snapshot — the agreement protocol then resumes one
        # step earlier, which is safe.  Stale .old/.tmp dirs from
        # crashed saves are invisible to the step scan (the regex
        # matches step_<digits> exactly) and are swept here on the next
        # save of the same step, so they cannot accumulate or make the
        # rename-aside fail with ENOTEMPTY.
        old = f"{target}.old{os.getpid()}"
        for stale in sorted(_glob.glob(f"{_glob.escape(target)}.old*")):
            shutil.rmtree(stale, ignore_errors=True)
        if os.path.exists(target):
            os.rename(target, old)
        os.rename(tmp, target)
        shutil.rmtree(old, ignore_errors=True)

    # -- agreement + resume --------------------------------------------
    def newest_common_step(self) -> Optional[int]:
        """The newest step every process has on disk (parity: the allgather
        of snapshot inventories + max-common computation).

        The inventory exchange rides the SAME lockstep retry as
        ``comm_wire.plan_agreement`` / ``analysis.trace_agreement``: a
        transient obj-store fault or a torn payload during resume is
        observed by every process (each one unpickles each rank's
        payload), so all ranks fail — and re-exchange — together instead
        of desynchronizing the agreement.
        """
        from ..resilience.retry import lockstep_allgather

        with _obs.span("checkpoint.agreement"):
            local = self._available_steps()
            inventories = lockstep_allgather(
                self._comm, local,
                site="checkpoint.newest_common_step",
            )
            common = set(inventories[0])
            for inv in inventories[1:]:
                common &= set(inv)
            return max(common) if common else None

    def resume(self, like: Optional[Dict[str, Any]] = None):
        """Load the newest common snapshot; returns (step, state) or
        (None, None) when no checkpoint exists.

        Elastic restart: when the elected snapshot's world manifest
        names a DIFFERENT world size than this communicator spans, the
        load routes through the checkpoint resharder
        (``resilience.elastic.reshard_state``, template-driven by
        ``like``) instead of failing — ZeRO blocks re-partition, per-rank
        residuals drop, world-size-independent leaves survive.  A
        mismatch with no ``like`` template raises
        ``WorldResizeRequiredError`` (resharding needs the new world's
        freshly initialized state to re-partition onto).
        ``self.last_resize`` records ``(old_world, new_world)`` when the
        route was taken.
        """
        with _obs.span("checkpoint.resume"):
            return self._resume(like)

    def _resume(self, like: Optional[Dict[str, Any]] = None):
        self.wait_until_finished()  # async: the in-flight save counts
        self.last_resize = None
        self.last_manifest = None
        step = self.newest_common_step()
        if step is None:
            return None, None
        target = self._step_dir(step)
        manifest = _elastic.read_world_manifest(target)
        self.last_manifest = manifest
        old_world = (manifest or {}).get("world_size")
        resize = old_world is not None and int(old_world) != int(
            self._comm.size
        )
        npz = os.path.join(target, "state.npz")
        if os.path.exists(npz):
            treedef_path = os.path.join(target, "treedef.pkl")
            if not os.path.exists(treedef_path):
                raise RuntimeError(
                    f"checkpoint {target} uses the pre-0.2 flattened npz "
                    "format (no treedef.pkl); its tree structure cannot "
                    "be reconstructed — re-save with the current version"
                )
            data = np.load(npz, allow_pickle=True)
            with open(treedef_path, "rb") as f:
                treedef = pickle.load(f)
            leaves = [data[f"leaf_{i}"] for i in range(treedef.num_leaves)]
            leaves = [l[()] if l.ndim == 0 and l.dtype == object else l
                      for l in leaves]
            state = jax.tree_util.tree_unflatten(treedef, leaves)
            if resize:
                state = self._reshard(state, like, old_world, step)
            return step, state
        if resize:
            # World mismatch: the template's shapes (and shardings)
            # belong to the NEW world, so orbax must restore the SAVED
            # shapes as host arrays; the resharder's walk tolerates the
            # raw string-keyed-dict spelling of the saved structure.
            state = self._restore_raw_host(target)
            state = self._reshard(state, like, old_world, step)
            return step, state
        restore_kwargs = {}
        if like is not None:
            try:
                # Restore each leaf directly onto its devices with the
                # template's sharding (mesh-sharded TP kernels / expert
                # blocks / ZeRO state land sharded, no host round-trip).
                import orbax.checkpoint as ocp

                restore_kwargs["restore_args"] = (
                    ocp.checkpoint_utils.construct_restore_args(like)
                )
            except Exception as e:
                # Non-array template leaves (or an orbax API change):
                # restore still works via orbax defaults, but sharded
                # leaves then land replicated — say so rather than
                # silently degrading a large-model restore.
                import warnings

                warnings.warn(
                    "could not build sharded restore args from the "
                    f"template ({type(e).__name__}: {e}); restoring "
                    "with orbax defaults (leaves may come back "
                    "host-replicated — re-place with step.place)"
                )
        state = self._orbax().restore(
            os.path.abspath(target), item=like, **restore_kwargs
        )
        return step, state

    def _restore_raw_host(self, target: str):
        """Restore an orbax snapshot in its SAVED shapes as host numpy
        arrays (no template): the elastic path's loader — a
        world-mismatched snapshot cannot restore onto the new world's
        template shapes/shardings, and arrays saved from sharded
        ``jax.Array`` leaves need an explicit numpy restore type."""
        import orbax.checkpoint as ocp

        path = os.path.abspath(target)
        ckptr = self._orbax()
        try:
            meta = ckptr.metadata(path)

            def arg_of(m):
                # true zarr-backed arrays must restore as numpy (the
                # default would rebuild jax.Arrays and demand the dead
                # world's shardings); scalar/string leaves live in the
                # aggregate file and must keep the default restore —
                # forcing np.ndarray makes orbax look for a zarr entry
                # that does not exist
                if type(m).__name__ == "ArrayMetadata":
                    return ocp.RestoreArgs(restore_type=np.ndarray)
                return ocp.RestoreArgs()

            restore_args = jax.tree_util.tree_map(arg_of, meta)
            return ckptr.restore(path, restore_args=restore_args)
        except Exception:
            # older orbax without metadata()/RestoreArgs spelling
            return ckptr.restore(path)

    def _reshard(self, state, like, old_world, step: int):
        """Route a world-mismatched snapshot through the elastic
        resharder (see :meth:`resume`)."""
        from ..resilience.errors import WorldResizeRequiredError

        new_world = int(self._comm.size)
        old_world = int(old_world)
        if like is None:
            raise WorldResizeRequiredError(
                f"checkpoint step {step} was written at world size "
                f"{old_world} but this world spans {new_world} chips; "
                "resharding needs a template of the new world's state — "
                "call resume(like=...) with freshly initialized "
                "params/opt_state (restore_trainer passes the trainer's "
                "own), or restart via Trainer.run_elastic",
                site="checkpoint.resume",
            )
        with _obs.span("checkpoint.reshard", step=int(step),
                       old_world=old_world, new_world=new_world):
            state = _elastic.reshard_state(
                state, like, old_world, new_world, label=f"step_{step}"
            )
        self.last_resize = (old_world, new_world)
        _emit(
            "elastic_resume", "checkpoint.resume",
            step=step, old_world=old_world, new_world=new_world,
        )
        return state

    def _rm_step(self, step: int) -> None:
        """Delete one snapshot AND its sibling world manifest (the npz
        tier's manifest lives inside the dir and goes with it)."""
        target = self._step_dir(step)
        shutil.rmtree(target, ignore_errors=True)
        try:
            os.remove(_elastic.manifest_sibling(target))
        except OSError:
            pass
        self._verified.pop(target, None)

    def _gc_local(self) -> None:
        """GC for the per-rank local-disk tier: every process owns its
        own directory, so deletion is local and barrier-free (a barrier
        here would turn one dead rank into a hang for all)."""
        steps = self._available_steps()
        for s in steps[: -self._keep] if self._keep else []:
            self._rm_step(s)

    def _gc(self) -> None:
        if self._multiproc and not self._use_orbax:
            self._gc_local()
            return
        if self._multiproc:
            # shared-FS deletes are chief-only; peers wait so a stale dir
            # never reappears in a subsequent scan
            if self._is_chief:
                steps = self._available_steps()
                for s in steps[: -self._keep] if self._keep else []:
                    self._rm_step(s)
            self._comm.barrier()
            return
        steps = self._available_steps()
        for s in steps[: -self._keep] if self._keep else []:
            self._rm_step(s)

    def finalize(self, trainer=None) -> None:
        """Parity: the reference's finalize/GC of stale snapshots (plus,
        async tier: drain the in-flight save so process exit cannot
        truncate a snapshot)."""
        self.wait_until_finished()
        self._gc()

    # -- trainer-extension protocol ------------------------------------
    def __call__(self, trainer) -> None:
        state = {
            "params": trainer.updater.params,
            "opt_state": trainer.updater.opt_state,
            "trainer": trainer.state_dict(),
        }
        self.save(trainer.iteration, state)

    def restore_trainer(self, trainer) -> Optional[int]:
        step, state = self.resume(
            like={
                "params": trainer.updater.params,
                "opt_state": trainer.updater.opt_state,
                "trainer": trainer.state_dict(),
            }
        )
        if step is None:
            return None
        if self.last_resize:
            # Iterator cursors are per-PROCESS state (each controller
            # feeds its own dataset shard), so they re-map by PROCESS
            # count: pos rescaled onto the new shard width, order
            # redrawn from the restored RNG.  An UNCHANGED process
            # count (chips-per-process resize, or single-controller
            # global batches) leaves the shard width — and therefore
            # the cursor and the in-flight permutation — exactly valid,
            # so they survive untouched.
            old_pc = int((self.last_manifest or {}).get(
                "process_count"
            ) or 1)
            new_pc = int(self._comm.process_count)
            tr = state.get("trainer")
            if old_pc != new_pc and isinstance(
                tr, dict
            ) and isinstance(tr.get("iterator"), dict):
                tr["iterator"] = _elastic.reshard_iterator_state(
                    tr["iterator"], old_pc, new_pc
                )
            # the resharded leaves are host arrays; lay them out onto
            # the NEW world's mesh per the step's own placement rules
            # (ZeRO state shards land sharded, params replicate)
            place = getattr(trainer.updater.step_fn, "place", None)
            if place is not None:
                state["params"], state["opt_state"] = place(
                    state["params"], state["opt_state"]
                )
        trainer.updater.params = state["params"]
        trainer.updater.opt_state = state["opt_state"]
        trainer.load_state_dict(state["trainer"])
        return step


def create_multi_node_checkpointer(name: str, comm, path: str = "checkpoints",
                                   trigger=(1, "epoch"), keep: int = 3,
                                   **kw) -> _MultiNodeCheckpointer:
    """Parity: ``chainermn.create_multi_node_checkpointer(name, comm)``."""
    return _MultiNodeCheckpointer(name, comm, path=path, trigger=trigger,
                                  keep=keep, **kw)

"""Transformer language model, TPU-first and sequence-parallel-native.

The reference has no transformer (2017-era RNN/CNN zoo); this is the
model family its modern successors need, built directly on the
framework's sequence-parallel layer (SURVEY.md section 5.7: ring/Ulysses
over the reference's p2p/alltoall primitives).

Design:
* One module, two execution regimes.  With ``seq_axis=None`` it is an
  ordinary single-device causal LM.  Called inside ``shard_map`` with the
  token sequence sharded over ``seq_axis``, the SAME module becomes
  sequence-parallel: positional embeddings use global positions (axis
  index offset) and attention runs :func:`parallel.ring_attention` (or
  :func:`parallel.ulysses_attention` with ``sp_impl="ulysses"``) over
  the axis — everything else (LN, MLPs, embeddings) is position-local and
  needs no communication.
* ``attention_fn`` hook: the single-device core (default
  ``ops.multi_head_attention``; pass ``ops.flash_attention_fn()`` for the
  Pallas kernel).
* bfloat16 compute, fp32 params, fp32 LayerNorm/softmax; logits fp32.
* Pre-LN blocks; weight-tied output head (standard, halves embed params).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax

# Communication goes through the audited wrappers — raw lax collectives
# outside the sanctioned comm modules are a lint error (analysis.lint).
from chainermn_tpu.functions import collectives as _cc


class MlpBlock(nn.Module):
    d_ff: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        h = nn.Dense(self.d_ff, dtype=self.dtype)(x)
        h = nn.gelu(h)
        return nn.Dense(d, dtype=self.dtype)(h)


# Data-parallel mesh axis names this package's communicators bind
# (variants.py mesh factorizations).  Dropout folds bound ones into its
# rng so data shards draw independent masks.
_DATA_AXES = ("mn", "mn_data", "mn_inter", "mn_intra", "mn_x", "mn_y")


def _bound_axes(names, exclude=()):
    """The subset of ``names`` bound in the current trace (shard_map).
    ``lax.axis_index`` raises NameError on an unbound axis; anything
    else is a real error and propagates."""
    out = []
    for a in names:
        if a in exclude or a is None:
            continue
        try:
            lax.axis_index(a)
        except NameError:
            continue
        out.append(a)
    return out


def _stream_dropout(module: nn.Module, h, rate: float,
                    deterministic: bool, seq_axis, tp_axis=None):
    """Inverted dropout for the residual stream.  The 'dropout' rng
    collection is replicated across the mesh, so every *token-splitting*
    shard index is folded in — the sequence shard (SP) and any bound
    data axis (DP) — giving each shard an independent mask instead of
    one pattern correlated across the global batch.  The tensor axis is
    deliberately NOT folded: across TP shards the residual stream is
    replicated and the masks must agree."""
    if rate <= 0.0 or deterministic:
        return h
    rng = module.make_rng("dropout")
    fold = _bound_axes(_DATA_AXES, exclude=(tp_axis, seq_axis))
    if seq_axis is not None:
        fold.append(seq_axis)
    for a in fold:
        rng = jax.random.fold_in(rng, lax.axis_index(a))
    keep = jax.random.bernoulli(rng, 1.0 - rate, h.shape)
    return jnp.where(keep, h / (1.0 - rate), 0).astype(h.dtype)


class SelfAttention(nn.Module):
    """Causal self-attention; optionally tensor-parallel over ``tp_axis``
    (heads sharded Megatron-style: column-parallel q/k/v projections, one
    row-parallel psum on the output projection) and/or sequence-parallel
    over ``seq_axis``.  The two compose: each chip then holds its head
    shard of its sequence shard.

    ``sp_impl`` picks the sequence-parallel algorithm: ``"ring"``
    (ppermute K/V rotation — any head count, O(seq/chips) memory) or
    ``"ulysses"`` (two all_to_alls exchanging sequence- for
    head-sharding; local heads must divide by the seq-axis size, bulk
    ICI transposes instead of n ring hops).  Ulysses runs
    ``attention_fn`` on its gathered blocks (pass the flash kernel);
    ring uses its own flash tier automatically on TPU."""

    n_heads: int
    dtype: Any = jnp.bfloat16
    seq_axis: Optional[str] = None
    tp_axis: Optional[str] = None
    sp_impl: str = "ring"
    # KV-cache decode mode: keys/values accumulate in 'cache' variables
    # of length cache_len; each call appends its s positions and attends
    # against everything cached so far.  Causal only; composes with
    # tp_axis (head-sharded caches) but not seq_axis.
    decode: bool = False
    cache_len: int = 0
    attention_fn: Optional[Callable] = None

    def _decode_attend(self, q, k, v, b, heads, dh, scale):
        """Append k/v to the cache and attend q against the filled
        prefix — exact causal attention at O(cache_len) per step.

        The dtype flow mirrors ``ops.multi_head_attention`` exactly
        (caches in compute dtype, QK einsum in compute dtype then fp32
        softmax, probs cast back for the PV einsum) so the KV-cache and
        recompute generate tiers stay token-for-token identical for
        bf16 models too."""
        ck = self.variable(
            "cache", "cached_key", jnp.zeros,
            (b, self.cache_len, heads, dh), q.dtype,
        )
        cv = self.variable(
            "cache", "cached_value", jnp.zeros,
            (b, self.cache_len, heads, dh), q.dtype,
        )
        ci = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        idx = ci.value
        ck.value = lax.dynamic_update_slice(
            ck.value, k.astype(q.dtype), (0, idx, 0, 0)
        )
        cv.value = lax.dynamic_update_slice(
            cv.value, v.astype(q.dtype), (0, idx, 0, 0)
        )
        s = q.shape[1]
        ci.value = idx + s
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, ck.value
        ).astype(jnp.float32) * scale
        kpos = jnp.arange(self.cache_len)[None, :]
        qpos = idx + jnp.arange(s)[:, None]
        mask = kpos <= qpos  # causal AND only-written positions
        scores = jnp.where(
            mask[None, None], scores, jnp.finfo(jnp.float32).min
        )
        # Overflowing the cache would otherwise be silently clamped by
        # dynamic_update_slice (the failure the static max_len guard
        # prevents in training mode) — poison the logits loudly instead.
        scores = jnp.where(idx + s > self.cache_len, jnp.nan, scores)
        p = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        return jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(q.dtype), cv.value
        )

    @nn.compact
    def __call__(self, x, *, causal: bool = True):
        b, s, d = x.shape
        if d % self.n_heads:
            raise ValueError(f"d_model ({d}) % n_heads ({self.n_heads})")
        dh = d // self.n_heads
        heads = self.n_heads
        if self.tp_axis is not None:
            from chainermn_tpu.parallel import (
                ColumnParallelDense,
                RowParallelDense,
            )

            ntp = lax.axis_size(self.tp_axis)
            if heads % ntp:
                raise ValueError(
                    f"n_heads ({heads}) not divisible by the "
                    f"'{self.tp_axis}' axis size ({ntp})"
                )
            heads = heads // ntp  # local heads
            # Auto-generated module names (ColumnParallelDense_0/1/2 =
            # q/k/v) keep the param tree spec-derivable without name
            # markers that could collide with user modules.
            col = functools.partial(
                ColumnParallelDense, axis_name=self.tp_axis,
                use_bias=False, dtype=self.dtype,
            )
            q = col(d)(x)
            k = col(d)(x)
            v = col(d)(x)
        else:
            qkv = nn.Dense(3 * d, use_bias=False, dtype=self.dtype)(x)
            q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, heads, dh)
        k = k.reshape(b, s, heads, dh)
        v = v.reshape(b, s, heads, dh)
        if self.decode:
            if self.seq_axis is not None:
                raise ValueError(
                    "decode mode does not compose with sequence "
                    "parallelism (a decoded token needs its whole cache)"
                )
            if not causal:
                raise ValueError("decode mode implies causal attention")
            if self.cache_len <= 0:
                raise ValueError("decode mode needs cache_len > 0")
            # tp_axis composes: q/k/v hold this chip's LOCAL heads, the
            # cache shards with them, and the row-parallel output
            # projection below carries the one psum per step.
            out = self._decode_attend(q, k, v, b, heads, dh, dh**-0.5)
        elif self.seq_axis is not None:
            if self.sp_impl == "ring":
                from chainermn_tpu.parallel import ring_attention

                out = ring_attention(q, k, v, self.seq_axis, causal=causal)
            elif self.sp_impl == "ulysses":
                from chainermn_tpu.parallel import ulysses_attention

                out = ulysses_attention(
                    q, k, v, self.seq_axis, causal=causal,
                    attention_fn=self.attention_fn,
                )
            else:
                raise ValueError(
                    f"sp_impl must be 'ring' or 'ulysses', got "
                    f"{self.sp_impl!r}"
                )
        elif self.attention_fn is not None:
            out = self.attention_fn(q, k, v, causal, dh**-0.5)
        else:
            from chainermn_tpu.ops import multi_head_attention

            out = multi_head_attention(q, k, v, causal=causal)
        out = out.reshape(b, s, heads * dh)
        if self.tp_axis is not None:
            return RowParallelDense(
                d, axis_name=self.tp_axis, use_bias=False,
                dtype=self.dtype,
            )(out)
        return nn.Dense(d, use_bias=False, dtype=self.dtype)(out)


class TpMlpBlock(nn.Module):
    """Megatron MLP: column-parallel up-projection -> gelu ->
    row-parallel down-projection — exactly one psum per block."""

    d_ff: int
    tp_axis: str = "mn_model"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        from chainermn_tpu.parallel import (
            ColumnParallelDense,
            RowParallelDense,
        )

        d = x.shape[-1]
        h = ColumnParallelDense(
            self.d_ff, axis_name=self.tp_axis, dtype=self.dtype,
        )(x)
        h = nn.gelu(h)
        return RowParallelDense(
            d, axis_name=self.tp_axis, dtype=self.dtype,
        )(h)


class TransformerBlock(nn.Module):
    n_heads: int
    d_ff: int
    dtype: Any = jnp.bfloat16
    seq_axis: Optional[str] = None
    tp_axis: Optional[str] = None
    sp_impl: str = "ring"
    decode: bool = False
    cache_len: int = 0
    dropout_rate: float = 0.0
    deterministic: bool = False
    attention_fn: Optional[Callable] = None
    # fp32 LayerNorm is the numerics-safe default; bf16 exists as a
    # measured perf knob (benchmarks/transformer_mfu.py `ln_bf16` rung)
    ln_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        ln = lambda: nn.LayerNorm(dtype=self.ln_dtype)

        def drop(h):
            return _stream_dropout(
                self, h, self.dropout_rate, self.deterministic,
                self.seq_axis, self.tp_axis,
            )

        x = x + drop(SelfAttention(
            self.n_heads, dtype=self.dtype, seq_axis=self.seq_axis,
            tp_axis=self.tp_axis, sp_impl=self.sp_impl,
            decode=self.decode, cache_len=self.cache_len,
            attention_fn=self.attention_fn,
        )(ln()(x).astype(self.dtype)))
        if self.tp_axis is not None:
            mlp = TpMlpBlock(self.d_ff, tp_axis=self.tp_axis,
                             dtype=self.dtype)
        else:
            mlp = MlpBlock(self.d_ff, dtype=self.dtype)
        x = x + drop(mlp(ln()(x).astype(self.dtype)))
        return x


def make_lm_embed(parent: nn.Module, vocab_size: int, d_model: int,
                  tp_axis, vocab_parallel: bool):
    """The embedding module both LM families construct: dense
    ``nn.Embed`` (named "embed") or, with ``vocab_parallel=True``, a
    :class:`~chainermn_tpu.parallel.VocabParallelEmbed` sharded over
    ``tp_axis`` (auto-named so the class marker stays in the flax path
    for spec derivation).  Must be called from inside ``parent``'s
    compact ``__call__`` (the submodule registers on ``parent``)."""
    del parent  # registration happens via the nn.compact caller's scope
    if vocab_parallel:
        if tp_axis is None:
            raise ValueError(
                "vocab_parallel=True requires tp_axis (the vocab "
                "shards over the model axis)"
            )
        from chainermn_tpu.parallel import VocabParallelEmbed

        return VocabParallelEmbed(
            vocab_size, d_model, axis_name=tp_axis, dtype=jnp.float32,
        )
    return nn.Embed(
        vocab_size, d_model,
        embedding_init=nn.initializers.normal(0.02),
        dtype=jnp.float32, name="embed",
    )


class TransformerLM(nn.Module):
    """Causal LM: tokens (batch, seq) -> logits (batch, seq, vocab).

    Inside ``shard_map`` with tokens sequence-sharded over ``seq_axis``,
    the returned logits are the local sequence shard's logits (global
    positions preserved).
    """

    vocab_size: int
    d_model: int = 256
    n_heads: int = 8
    n_layers: int = 4
    d_ff: Optional[int] = None
    max_len: int = 2048
    dtype: Any = jnp.bfloat16
    seq_axis: Optional[str] = None
    tp_axis: Optional[str] = None
    sp_impl: str = "ring"
    # KV-cache decode: see SelfAttention.decode; generate(use_cache=True)
    # builds the decode-mode twin automatically, sizing cache_len to the
    # actual generation length (0 = default to max_len).
    decode: bool = False
    cache_len: int = 0
    # Residual-stream dropout (attention out, MLP out, token
    # embeddings) — applied identically on the TP and non-TP paths, with
    # per-shard independent masks under SP; 0.0 draws no rng.  Construct
    # an eval twin with deterministic=True to switch it off (generate()
    # does this automatically).
    dropout_rate: float = 0.0
    deterministic: bool = False
    # Shard the embedding table AND the tied output head over tp_axis
    # (Megatron VocabParallelEmbedding): logits come back as the LOCAL
    # vocab block — train with vp_lm_loss, which assembles the softmax
    # statistics with collectives instead of materializing (.., V) rows.
    vocab_parallel: bool = False
    # Return the post-LayerNorm hidden states (b, s, d) instead of
    # logits: the chunked fused linear+CE loss
    # (ops.chunked_lm_loss) applies the weight-tied head itself, one
    # vocab chunk at a time, so the (b, s, V) logits never materialize.
    return_hidden: bool = False
    attention_fn: Optional[Callable] = None
    # fp32 LayerNorm is the numerics-safe default; bf16 is a measured
    # perf knob (benchmarks/transformer_mfu.py `ln_bf16` rung)
    ln_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens):
        b, s = tokens.shape
        d_ff = self.d_ff or 4 * self.d_model
        embed = make_lm_embed(
            self, self.vocab_size, self.d_model, self.tp_axis,
            self.vocab_parallel,
        )
        pos_table = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (self.max_len, self.d_model), jnp.float32,
        )
        # dynamic_slice clamps out-of-range starts, which would silently
        # reuse positional rows — guard statically instead (shapes and axis
        # sizes are static under jit).
        offset = 0
        if self.seq_axis is not None:
            n_shards = lax.axis_size(self.seq_axis)
            if n_shards * s > self.max_len:
                raise ValueError(
                    f"global sequence length {n_shards}*{s} exceeds "
                    f"max_len={self.max_len}; raise max_len"
                )
            # Global positions: shard r holds [r*s, (r+1)*s).
            offset = lax.axis_index(self.seq_axis) * s
        elif s > self.max_len:
            raise ValueError(
                f"sequence length {s} exceeds max_len={self.max_len}; "
                "raise max_len"
            )
        if self.decode:
            # global position of this call's first token = tokens cached
            # so far (a dedicated counter so the embedding stays in sync
            # with the attention caches)
            pos_idx = self.variable(
                "cache", "pos_index", lambda: jnp.zeros((), jnp.int32)
            )
            offset = pos_idx.value
            pos_idx.value = offset + s
        pos = lax.dynamic_slice_in_dim(pos_table, offset, s, axis=0)

        x = (embed(tokens) + pos[None]).astype(self.dtype)
        x = _stream_dropout(
            self, x, self.dropout_rate, self.deterministic, self.seq_axis,
            self.tp_axis,
        )
        for _ in range(self.n_layers):
            x = TransformerBlock(
                self.n_heads, d_ff, dtype=self.dtype,
                seq_axis=self.seq_axis, tp_axis=self.tp_axis,
                sp_impl=self.sp_impl, decode=self.decode,
                cache_len=self.cache_len or self.max_len,
                dropout_rate=self.dropout_rate,
                deterministic=self.deterministic,
                attention_fn=self.attention_fn,
                ln_dtype=self.ln_dtype,
            )(x)
        x = nn.LayerNorm(dtype=self.ln_dtype)(x)
        if self.return_hidden:
            return x.astype(jnp.float32)
        # Weight-tied head.
        if self.vocab_parallel:
            return embed.attend(x.astype(jnp.float32))  # local vocab block
        logits = x.astype(jnp.float32) @ embed.embedding.T
        return logits


def lm_loss(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Next-token cross entropy over a (batch, seq) token block."""
    import optax

    targets = tokens[:, 1:]
    preds = logits[:, :-1]
    return optax.softmax_cross_entropy_with_integer_labels(
        preds, targets
    ).mean()


def _sp_targets(tokens: jnp.ndarray, axis_name: str):
    """The shard-boundary protocol shared by the sequence-parallel
    losses: each shard's last position predicts the NEXT shard's first
    token (targets cross the boundary via ``ppermute`` — the
    differentiable p2p layer the reference's send/recv points at), and
    the final *global* position has no target.  Returns
    ``(targets (b, s), valid (1, s) float mask)``."""
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, s = tokens.shape
    nxt = _cc.ppermute(
        tokens[:, :1], axis_name,
        [((i + 1) % n, i) for i in range(n)],
    )
    targets = jnp.concatenate([tokens[:, 1:], nxt], axis=1)
    # mask the last global position (wrapped target is shard 0's BOS)
    global_pos = me * s + jnp.arange(s)[None, :]
    valid = (global_pos < n * s - 1).astype(jnp.float32)
    return targets, valid


def _sp_masked_mean(ce: jnp.ndarray, valid: jnp.ndarray,
                    axis_name: str) -> jnp.ndarray:
    valid = jnp.broadcast_to(valid.astype(ce.dtype), ce.shape)
    total = _cc.psum(jnp.sum(ce * valid), axis_name)
    count = _cc.psum(jnp.sum(valid), axis_name)
    return total / count


def sp_lm_loss(logits: jnp.ndarray, tokens: jnp.ndarray,
               axis_name: str) -> jnp.ndarray:
    """Next-token cross entropy for a sequence-sharded block
    (boundary-crossing targets per :func:`_sp_targets`).  Returns the
    global mean (psum-reduced), identical on every shard."""
    import optax

    targets, valid = _sp_targets(tokens, axis_name)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    return _sp_masked_mean(ce, valid, axis_name)


def vp_lm_loss(logits_local: jnp.ndarray, tokens: jnp.ndarray,
               model_axis: str,
               seq_axis: Optional[str] = None) -> jnp.ndarray:
    """Next-token cross entropy from vocab-sharded logits
    (``TransformerLM(vocab_parallel=True)``): per-position CE is
    assembled by :func:`~chainermn_tpu.parallel.vocab_parallel_cross_entropy`
    (one pmax + two psums over ``model_axis`` — no full-vocab row), with
    the same boundary-crossing targets as :func:`sp_lm_loss` when the
    sequence is also sharded over ``seq_axis``."""
    from chainermn_tpu.parallel import vocab_parallel_cross_entropy

    if seq_axis is not None:
        targets, valid = _sp_targets(tokens, seq_axis)
        ce = vocab_parallel_cross_entropy(
            logits_local, targets, model_axis
        )
        return _sp_masked_mean(ce, valid, seq_axis)
    ce = vocab_parallel_cross_entropy(
        logits_local[:, :-1], tokens[:, 1:], model_axis
    )
    return ce.mean()


def generate(model: TransformerLM, params, prompt: jnp.ndarray,
             max_new_tokens: int, *, temperature: float = 0.0,
             rng=None, use_cache: Optional[bool] = None,
             comm=None, param_specs=None) -> jnp.ndarray:
    """Autoregressive sampling from a (dense, single-device) LM.

    Greedy when ``temperature == 0``, else softmax sampling at the given
    temperature.  Two tiers, numerically identical (pinned by test):

    * ``use_cache=True`` (default for cache-capable models): the model's
      decode-mode twin prefills the prompt once, then each new token
      attends against the KV cache — O(max_len) per token.
    * ``use_cache=False``: one jitted ``fori_loop`` re-running the
      causal forward on a statically padded buffer each step — positions
      past the frontier cannot influence earlier logits, so the
      recompute is exact.  Works for ANY logits-or-(logits, aux) model;
      for capacity-routed MoE models (e.g. dense-mode
      ``MoeTransformerLM``) the twin's expert capacity is raised to the
      no-drop bound so a pad token's route can never evict a real
      token's (see :func:`_recompute_twin`).  An explicitly pinned
      ``model.capacity`` is therefore *not honored* during generation —
      a ``UserWarning`` is emitted when one gets raised.

    Both compiled loops are cached per (model config, shapes,
    temperature).  Tensor-parallel models sample natively: pass ``comm``
    (whose mesh binds ``model.tp_axis``) and ``param_specs`` — the whole
    loop then runs in one ``shard_map`` with head-sharded KV caches and
    a row-parallel psum per decoded token.  Vocab-parallel models
    (``vocab_parallel=True``) sample natively too: the embedding/tied
    head stay vocab-sharded and only the frontier logits row is
    all-gathered per decoded token (b x V floats — never the
    (b, s, V) tensor), making tokens identical to the dense head's.
    Sequence-parallel is training-only; materialize a ``seq_axis=None``
    model (same param tree) to sample.

    Args:
      prompt: (batch, prompt_len) int32 token ids.
      max_new_tokens: tokens to append; ``prompt_len + max_new_tokens``
        must fit ``model.max_len``.
      rng: PRNGKey, required when ``temperature > 0``.
      use_cache: ``None`` auto-selects (cache when the model supports
        decode mode and runs single-device dense).
    Returns:
      (batch, prompt_len + max_new_tokens) tokens, prompt included.
    """
    b, s0 = prompt.shape
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got "
                         f"{max_new_tokens}")
    if max_new_tokens == 0:
        return prompt
    total = s0 + max_new_tokens
    if total > model.max_len:
        raise ValueError(
            f"prompt_len + max_new_tokens = {total} exceeds "
            f"max_len={model.max_len}"
        )
    if temperature > 0 and rng is None:
        raise ValueError("temperature > 0 needs an rng key")
    if rng is None:
        rng = jax.random.PRNGKey(0)  # unused in greedy mode
    tp_axis = getattr(model, "tp_axis", None)
    vocab_parallel = getattr(model, "vocab_parallel", False)
    if getattr(model, "seq_axis", None) is not None:
        raise ValueError(
            "generate() samples from dense (optionally tensor-/vocab-"
            "parallel) models; construct one with seq_axis=None (the "
            "param tree is compatible)"
        )
    if tp_axis is not None and (comm is None or param_specs is None):
        raise ValueError(
            "a tensor-parallel model generates under its mesh: pass "
            "comm= (whose mesh binds the tp axis) and param_specs= "
            "(the parameter PartitionSpec tree, e.g. "
            "megatron_param_specs/moe_param_specs)"
        )
    # vocab_parallel implies tp_axis (enforced at model construction),
    # so the TP-tier requirements above already hold; sampling gathers
    # only the frontier logits row per token (_full_vocab).
    vp_axis = tp_axis if vocab_parallel else None
    if use_cache is None:
        use_cache = _has_decode_field(model)
    if use_cache:
        loop = _cached_decode_loop(
            _decode_twin(model, total, batch=b), s0, max_new_tokens,
            float(temperature), vp_axis=vp_axis,
        )
        run, args = loop, (params, prompt, rng)
    else:
        buf0 = jnp.zeros((b, total), jnp.int32)
        buf0 = lax.dynamic_update_slice(buf0, prompt, (0, 0))
        loop = _generate_loop(
            _recompute_twin(model, b, total), s0, max_new_tokens,
            float(temperature), vp_axis=vp_axis,
        )
        run = lambda p, buf, key: loop(p, buf, key)[0]
        args = (params, buf0, rng)
    if tp_axis is None:
        return run(*args)
    # TP tier: the whole sampling loop runs inside one shard_map over
    # the communicator's mesh — head-sharded KV caches live as scan
    # carries within the body, the row-parallel projections carry one
    # psum per decoded token.  Tokens are replicated (P()) outputs.
    from jax.sharding import PartitionSpec as P

    sharded = jax.jit(
        jax.shard_map(
            run, mesh=comm.mesh,
            in_specs=(param_specs, P(), P()), out_specs=P(),
            check_vma=False,
        )
    )
    return sharded(*args)


def _has_decode_field(model) -> bool:
    import dataclasses

    try:
        return "decode" in {f.name for f in dataclasses.fields(model)}
    except TypeError:
        return False


def _eval_twin(model):
    """The same architecture with dropout off (``deterministic=True``
    where the field exists) — sampling must not apply training-time
    dropout, and the 'dropout' rng collection isn't threaded through
    the generation loops."""
    import dataclasses

    fields = {
        f.name: getattr(model, f.name)
        for f in dataclasses.fields(model)
        if f.name not in ("parent", "name")
    }
    if "deterministic" in fields:
        fields["deterministic"] = True
    return type(model)(**fields)


def _recompute_twin(model, batch: int, total: int):
    """Eval twin made exact for capacity-routed MoE models.

    The recompute tier runs the forward on a zero-padded (batch, total)
    buffer; positions past the frontier are routed by the MoE gate like
    real tokens, and with a finite per-expert capacity a pad token's
    route can claim a queue slot ahead of a real token's (route-major
    slot assignment), changing earlier logits.  Overriding capacity to
    the flattened token count makes drops impossible (an expert can be
    chosen by at most every token once, since top-k picks distinct
    experts), restoring the padding-invariance the tier's exactness
    claim rests on."""
    import dataclasses

    twin = _eval_twin(model)
    names = {f.name for f in dataclasses.fields(twin)}
    if "capacity" in names:
        fields = {
            f.name: getattr(twin, f.name)
            for f in dataclasses.fields(twin)
            if f.name not in ("parent", "name")
        }
        _warn_capacity_override(fields.get("capacity"), batch * total)
        # dense path: per-call no-drop capacity (cap = this call's token
        # count); EP path keeps the static prefill-sized bound
        fields["capacity"] = batch * total
        if "no_drop" in names:
            fields["no_drop"] = True
        twin = type(twin)(**fields)
    return twin


def _warn_capacity_override(pinned, no_drop: int) -> None:
    """Generation overrides a user-pinned MoE ``capacity`` with the
    no-drop bound (padding-exactness needs it), which means sampling
    routes tokens through a *less drop-constrained* model than the one
    trained.  Outputs stay deterministic and the two generate tiers
    agree with each other — but not necessarily with train-time routing,
    so say so rather than diverge silently."""
    if pinned is not None and pinned != no_drop:
        import warnings

        warnings.warn(
            f"generate(): model.capacity={pinned} is overridden to the "
            f"no-drop bound {no_drop} for padding-exact generation; "
            "sampled routing may differ from the capacity-constrained "
            "routing seen in training",
            stacklevel=3,
        )


def _decode_twin(model, cache_len: int, batch: Optional[int] = None):
    """The eval twin with ``decode=True`` and caches sized to the
    actual generation length (not max_len — a short sample from a
    long-context model shouldn't pay full-context attention per step);
    parameters are layout-identical.  Capacity-routed MoE models get the
    same no-drop capacity override as :func:`_recompute_twin` (prefill
    routes batch*prompt_len tokens at once; a drop there would desync
    the two generate tiers)."""
    import dataclasses

    if not _has_decode_field(model):
        raise ValueError(
            f"{type(model).__name__} has no decode mode; call "
            "generate(..., use_cache=False) for the recompute tier"
        )
    twin = _eval_twin(model)
    fields = {
        f.name: getattr(twin, f.name)
        for f in dataclasses.fields(twin)
        if f.name not in ("parent", "name")
    }
    fields["decode"] = True
    if "cache_len" in fields:
        fields["cache_len"] = cache_len
    if "capacity" in fields and batch is not None:
        _warn_capacity_override(fields.get("capacity"), batch * cache_len)
        # dense path: no_drop sizes each call's expert queues to its own
        # token count — the prefill routes batch*prompt tokens but each
        # decode step routes only batch, so queues shrink ~cache_len-fold
        fields["capacity"] = batch * cache_len
        if "no_drop" in fields:
            fields["no_drop"] = True
    return type(model)(**fields)


def _full_vocab(step_logits, vp_axis):
    """Vocab-parallel models emit the LOCAL vocab block; sampling needs
    the full row.  One tiled all_gather of the (b, V/n) frontier row —
    shard r holds global rows [r*V/n, (r+1)*V/n), so concatenation in
    axis order IS global vocab order and the downstream `_sample` is
    token-identical to the dense head's.  Only the sampled position is
    gathered (b x V floats per token), never the (b, s, V) tensor the
    vp training path exists to avoid."""
    if vp_axis is None:
        return step_logits
    return _cc.all_gather(step_logits, vp_axis, axis=-1, tiled=True)


def _sample(step_logits, key, temperature: float):
    """One sampling decision — shared by both generate tiers so their
    pinned numerical identity can't drift (same key-split order)."""
    if temperature > 0:
        key, sub = jax.random.split(key)
        return jax.random.categorical(
            sub, step_logits / temperature, axis=-1
        ).astype(jnp.int32), key
    return jnp.argmax(step_logits, axis=-1).astype(jnp.int32), key


@functools.lru_cache(maxsize=32)
def _cached_decode_loop(dmodel, s0: int, max_new_tokens: int,
                        temperature: float, vp_axis=None):
    """Compiled KV-cache sampling: prefill the prompt, then scan one
    token at a time against the caches."""

    def logits_of(out):
        # (logits, aux) models (MoeTransformerLM) vs plain logits
        return out[0] if isinstance(out, tuple) else out

    @jax.jit
    def run(params, prompt, key):
        out, mut = dmodel.apply(params, prompt, mutable=["cache"])
        cache = mut["cache"]
        nxt, key = _sample(
            _full_vocab(
                logits_of(out)[:, -1].astype(jnp.float32), vp_axis
            ), key, temperature
        )

        def body(carry, _):
            cache, tok, key = carry
            out, mut = dmodel.apply(
                {**params, "cache": cache}, tok[:, None],
                mutable=["cache"],
            )
            nxt, key = _sample(
                _full_vocab(
                    logits_of(out)[:, -1].astype(jnp.float32), vp_axis
                ), key, temperature
            )
            return (mut["cache"], nxt, key), nxt

        (_, _, key), rest = lax.scan(
            body, (cache, nxt, key), None, length=max_new_tokens - 1
        )
        new = jnp.concatenate(
            [nxt[:, None], jnp.moveaxis(rest, 0, 1)], axis=1
        ) if max_new_tokens > 1 else nxt[:, None]
        return jnp.concatenate([prompt, new], axis=1)

    return run


@functools.lru_cache(maxsize=32)
def _generate_loop(model, s0: int, max_new_tokens: int,
                   temperature: float, vp_axis=None):
    """Compiled sampling loop, cached per (model config, shapes,
    temperature) so repeated generate() calls reuse the executable
    (flax modules are frozen/hashable; a fresh jit per call would
    re-trace every time)."""

    @jax.jit
    def run(params, buf0, key):
        def body(i, carry):
            buf, key = carry
            out = model.apply(params, buf)
            logits = out[0] if isinstance(out, tuple) else out
            step_logits = lax.dynamic_index_in_dim(
                logits, s0 + i - 1, axis=1, keepdims=False
            )  # (b, V) at the frontier position ((b, V/n) under vp)
            nxt, key = _sample(
                _full_vocab(step_logits.astype(jnp.float32), vp_axis),
                key, temperature
            )
            buf = lax.dynamic_update_slice(
                buf, nxt[:, None], (0, s0 + i)
            )
            return buf, key

        return lax.fori_loop(0, max_new_tokens, body, (buf0, key))

    return run

"""Network-in-Network, TPU-first.

Parity target: ``examples/imagenet/models/nin.py`` in the reference — the
``NIN`` chain (mlpconv stacks + global average pooling head).

A 1x1 conv is exactly an MXU matmul over the channel axis, so the mlpconv
pattern maps perfectly to TPU; NHWC + bfloat16 as elsewhere.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax.numpy as jnp
from flax import linen as nn


class _MLPConv(nn.Module):
    """conv(k) → relu → 1x1 conv → relu → 1x1 conv → relu."""

    features: Tuple[int, int, int]
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "VALID"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        f1, f2, f3 = self.features
        x = nn.Conv(f1, self.kernel, strides=self.strides,
                    padding=self.padding, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.relu(nn.Conv(f2, (1, 1), dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(f3, (1, 1), dtype=self.dtype)(x))
        return x


class NIN(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x, *, deterministic: bool | None = None):
        det = not self.train if deterministic is None else deterministic
        x = x.astype(self.dtype)
        x = _MLPConv((96, 96, 96), (11, 11), strides=(4, 4),
                     dtype=self.dtype)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = _MLPConv((256, 256, 256), (5, 5), padding=[(2, 2), (2, 2)],
                     dtype=self.dtype)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = _MLPConv((384, 384, 384), (3, 3), padding=[(1, 1), (1, 1)],
                     dtype=self.dtype)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        if 0 in x.shape[1:3]:
            raise ValueError(
                "NIN: input image too small — a VALID max_pool collapsed "
                f"the feature map to spatial shape {x.shape[1:3]}; use an "
                "image size >= 96 (a zero-size mean would silently be NaN)"
            )
        x = nn.Dropout(0.5, deterministic=det)(x)
        x = _MLPConv((1024, 1024, self.num_classes), (3, 3),
                     padding=[(1, 1), (1, 1)], dtype=self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pooling head
        return x.astype(jnp.float32)

"""ResNet family (ResNet-18/50/101), TPU-first.

Parity target: ``examples/imagenet/models/resnet50.py`` in the reference —
the headline data-parallel workload (BASELINE.md: images/sec/chip and
scaling efficiency are measured on ResNet-50).

TPU-native design choices:
* NHWC layout (XLA:TPU's native conv layout; NCHW would transpose on every
  conv) and bfloat16 compute with fp32 parameters; BatchNorm normalizes in
  the compute dtype (the round-3 MFU ablation's biggest lever: fp32 BN
  arithmetic cost 23% of the step) while statistics accumulate in fp32.
* A ``norm`` factory field so ``create_mnbn_model`` can swap BatchNorm for
  :class:`~chainermn_tpu.links.MultiNodeBatchNormalization` without
  touching model code.
* All convs lower to MXU-tiled ``lax.conv_general_dilated`` via flax; the
  stem + residual adds fuse into the surrounding convs under XLA.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn


def default_norm(size: int, **kw):
    """Plain BatchNorm factory.  ``size`` is the channel count (kept
    positional for MNBN-factory compatibility).

    ``dtype`` sets the *normalization arithmetic* dtype and defaults to
    fp32; models pass their compute dtype through ``_bind_norm``, so
    bf16 models normalize in bf16 — measured +29% ResNet-50 step
    throughput on v5e (benchmarks/resnet_mfu_loop.py: 45.7 vs 59.3
    ms/step), while batch statistics still ACCUMULATE in fp32 (flax
    promotes half-precision reductions unless force_float32_reductions
    is disabled), so mean/var stay accurate over millions of elements."""
    del size
    return nn.BatchNorm(
        use_running_average=kw.pop("use_running_average", None),
        momentum=0.9, epsilon=1e-5,
        dtype=kw.pop("dtype", jnp.float32), **kw
    )



def _bind_norm(norm_factory: Callable, size: int, train: bool,
               dtype=None, **kw):
    """Instantiate a norm module and bind train/eval mode at call time
    (both flax BatchNorm and MultiNodeBatchNormalization accept
    ``use_running_average`` in ``__call__``).

    ``dtype`` is the model's compute dtype, offered to the factory as a
    *default* — only when its signature can accept it (a ``dtype``
    parameter or ``**kwargs``), and never overriding a dtype the factory
    or its creator pinned explicitly.  Factories written to the plain
    ``norm(size) -> Module`` contract keep working unchanged."""
    import inspect

    if dtype is not None and "dtype" not in kw:
        try:
            params = inspect.signature(norm_factory).parameters.values()
            accepts = any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                or p.name == "dtype"
                for p in params
            )
        except (TypeError, ValueError):
            accepts = False
        if accepts:
            kw["dtype"] = dtype
    m = norm_factory(size, **kw)
    try:
        accepts = "use_running_average" in inspect.signature(
            type(m).__call__
        ).parameters
    except (TypeError, ValueError):
        accepts = False
    if accepts:
        return lambda x: m(x, use_running_average=not train)
    return m


class Bottleneck(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)
    norm: Callable = default_norm
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        needs_proj = (
            x.shape[-1] != self.features * 4 or self.strides != (1, 1)
        )
        residual = x
        y = conv(self.features, (1, 1))(x)
        y = _bind_norm(self.norm, self.features, self.train, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = conv(self.features, (3, 3), strides=self.strides, padding=[(1, 1), (1, 1)])(y)
        y = _bind_norm(self.norm, self.features, self.train, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = conv(self.features * 4, (1, 1))(y)
        y = _bind_norm(self.norm, self.features * 4, self.train,
                       dtype=self.dtype,
                       scale_init=nn.initializers.zeros)(y)
        if needs_proj:
            residual = conv(self.features * 4, (1, 1), strides=self.strides)(x)
            residual = _bind_norm(self.norm, self.features * 4, self.train, dtype=self.dtype)(residual)
        return nn.relu(y + residual)


class BasicBlock(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)
    norm: Callable = default_norm
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.features, (3, 3), strides=self.strides, padding=[(1, 1), (1, 1)])(x)
        y = _bind_norm(self.norm, self.features, self.train, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = conv(self.features, (3, 3), padding=[(1, 1), (1, 1)])(y)
        y = _bind_norm(self.norm, self.features, self.train,
                       dtype=self.dtype,
                       scale_init=nn.initializers.zeros)(y)
        if x.shape[-1] != self.features or self.strides != (1, 1):
            residual = conv(self.features, (1, 1), strides=self.strides)(x)
            residual = _bind_norm(self.norm, self.features, self.train, dtype=self.dtype)(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: type = Bottleneck
    num_classes: int = 1000
    num_filters: int = 64
    norm: Callable = default_norm
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(self.num_filters, (7, 7), strides=(2, 2),
                    padding=[(3, 3), (3, 3)], use_bias=False,
                    dtype=self.dtype, name="conv_init")(x)
        x = nn.relu(_bind_norm(self.norm, self.num_filters, self.train, dtype=self.dtype)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2**i, strides=strides, norm=self.norm,
                    dtype=self.dtype, train=self.train,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


def ResNet18(**kw) -> ResNet:
    return ResNet(stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock, **kw)


def ResNet50(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=Bottleneck, **kw)


def ResNet101(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 23, 3], block_cls=Bottleneck, **kw)

from .mlp import MLP  # noqa: F401

__all__ = ["MLP"]


def __getattr__(name):
    # Lazy imports keep `import chainermn_tpu` light; model families pull in
    # their own modules on first use.
    if name in ("ResNet50", "ResNet18", "ResNet101"):
        from . import resnet

        return getattr(resnet, name)
    if name in ("VGG16",):
        from . import vgg

        return getattr(vgg, name)
    if name in ("AlexNet",):
        from . import alexnet

        return getattr(alexnet, name)
    if name in ("GoogLeNet", "GoogLeNetBN"):
        from . import googlenet

        return getattr(googlenet, name)
    if name in ("NIN",):
        from . import nin

        return getattr(nin, name)
    if name in ("Seq2Seq", "Encoder", "Decoder"):
        from . import seq2seq

        return getattr(seq2seq, name)
    if name in ("TransformerLM", "TransformerBlock", "lm_loss",
                "sp_lm_loss", "vp_lm_loss", "generate"):
        from . import transformer

        return getattr(transformer, name)
    if name in ("MoeTransformerLM", "MoeTransformerBlock", "MoeMlp",
                "moe_lm_loss", "moe_param_specs"):
        from . import moe_transformer

        return getattr(moe_transformer, name)
    raise AttributeError(name)

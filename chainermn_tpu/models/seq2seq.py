"""Seq2seq (encoder-decoder LSTM) model family.

Parity target: the reference's ``examples/seq2seq/seq2seq.py`` — a WMT
En<->Fr encoder-decoder built from Chainer ``NStepLSTM``s with an embedding
per side and a projection to the target vocabulary, trained with
teacher forcing and evaluated with greedy translation (and its
model-parallel split ``seq2seq_mp1.py``, encoder and decoder on different
ranks via ``MultiNodeChainList`` + ``create_multi_node_n_step_rnn``).

TPU-native redesign:
* Static shapes everywhere — sequences are padded to a fixed length with
  ``PAD`` and masked in the loss, instead of the reference's per-sentence
  variable-length lists (dynamic shapes would force recompilation and
  defeat XLA tiling).
* The recurrence is :class:`~chainermn_tpu.links.n_step_rnn.LSTMStack`
  (``lax.scan`` over time, fused 4-gate matmuls on the MXU).
* Teacher-forced training is one compiled forward; greedy translation is
  an incremental decode that carries the ``(h, c)`` state, one step per
  token.
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from chainermn_tpu.links.n_step_rnn import LSTMStack

PAD = 0
EOS = 1
BOS = 2
N_SPECIAL = 3  # number of reserved token ids


class Encoder(nn.Module):
    """Source embedding + LSTM stack; returns the final ``(h, c)`` state.

    Packaged as its own module so the model-parallel example can place it
    on its own chip (reference ``seq2seq_mp1.py`` rank-0 component).
    """

    n_vocab: int
    n_units: int
    n_layers: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xs: jnp.ndarray):
        """xs: (batch, time) int32 source tokens. Returns ((h, c), outs)."""
        emb = nn.Embed(self.n_vocab, self.n_units, dtype=self.dtype,
                       name="embed_x")
        mask = (xs != PAD)
        ex = emb(xs) * mask[..., None].astype(self.dtype)
        state, outs = LSTMStack(self.n_units, self.n_layers,
                                self.dtype, name="lstm")(ex)
        return state, outs


class Decoder(nn.Module):
    """Target embedding + LSTM stack + vocab projection.

    ``__call__(state, ys_in)`` teacher-forces the whole target sequence in
    one compiled scan and returns per-position logits; ``state`` is the
    encoder's final ``(h, c)`` (or ``None`` for language-model use).
    """

    n_vocab: int
    n_units: int
    n_layers: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, state, ys_in: jnp.ndarray):
        emb = nn.Embed(self.n_vocab, self.n_units, dtype=self.dtype,
                       name="embed_y")
        ey = emb(ys_in)
        new_state, hs = LSTMStack(self.n_units, self.n_layers,
                                  self.dtype, name="lstm")(ey, state)
        logits = nn.Dense(self.n_vocab, dtype=jnp.float32, name="W")(hs)
        return new_state, logits


class Seq2Seq(nn.Module):
    """Encoder-decoder with teacher forcing.

    ``__call__(xs, ys_in)`` returns logits of shape
    ``(batch, target_time, n_target_vocab)``.
    """

    n_source_vocab: int
    n_target_vocab: int
    n_units: int = 256
    n_layers: int = 2
    dtype: Any = jnp.float32

    def setup(self):
        self.encoder = Encoder(self.n_source_vocab, self.n_units,
                               self.n_layers, self.dtype)
        self.decoder = Decoder(self.n_target_vocab, self.n_units,
                               self.n_layers, self.dtype)

    def __call__(self, xs: jnp.ndarray, ys_in: jnp.ndarray) -> jnp.ndarray:
        state, _ = self.encoder(xs)
        _, logits = self.decoder(state, ys_in)
        return logits

    def encode(self, xs: jnp.ndarray):
        return self.encoder(xs)[0]

    def decode(self, state, ys_in: jnp.ndarray):
        return self.decoder(state, ys_in)


def seq2seq_loss(logits: jnp.ndarray, ys_out: jnp.ndarray) -> jnp.ndarray:
    """Masked token-mean cross entropy (PAD positions excluded), as the
    reference computes ``F.softmax_cross_entropy(concat_os, concat_ys_out)``
    over concatenated unpadded sequences."""
    mask = (ys_out != PAD).astype(jnp.float32)
    raw = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), ys_out[..., None], axis=-1
    )[..., 0]
    return -(raw * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def seq2seq_metrics(logits: jnp.ndarray, ys_out: jnp.ndarray) -> dict:
    """loss / perplexity / token accuracy, mirroring the reference's
    reported ``main/loss`` and ``main/perp`` observations."""
    loss = seq2seq_loss(logits, ys_out)
    mask = (ys_out != PAD)
    correct = (jnp.argmax(logits, -1) == ys_out) & mask
    acc = correct.sum() / jnp.maximum(mask.sum(), 1)
    return {"loss": loss, "perp": jnp.exp(loss), "accuracy": acc}


def teacher_forcing(ys: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(ys_in, ys_out) = (BOS + shifted targets, targets) — the reference
    builds the same pair per sentence (``eos``-terminated)."""
    bos = jnp.full((ys.shape[0], 1), BOS, ys.dtype)
    return jnp.concatenate([bos, ys[:, :-1]], axis=1), ys


@functools.partial(jax.jit, static_argnums=(0, 3))
def _greedy_decode(model, variables, xs, max_length: int):
    """One compiled encode + scan-decode program.

    Module-level and jitted with the (hashable) flax module static, so
    repeated ``translate`` calls reuse the executable and weights stay
    runtime arguments rather than baked-in constants; the token loop is a
    ``lax.scan`` (static trip count, XLA-friendly).
    """
    state = model.apply(variables, xs, method=Seq2Seq.encode)
    tok0 = jnp.full((xs.shape[0],), BOS, jnp.int32)

    def body(carry, _):
        state, tok = carry
        new_state, logits = model.apply(
            variables, state, tok[:, None], method=Seq2Seq.decode
        )
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        return (new_state, nxt), nxt

    _, ys = jax.lax.scan(body, (state, tok0), None, length=max_length)
    return ys.T  # (batch, max_length)


def translate(model: Seq2Seq, variables, xs: jnp.ndarray,
              max_length: int = 24) -> np.ndarray:
    """Greedy decode (reference ``Seq2seq.translate``): encode once, then
    feed back the argmax token one step at a time, carrying ``(h, c)``.

    Returns int32 tokens ``(batch, max_length)`` with everything after the
    first EOS replaced by PAD.
    """
    ys = np.array(_greedy_decode(model, variables, xs, max_length))
    # Mask everything after the first EOS.
    done = np.cumsum(ys == EOS, axis=1) > 0
    after = np.concatenate(
        [np.zeros_like(done[:, :1]), done[:, :-1]], axis=1
    )
    ys[after] = PAD
    return ys

"""MNIST MLP.

Parity target: the model in the reference's ``examples/mnist/train_mnist.py``
(a 3-layer fully-connected net) — the canonical data-parallel smoke model.
TPU notes: compute in bfloat16 with fp32 params (MXU-native), single fused
matmuls per layer.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn


class MLP(nn.Module):
    n_units: int = 1000
    n_out: int = 10
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        x = nn.relu(nn.Dense(self.n_units, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(self.n_units, dtype=self.dtype)(x))
        x = nn.Dense(self.n_out, dtype=self.dtype)(x)
        return x.astype(jnp.float32)

"""VGG-16, TPU-first.

The reference imagenet example ships resnet/alex/googlenet/nin; VGG-16 is
included here as the canonical dense-conv benchmark arch (same role as the
reference's ``alex`` fallback for small-memory runs).  NHWC + bfloat16.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

_CFG: Sequence[Sequence[int]] = ((64, 64), (128, 128), (256, 256, 256),
                                 (512, 512, 512), (512, 512, 512))


class VGG16(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x, *, deterministic: bool | None = None):
        det = not self.train if deterministic is None else deterministic
        x = x.astype(self.dtype)
        for stage in _CFG:
            for features in stage:
                x = nn.Conv(features, (3, 3), padding=[(1, 1), (1, 1)],
                            dtype=self.dtype)(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=det)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=det)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)

"""AlexNet, TPU-first.

Parity target: ``examples/imagenet/models/alex.py`` in the reference — the
``Alex`` chain used by ``train_imagenet.py --arch alex``.

TPU-native design choices: NHWC layout, bfloat16 compute with fp32 params,
no LRN (the reference's local-response-norm is an accelerator-hostile
depth-window op; per the modern consensus it contributes nothing at these
scales, so it is dropped rather than emulated — batch statistics do the
job), dropout gated on ``train``.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn


class AlexNet(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x, *, deterministic: bool | None = None):
        det = not self.train if deterministic is None else deterministic
        x = x.astype(self.dtype)
        x = nn.Conv(96, (11, 11), strides=(4, 4), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(256, (5, 5), padding=[(2, 2), (2, 2)], dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(384, (3, 3), padding=[(1, 1), (1, 1)], dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(384, (3, 3), padding=[(1, 1), (1, 1)], dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(256, (3, 3), padding=[(1, 1), (1, 1)], dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=det)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=det)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)

"""GoogLeNet (Inception-v1) and its BatchNorm variant, TPU-first.

Parity targets: ``examples/imagenet/models/googlenet.py`` and
``googlenetbn.py`` in the reference.  ``GoogLeNetBN`` is the variant the
reference pairs with ``create_mnbn_model`` in multi-node runs, so its norm
layers go through the same ``norm`` factory as ResNet — swapping in
:class:`~chainermn_tpu.links.MultiNodeBatchNormalization` needs no model
changes.

TPU notes: inception branches are independent convs XLA schedules
back-to-back on the MXU; the concat is a free layout op in NHWC.  The
auxiliary classifier heads of the original paper are omitted (the reference
uses them only as a training-era regularizer; BN makes them redundant) — loss
is computed from the main head only.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Tuple

import jax.numpy as jnp
from flax import linen as nn

from .resnet import default_norm, _bind_norm


class Inception(nn.Module):
    """Four-branch inception block: 1x1 / 3x3 / 5x5 / pool-proj."""

    out1: int
    proj3: int
    out3: int
    proj5: int
    out5: int
    proj_pool: int
    norm: Callable | None = None  # None → plain conv+bias (GoogLeNet v1)
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        use_norm = self.norm is not None
        conv = functools.partial(
            nn.Conv, use_bias=not use_norm, dtype=self.dtype
        )

        def unit(y, features, kernel, padding="SAME"):
            y = conv(features, kernel, padding=padding)(y)
            if use_norm:
                y = _bind_norm(self.norm, features, self.train, dtype=self.dtype)(y)
            return nn.relu(y)

        b1 = unit(x, self.out1, (1, 1))
        b3 = unit(unit(x, self.proj3, (1, 1)), self.out3, (3, 3))
        b5 = unit(unit(x, self.proj5, (1, 1)), self.out5, (5, 5))
        bp = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = unit(bp, self.proj_pool, (1, 1))
        return jnp.concatenate([b1, b3, b5, bp], axis=-1)


# (out1, proj3, out3, proj5, out5, proj_pool) per inception block, grouped
# by stage (max-pool between stages) — the v1 paper table.
_STAGES: Tuple[Tuple[Tuple[int, ...], ...], ...] = (
    ((64, 96, 128, 16, 32, 32), (128, 128, 192, 32, 96, 64)),
    ((192, 96, 208, 16, 48, 64), (160, 112, 224, 24, 64, 64),
     (128, 128, 256, 24, 64, 64), (112, 144, 288, 32, 64, 64),
     (256, 160, 320, 32, 128, 128)),
    ((256, 160, 320, 32, 128, 128), (384, 192, 384, 48, 128, 128)),
)


class GoogLeNet(nn.Module):
    num_classes: int = 1000
    norm: Callable | None = None
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x, *, deterministic: bool | None = None):
        det = not self.train if deterministic is None else deterministic
        use_norm = self.norm is not None
        conv = functools.partial(
            nn.Conv, use_bias=not use_norm, dtype=self.dtype
        )

        def unit(y, features, kernel, **kw):
            y = conv(features, kernel, **kw)(y)
            if use_norm:
                y = _bind_norm(self.norm, features, self.train, dtype=self.dtype)(y)
            return nn.relu(y)

        x = x.astype(self.dtype)
        x = unit(x, 64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)])
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = unit(x, 64, (1, 1))
        x = unit(x, 192, (3, 3), padding=[(1, 1), (1, 1)])
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for si, stage in enumerate(_STAGES):
            if si:
                x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            for cfg in stage:
                x = Inception(*cfg, norm=self.norm, dtype=self.dtype,
                              train=self.train)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.4, deterministic=det)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


def GoogLeNetBN(norm: Callable = default_norm, **kw) -> GoogLeNet:
    """GoogLeNet with BatchNorm after every conv (reference googlenetbn.py);
    pass a MultiNodeBatchNormalization factory (or use create_mnbn_model)
    for cross-rank sync-BN."""
    return GoogLeNet(norm=norm, **kw)

from .scatter_dataset import scatter_dataset, scatter_index, SubDataset  # noqa: F401
from .empty_dataset import create_empty_dataset  # noqa: F401

__all__ = [
    "scatter_dataset",
    "scatter_index",
    "SubDataset",
    "create_empty_dataset",
]

from .scatter_dataset import (  # noqa: F401
    SubDataset,
    rescatter,
    scatter_dataset,
    scatter_index,
    weighted_shard_counts,
)
from .empty_dataset import create_empty_dataset  # noqa: F401

__all__ = [
    "scatter_dataset",
    "scatter_index",
    "rescatter",
    "weighted_shard_counts",
    "SubDataset",
    "create_empty_dataset",
]

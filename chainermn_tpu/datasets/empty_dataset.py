"""Empty dataset stub.

Reference parity: ``chainermn/datasets/empty_dataset.py`` —
``create_empty_dataset(dataset)``: a length-preserving dataset of ``None``s
for ranks that only consume activations in model-parallel execution (they
must still iterate the same number of steps as data-holding ranks).
"""

from __future__ import annotations


class _EmptyDataset:
    def __init__(self, length: int):
        self._length = length

    def __len__(self) -> int:
        return self._length

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [None] * len(range(*i.indices(self._length)))
        if not -self._length <= i < self._length:
            raise IndexError(i)
        return None


def create_empty_dataset(dataset):
    """Length-preserving stub of ``None``s (see module docstring)."""
    return _EmptyDataset(len(dataset))

"""Dataset scattering.

Reference parity: ``chainermn/datasets/scatter_dataset.py`` —
``scatter_dataset(dataset, comm, root=0, shuffle=False, seed=None)``: root
builds an (optionally shuffled) permutation, slices it into ``size``
near-equal ``SubDataset`` shards, and pickles each shard to its rank over
MPI (chunked ~256 MB sends).

TPU-native redesign: physically shipping pickled data is an artifact of the
MPI world.  Under JAX every process can compute its own index range, so
scattering becomes a *metadata-only* operation: broadcast the RNG seed
(control plane) so all processes agree on the permutation, then each rank
takes a slice of indices into the original dataset.  O(1) communication
instead of O(data), with identical shard semantics — including the
reference's behavior of padding shards to equal length so every rank steps
the same number of times per epoch.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np


class SubDataset:
    """A view of ``dataset`` through ``order[start:end]`` (parity with the
    chainer ``SubDataset`` shards the reference scattered).

    Shards are equalized in length by wrapping around the permutation, so
    all ranks run the same number of iterations per epoch (the reference
    achieved this by slicing near-equal ranges; we pad the short shards).
    """

    def __init__(self, dataset, order: np.ndarray, start: int, end: int):
        self._dataset = dataset
        self._order = order
        self._start = start
        self._end = end

    def __len__(self) -> int:
        return self._end - self._start

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if not -len(self) <= i < len(self):
            raise IndexError(i)
        if i < 0:
            i += len(self)
        return self._dataset[int(self._order[self._start + i])]

    @property
    def indices(self) -> np.ndarray:
        return self._order[self._start : self._end]


def weighted_shard_counts(total: int, weights: Sequence[float], *,
                          min_count: int = 0) -> list:
    """Per-rank sample counts for a weighted split of ``total`` samples.

    Largest-remainder method with DETERMINISTIC remainder placement:
    each rank's quota is ``total * w_r / sum(w)``; floors are taken,
    and the remaining samples go to the largest fractional parts, ties
    broken by the LOWER rank — which makes equal weights reproduce the
    equalized split's "first ``rem`` ranks absorb the remainder"
    pattern exactly.  ``min_count`` lifts short shards (stealing one
    sample at a time from the currently largest shard, ties again to
    the lower rank) so an equalized weighted shard can never be empty.

    An EXPLICIT zero weight is legal and means "this rank owns no
    samples" — the probationary-host contract (scale-up: a candidate
    runs report windows on a weight-0 shard before it may carry state).
    Zero-weight ranks get exactly 0, never receive remainder samples,
    and are exempt from the ``min_count`` lift; at least one weight
    must still be positive (someone has to own the data), and negative
    or non-finite weights stay errors.
    """
    w = np.asarray(list(weights), dtype=np.float64)
    if w.ndim != 1 or w.size == 0:
        raise ValueError(f"weights must be a non-empty 1-D sequence, "
                         f"got shape {w.shape}")
    if not np.all(np.isfinite(w)) or np.any(w < 0):
        raise ValueError(
            f"weights must be finite and >= 0 (zero = a probationary "
            f"rank owning no samples; demotion, not a negative weight, "
            f"removes a rank); got {list(weights)!r}"
        )
    if not np.any(w > 0):
        raise ValueError(
            f"at least one weight must be > 0, got {list(weights)!r}"
        )
    pos = w > 0
    size = int(w.size)
    total = int(total)
    if min_count * int(np.count_nonzero(pos)) > total:
        raise ValueError(
            f"cannot give {int(np.count_nonzero(pos))} shards >= "
            f"{min_count} sample(s) each from {total} total"
        )
    quota = total * (w / w.sum())
    counts = np.floor(quota).astype(np.int64)
    frac = quota - counts
    # float-noise guards around the exact integer total
    while counts.sum() > total:
        counts[int(np.argmax(counts))] -= 1
    rem = int(total - counts.sum())
    if rem > 0:
        # largest fractional part first; ties -> lowest rank; a
        # zero-weight rank's frac is exactly 0.0 but float noise can
        # zero a positive rank's frac too — the remainder must land on
        # ranks that OWN data
        ranked = [i for i in np.lexsort((np.arange(size), -frac))
                  if pos[i]]
        counts[ranked[:rem]] += 1
    while True:
        short = np.where((counts < min_count) & pos)[0]
        if short.size == 0:
            break
        donor = int(np.argmax(counts))  # ties -> lowest rank
        if counts[donor] <= min_count:
            raise ValueError(
                f"cannot satisfy min_count={min_count} over "
                f"{size} shards of {total} samples"
            )
        counts[donor] -= 1
        counts[int(short[0])] += 1
    return [int(c) for c in counts]


def _weighted_split(order: np.ndarray, size: int, rank: int,
                    weights: Sequence[float], equalize: bool):
    """Weighted contiguous split of ``order``.  With ``equalize`` every
    shard is padded (by wrapping ITS OWN indices — the per-shard form
    of the equal split's wrap-around pad) to the widest shard's length,
    so every rank still steps the same number of times per epoch: the
    lockstep-SPMD contract an adaptive rebalance must not break.

    A weight-0 shard owns NO samples of its own (see
    :func:`weighted_shard_counts`); under ``equalize`` it is padded
    from the HEAD of the epoch permutation — pure re-served padding,
    so the probationary rank still steps in lockstep while drawing
    nothing the data-owning ranks don't already cover."""
    if len(weights) != size:
        raise ValueError(
            f"got {len(weights)} weights for {size} shards"
        )
    counts = weighted_shard_counts(
        len(order), weights, min_count=1 if equalize else 0
    )
    if not equalize:
        offsets = np.concatenate([[0], np.cumsum(counts)])
        return order, int(offsets[rank]), int(offsets[rank + 1])
    width = max(counts)
    segments, off = [], 0
    for c in counts:
        seg = order[off:off + c]
        off += c
        if c == 0:
            # np.resize of an EMPTY segment would fabricate zeros
            # (indices the shard never owned); a weight-0 shard's
            # lockstep pad is the permutation's head instead
            seg = order[:width]
        segments.append(np.resize(seg, width))  # wrap-pad within shard
    out = np.concatenate(segments)
    return out, rank * width, (rank + 1) * width


def scatter_index(n: int, size: int, rank: int, *,
                  shuffle: bool = False, seed: Optional[int] = None,
                  equalize: bool = True,
                  weights: Optional[Sequence[float]] = None,
                  order: Optional[np.ndarray] = None) -> np.ndarray:
    """Index shard for ``rank`` of ``size`` over a dataset of length ``n``.

    ``weights``: explicit per-rank shard weights (the straggler-adaptive
    rebalance substrate — see :func:`weighted_shard_counts` for the
    deterministic remainder placement).  ``order``: a precomputed base
    permutation to re-split (how a rebalance re-shards the SAME epoch
    permutation under new weights instead of redrawing it).
    """
    if order is None:
        order = np.arange(n)
        if shuffle:
            order = np.random.RandomState(seed).permutation(n)
    else:
        order = np.asarray(order)
    if weights is not None:
        return _weighted_split(order, size, rank, weights, equalize)
    if equalize and len(order) % size:
        pad = size - len(order) % size
        order = np.concatenate([order, order[:pad]])
    per = len(order) // size
    rem = len(order) % size
    start = rank * per + min(rank, rem)
    end = start + per + (1 if rank < rem else 0)
    return order, start, end


def scatter_dataset(
    dataset: Sequence[Any],
    comm,
    root: int = 0,
    shuffle: bool = False,
    seed: Optional[int] = None,
    *,
    rank: Optional[int] = None,
    n_shards: Optional[int] = None,
    force_equal_length: bool = True,
    weights: Optional[Sequence[float]] = None,
):
    """Shard ``dataset`` across the communicator.

    Default sharding is by *process* (each controller keeps the slice that
    feeds its addressable chips; the jitted step then shards each batch
    across chips) — the single-controller equivalent of the reference's
    one-shard-per-MPI-rank.  Pass ``n_shards=comm.size`` with an explicit
    ``rank`` for per-chip shards (model-parallel drivers, parity tests).

    All processes agree on the permutation by broadcasting the seed over
    the control plane (parity with the reference's root-generated
    permutation, minus the O(data) pickle transfer).
    """
    del root  # seed agreement below plays the root's role
    if seed is None:
        seed = int(np.random.randint(0, 2**31 - 1))
    # Agree on the seed across processes (rank 0's wins), like the
    # reference's root-owned permutation.
    seed = comm.bcast_obj(int(seed), root=0)
    if n_shards is None:
        if rank is not None:
            # Ambiguous: rank could index process-shards or chip-shards.
            raise ValueError(
                "scatter_dataset(rank=...) requires n_shards= as well "
                "(use n_shards=comm.size for per-chip shards or "
                "n_shards=comm.process_count for per-process shards)"
            )
        n_shards, r = comm.process_count, comm.process_index
    else:
        r = comm.rank if rank is None else rank
    if not 0 <= r < n_shards:
        raise ValueError(f"rank {r} out of range for {n_shards} shards")
    base = np.arange(len(dataset))
    if shuffle:
        base = np.random.RandomState(seed).permutation(len(dataset))
    order, start, end = scatter_index(
        len(dataset), n_shards, r, equalize=force_equal_length,
        weights=weights, order=base,
    )
    sub = SubDataset(dataset, order, start, end)
    # rescatter metadata: the straggler-adaptive rebalance re-splits the
    # SAME base permutation under new weights (no redraw, no re-seed)
    sub.base_order = base
    sub.scatter_spec = {
        "n_shards": int(n_shards), "rank": int(r),
        "equalize": bool(force_equal_length),
        "weights": None if weights is None
        else tuple(float(w) for w in weights),
    }
    return sub


def rescatter(sub: SubDataset, weights: Sequence[float]) -> SubDataset:
    """Re-shard a scattered dataset under new per-rank ``weights``,
    preserving the original permutation (every rank re-splits the same
    ``base_order``, so agreeing on the weights IS agreeing on the new
    shard map).  Only shards produced by :func:`scatter_dataset` carry
    the needed metadata."""
    spec = getattr(sub, "scatter_spec", None)
    base = getattr(sub, "base_order", None)
    if spec is None or base is None:
        raise ValueError(
            "rescatter needs a SubDataset produced by scatter_dataset "
            "(carrying base_order + scatter_spec)"
        )
    order, start, end = scatter_index(
        len(base), spec["n_shards"], spec["rank"],
        equalize=spec["equalize"], weights=weights, order=base,
    )
    out = SubDataset(sub._dataset, order, start, end)
    out.base_order = base
    out.scatter_spec = dict(
        spec, weights=tuple(float(w) for w in weights)
    )
    return out


def scatter_dataset_all(dataset, comm, shuffle=False, seed=None):
    """All per-chip shards at once (single-controller convenience: one
    process owns every rank, so tests and model-parallel drivers can see
    each shard)."""
    if seed is None:
        seed = 0
    return [
        scatter_dataset(dataset, comm, shuffle=shuffle, seed=seed, rank=r,
                        n_shards=comm.size)
        for r in range(comm.size)
    ]

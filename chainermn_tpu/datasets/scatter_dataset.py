"""Dataset scattering.

Reference parity: ``chainermn/datasets/scatter_dataset.py`` —
``scatter_dataset(dataset, comm, root=0, shuffle=False, seed=None)``: root
builds an (optionally shuffled) permutation, slices it into ``size``
near-equal ``SubDataset`` shards, and pickles each shard to its rank over
MPI (chunked ~256 MB sends).

TPU-native redesign: physically shipping pickled data is an artifact of the
MPI world.  Under JAX every process can compute its own index range, so
scattering becomes a *metadata-only* operation: broadcast the RNG seed
(control plane) so all processes agree on the permutation, then each rank
takes a slice of indices into the original dataset.  O(1) communication
instead of O(data), with identical shard semantics — including the
reference's behavior of padding shards to equal length so every rank steps
the same number of times per epoch.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np


class SubDataset:
    """A view of ``dataset`` through ``order[start:end]`` (parity with the
    chainer ``SubDataset`` shards the reference scattered).

    Shards are equalized in length by wrapping around the permutation, so
    all ranks run the same number of iterations per epoch (the reference
    achieved this by slicing near-equal ranges; we pad the short shards).
    """

    def __init__(self, dataset, order: np.ndarray, start: int, end: int):
        self._dataset = dataset
        self._order = order
        self._start = start
        self._end = end

    def __len__(self) -> int:
        return self._end - self._start

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if not -len(self) <= i < len(self):
            raise IndexError(i)
        if i < 0:
            i += len(self)
        return self._dataset[int(self._order[self._start + i])]

    @property
    def indices(self) -> np.ndarray:
        return self._order[self._start : self._end]


def scatter_index(n: int, size: int, rank: int, *,
                  shuffle: bool = False, seed: Optional[int] = None,
                  equalize: bool = True) -> np.ndarray:
    """Index shard for ``rank`` of ``size`` over a dataset of length ``n``."""
    order = np.arange(n)
    if shuffle:
        order = np.random.RandomState(seed).permutation(n)
    if equalize and n % size:
        pad = size - n % size
        order = np.concatenate([order, order[:pad]])
    per = len(order) // size
    rem = len(order) % size
    start = rank * per + min(rank, rem)
    end = start + per + (1 if rank < rem else 0)
    return order, start, end


def scatter_dataset(
    dataset: Sequence[Any],
    comm,
    root: int = 0,
    shuffle: bool = False,
    seed: Optional[int] = None,
    *,
    rank: Optional[int] = None,
    n_shards: Optional[int] = None,
    force_equal_length: bool = True,
):
    """Shard ``dataset`` across the communicator.

    Default sharding is by *process* (each controller keeps the slice that
    feeds its addressable chips; the jitted step then shards each batch
    across chips) — the single-controller equivalent of the reference's
    one-shard-per-MPI-rank.  Pass ``n_shards=comm.size`` with an explicit
    ``rank`` for per-chip shards (model-parallel drivers, parity tests).

    All processes agree on the permutation by broadcasting the seed over
    the control plane (parity with the reference's root-generated
    permutation, minus the O(data) pickle transfer).
    """
    del root  # seed agreement below plays the root's role
    if seed is None:
        seed = int(np.random.randint(0, 2**31 - 1))
    # Agree on the seed across processes (rank 0's wins), like the
    # reference's root-owned permutation.
    seed = comm.bcast_obj(int(seed), root=0)
    if n_shards is None:
        if rank is not None:
            # Ambiguous: rank could index process-shards or chip-shards.
            raise ValueError(
                "scatter_dataset(rank=...) requires n_shards= as well "
                "(use n_shards=comm.size for per-chip shards or "
                "n_shards=comm.process_count for per-process shards)"
            )
        n_shards, r = comm.process_count, comm.process_index
    else:
        r = comm.rank if rank is None else rank
    if not 0 <= r < n_shards:
        raise ValueError(f"rank {r} out of range for {n_shards} shards")
    order, start, end = scatter_index(
        len(dataset), n_shards, r, shuffle=shuffle, seed=seed,
        equalize=force_equal_length,
    )
    return SubDataset(dataset, order, start, end)


def scatter_dataset_all(dataset, comm, shuffle=False, seed=None):
    """All per-chip shards at once (single-controller convenience: one
    process owns every rank, so tests and model-parallel drivers can see
    each shard)."""
    if seed is None:
        seed = 0
    return [
        scatter_dataset(dataset, comm, shuffle=shuffle, seed=seed, rank=r,
                        n_shards=comm.size)
        for r in range(comm.size)
    ]

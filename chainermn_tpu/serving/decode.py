"""Single-token decode step over the paged KV cache.

The serving twin of ``models.transformer``'s KV-cache decode mode: the
same TransformerLM architecture, the same parameter tree (module names
match, so a trained checkpoint loads verbatim), but the cache is the
shared page pool of :mod:`serving.kv_cache` instead of a per-call flax
variable — which is what lets continuous batching share one compiled
program across requests of different lengths.

Two cache layouts, one math:

* ``layout="paged"`` — production: pages gathered through the block
  table.  The attend is the exact fp32-softmax flow of
  ``SelfAttention._decode_attend`` (compute-dtype QK einsum, fp32
  softmax, compute-dtype PV), so greedy tokens agree with
  ``transformer.generate``'s decode tier.
* ``layout="dense"`` — the test oracle: a contiguous per-slot cache
  written positionally, no block table anywhere.  Same contraction
  length (``pages_per_slot * page_size``), same masking — the paged
  step is **bit-identical** to it (the acceptance pin: only the
  block-table plumbing differs).

``attention_impl="flash"`` swaps the decode-geometry Pallas kernel
(:func:`~chainermn_tpu.ops.pallas_attention.flash_decode`) into the
paged attend for single-token steps — fp32 online softmax over pages,
agreeing with the dense attend to float roundoff (the kernel is the
TPU fast path; the dense attend is the bit-exactness contract).

Tensor parallelism reuses the audited ``parallel`` layers
(ColumnParallel/RowParallel — heads shard, the row-parallel psum per
projection is the only collective), so the whole decode step costs
exactly 2 all-reduces per layer: pinned as the ``decode_step`` budget
in ``analysis.budgets`` and attributed by shardlint with zero
partitioner insertions (tests/test_serving.py).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax import lax

from ..models.transformer import MlpBlock, TpMlpBlock, TransformerLM
from ..observability import timeline as _obs
from ..resilience import fault_injection as _fi
from .kv_cache import NULL_PAGE, PagedKVCache, pages_needed

_LAYOUTS = ("paged", "dense")
_ATTENTION_IMPLS = ("dense", "flash")


def _write_paged(kl, vl, k, v, tables, lengths, page_size):
    """Scatter this call's k/v rows into the page pool.  Cache position
    for (row b, step j) is ``lengths[b] + j``; its page comes from the
    row's block table.  Inactive slots (length 0, table all null) write
    the null page — in-bounds garbage nothing ever reads."""
    b, s = k.shape[0], k.shape[1]
    pos = lengths[:, None] + jnp.arange(s)[None, :]          # (b, s)
    idx = pos // page_size
    # positions past the table width (a speculative verify near the end
    # of a slot's reservation) must land on the null page — the default
    # clamping gather would silently redirect them into the slot's LAST
    # real page and clobber live history
    page = jnp.take_along_axis(
        tables, jnp.clip(idx, 0, tables.shape[1] - 1), axis=1
    )
    page = jnp.where(idx >= tables.shape[1], NULL_PAGE, page)
    off = pos % page_size
    flat = lambda a: a.reshape(b * s, *a.shape[2:])
    kl = kl.at[flat(page), flat(off)].set(flat(k))
    vl = vl.at[flat(page), flat(off)].set(flat(v))
    return kl, vl


def _write_dense(kl, vl, k, v, lengths):
    """The oracle's write: position-indexed into a contiguous per-slot
    cache — no block table anywhere."""
    b, s = k.shape[0], k.shape[1]
    rows = jnp.arange(b)[:, None]
    pos = lengths[:, None] + jnp.arange(s)[None, :]
    kl = kl.at[rows, pos].set(k)
    vl = vl.at[rows, pos].set(v)
    return kl, vl


def _attend_cached(q, kg, vg, lengths, scale):
    """The decode attend on a gathered/contiguous cache view
    ``(b, K, heads, dh)`` — the exact dtype flow of
    ``SelfAttention._decode_attend`` (compute-dtype QK, fp32 softmax,
    compute-dtype PV), shared by the paged and dense layouts so their
    bit-identity is a property of the plumbing, not luck."""
    b, s = q.shape[0], q.shape[1]
    k_tot = kg.shape[1]
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, kg
    ).astype(jnp.float32) * scale
    kpos = jnp.arange(k_tot)[None, :]
    # causal-within-cache mask: query row j (cache position
    # lengths[b]+j) sees positions <= its own — including the k/v this
    # call just wrote
    qpos = lengths[:, None] + jnp.arange(s)[None, :]          # (b, s)
    mask = kpos[None, :, :] <= qpos[:, :, None]               # (b, s, K)
    scores = jnp.where(
        mask[:, None], scores, jnp.finfo(jnp.float32).min
    )
    p = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), vg)


class _PagedAttention(nn.Module):
    """SelfAttention's projections (same submodule names, so trained
    params load verbatim) around the paged/dense cache attend."""

    n_heads: int
    dtype: Any
    tp_axis: Optional[str]
    layout: str
    attention_impl: str
    page_size: int

    @nn.compact
    def __call__(self, x, kl, vl, tables, lengths):
        b, s, d = x.shape
        heads = self.n_heads
        dh = d // heads
        if self.tp_axis is not None:
            from ..parallel import ColumnParallelDense, RowParallelDense

            ntp = lax.axis_size(self.tp_axis)
            heads = heads // ntp
            col = functools.partial(
                ColumnParallelDense, axis_name=self.tp_axis,
                use_bias=False, dtype=self.dtype,
            )
            q, k, v = col(d)(x), col(d)(x), col(d)(x)
        else:
            qkv = nn.Dense(3 * d, use_bias=False, dtype=self.dtype)(x)
            q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, heads, dh)
        k = k.reshape(b, s, heads, dh).astype(q.dtype)
        v = v.reshape(b, s, heads, dh).astype(q.dtype)
        if self.layout == "paged":
            kl, vl = _write_paged(kl, vl, k, v, tables, lengths,
                                  self.page_size)
            if self.attention_impl == "flash" and s == 1:
                from ..ops.pallas_attention import flash_decode

                out = flash_decode(
                    q[:, 0], kl, vl, tables, lengths + 1,
                    scale=dh ** -0.5,
                )[:, None]
            else:
                kg = kl[tables].reshape(b, -1, heads, dh)
                vg = vl[tables].reshape(b, -1, heads, dh)
                out = _attend_cached(q, kg, vg, lengths, dh ** -0.5)
        else:
            kl, vl = _write_dense(kl, vl, k, v, lengths)
            out = _attend_cached(q, kl, vl, lengths, dh ** -0.5)
        out = out.reshape(b, s, heads * dh)
        if self.tp_axis is not None:
            out = RowParallelDense(
                d, axis_name=self.tp_axis, use_bias=False,
                dtype=self.dtype,
            )(out)
        else:
            out = nn.Dense(d, use_bias=False, dtype=self.dtype)(out)
        return out, kl, vl


class _PagedBlock(nn.Module):
    """TransformerBlock's pre-LN residual wiring with the paged
    attention; submodule names match the training block's."""

    n_heads: int
    d_ff: int
    dtype: Any
    ln_dtype: Any
    tp_axis: Optional[str]
    layout: str
    attention_impl: str
    page_size: int

    @nn.compact
    def __call__(self, x, kl, vl, tables, lengths):
        h = nn.LayerNorm(dtype=self.ln_dtype, name="LayerNorm_0")(x)
        h, kl, vl = _PagedAttention(
            self.n_heads, dtype=self.dtype, tp_axis=self.tp_axis,
            layout=self.layout, attention_impl=self.attention_impl,
            page_size=self.page_size, name="SelfAttention_0",
        )(h.astype(self.dtype), kl, vl, tables, lengths)
        x = x + h
        h = nn.LayerNorm(dtype=self.ln_dtype, name="LayerNorm_1")(x)
        if self.tp_axis is not None:
            mlp = TpMlpBlock(self.d_ff, tp_axis=self.tp_axis,
                             dtype=self.dtype, name="TpMlpBlock_0")
        else:
            mlp = MlpBlock(self.d_ff, dtype=self.dtype,
                           name="MlpBlock_0")
        return x + mlp(h.astype(self.dtype)), kl, vl


class PagedLM(nn.Module):
    """TransformerLM's decode forward against an external paged cache.

    Parameter tree is identical to :class:`~chainermn_tpu.models.
    transformer.TransformerLM`'s (explicit submodule names), so trained
    checkpoints apply verbatim.  The cache arrays ride the call
    functionally — `(logits, k_pages, v_pages)` out — so the compiled
    step donates and returns them instead of mutating flax variables.
    """

    vocab_size: int
    d_model: int
    n_heads: int
    n_layers: int
    d_ff: int
    max_len: int
    dtype: Any = jnp.bfloat16
    ln_dtype: Any = jnp.float32
    tp_axis: Optional[str] = None
    layout: str = "paged"
    attention_impl: str = "dense"
    page_size: int = 16

    @nn.compact
    def __call__(self, tokens, k_pages, v_pages, tables, lengths):
        b, s = tokens.shape
        embed = nn.Embed(
            self.vocab_size, self.d_model,
            embedding_init=nn.initializers.normal(0.02),
            dtype=jnp.float32, name="embed",
        )
        pos_table = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (self.max_len, self.d_model), jnp.float32,
        )
        positions = lengths[:, None] + jnp.arange(s)[None, :]
        pos = jnp.take(
            pos_table, jnp.clip(positions, 0, self.max_len - 1), axis=0
        )  # (b, s, d)
        x = (embed(tokens) + pos).astype(self.dtype)
        for i in range(self.n_layers):
            x, kl, vl = _PagedBlock(
                self.n_heads, self.d_ff, dtype=self.dtype,
                ln_dtype=self.ln_dtype, tp_axis=self.tp_axis,
                layout=self.layout, attention_impl=self.attention_impl,
                page_size=self.page_size, name=f"TransformerBlock_{i}",
            )(x, k_pages[i], v_pages[i], tables, lengths)
            k_pages = k_pages.at[i].set(kl)
            v_pages = v_pages.at[i].set(vl)
        x = nn.LayerNorm(dtype=self.ln_dtype, name="LayerNorm_0")(x)
        logits = x.astype(jnp.float32) @ embed.embedding.T
        return logits, k_pages, v_pages


class DecodeEngine:
    """Owns the compiled decode/prefill programs and the page pool for
    one replica.

    ``model``: the (trained) :class:`TransformerLM` whose architecture
    and params to serve — ``seq_axis``/``vocab_parallel`` models are
    rejected (training-only shardings; materialize the dense twin,
    same param tree).  ``capacity`` fixed decode slots (the padded slot
    model: one compiled decode program per capacity, prompts padded to
    ``page_size`` buckets — join/leave between iterations never
    retraces).  Tensor-parallel models pass ``comm`` (mesh binding
    ``model.tp_axis``) and ``param_specs`` exactly like
    ``transformer.generate``.
    """

    def __init__(self, model: TransformerLM, params, *,
                 capacity: int = 4, page_size: int = 16,
                 pages_per_slot: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 comm=None, param_specs=None,
                 layout: str = "paged",
                 attention_impl: str = "dense"):
        if layout not in _LAYOUTS:
            raise ValueError(f"layout must be one of {_LAYOUTS}")
        if attention_impl not in _ATTENTION_IMPLS:
            raise ValueError(
                f"attention_impl must be one of {_ATTENTION_IMPLS}"
            )
        if getattr(model, "seq_axis", None) is not None:
            raise ValueError(
                "serving decodes dense (optionally tensor-parallel) "
                "models; construct the seq_axis=None twin (the param "
                "tree is identical)"
            )
        if getattr(model, "vocab_parallel", False):
            raise ValueError(
                "vocab_parallel serving is not implemented; serve the "
                "dense-head twin"
            )
        self.tp_axis = getattr(model, "tp_axis", None)
        if self.tp_axis is not None and (
            comm is None or param_specs is None
        ):
            raise ValueError(
                "a tensor-parallel model serves under its mesh: pass "
                "comm= and param_specs= (e.g. megatron_param_specs)"
            )
        self.model = model
        self.params = params
        self.comm = comm
        self.param_specs = param_specs
        self.capacity = int(capacity)
        self.page_size = int(page_size)
        if pages_per_slot is None:
            pages_per_slot = pages_needed(model.max_len, page_size)
        self.pages_per_slot = int(pages_per_slot)
        self.max_total = min(
            self.pages_per_slot * self.page_size, model.max_len
        )
        self.layout = layout
        self.attention_impl = attention_impl
        self.module = PagedLM(
            vocab_size=model.vocab_size, d_model=model.d_model,
            n_heads=model.n_heads, n_layers=model.n_layers,
            d_ff=model.d_ff or 4 * model.d_model,
            max_len=model.max_len, dtype=model.dtype,
            ln_dtype=getattr(model, "ln_dtype", jnp.float32),
            tp_axis=self.tp_axis, layout=layout,
            attention_impl=attention_impl, page_size=self.page_size,
        )
        self.cache = PagedKVCache(
            n_layers=model.n_layers, n_heads=model.n_heads,
            d_head=model.d_model // model.n_heads,
            capacity=self.capacity, page_size=self.page_size,
            num_pages=num_pages, pages_per_slot=self.pages_per_slot,
            dtype=model.dtype,
        )
        # an explicit (small) num_pages also bounds the admissible
        # request: one needing more pages than the whole pool passes
        # the slot-width check but can NEVER be admitted — submit()
        # must reject it up front or the batcher loops on it forever
        self.max_total = min(
            self.max_total, (self.cache.num_pages - 1) * self.page_size
        )
        if layout == "dense":
            # the oracle's contiguous per-slot cache, sized to the SAME
            # contraction length as the paged pool so the two layouts'
            # reductions are shape-identical (bit-exactness contract)
            shape = (model.n_layers, self.capacity, self.max_pages_tokens,
                     model.n_heads, model.d_model // model.n_heads)
            self.cache.k_pages = jnp.zeros(shape, model.dtype)
            self.cache.v_pages = jnp.zeros(shape, model.dtype)
        self._fn = self._build()
        self.steps = 0

    @property
    def max_pages_tokens(self) -> int:
        return self.pages_per_slot * self.page_size

    # -- compiled step --------------------------------------------------
    def _raw_fn(self) -> Callable:
        module = self.module

        def fn(params, tokens, k_pages, v_pages, tables, lengths):
            return module.apply(
                params, tokens, k_pages, v_pages, tables, lengths
            )

        return fn

    def _shard_mapped(self, fn) -> Callable:
        """The one place the TP program's specs live: pages shard by
        heads (axis 3, both layouts), everything else replicated —
        shared by the compiled step, the collective trace, and the
        shardlint HLO so they can never diverge."""
        from jax.sharding import PartitionSpec as P

        pages = P(None, None, None, self.tp_axis, None)
        return jax.shard_map(
            fn, mesh=self.comm.mesh,
            in_specs=(self.param_specs, P(), pages, pages, P(), P()),
            out_specs=(P(), pages, pages),
            check_vma=False,
        )

    def _build(self) -> Callable:
        fn = self._raw_fn()
        if self.tp_axis is None:
            return jax.jit(fn, donate_argnums=(2, 3))
        return jax.jit(self._shard_mapped(fn), donate_argnums=(2, 3))

    # -- serving ops ----------------------------------------------------
    def prompt_bucket(self, prompt_len: int) -> int:
        """Prompts pad to page_size multiples — one compiled prefill
        program per bucket, stable under continuous joins."""
        return max(pages_needed(prompt_len, self.page_size)
                   * self.page_size, self.page_size)

    def admit(self, total_tokens: int, prefix=None,
              slot: Optional[int] = None) -> int:
        if total_tokens > self.max_total:
            raise ValueError(
                f"request needs {total_tokens} cache positions > "
                f"max_total={self.max_total} (pages_per_slot * "
                "page_size, capped by model.max_len)"
            )
        return self.cache.admit(total_tokens, prefix=prefix, slot=slot)

    def release(self, slot: int) -> None:
        self.cache.release(slot)

    def _tables_for(self, rows) -> jnp.ndarray:
        if self.layout == "dense":
            # the oracle has no tables; pass the slot ids (unused by
            # the dense write/attend, but keeps one call signature)
            return jnp.asarray(np.asarray(rows, np.int32)).reshape(
                len(rows), 1
            )
        return jnp.asarray(self.cache.block_tables[rows])

    def prefill(self, slot: int, prompt: Sequence[int]) -> np.ndarray:
        """Run the prompt through the model, writing its k/v into the
        slot's pages; returns the next-token logits row (vocab,).
        The prompt is padded to its page bucket — padded positions hold
        garbage k/v that the masked attend never reads and the next
        writes overwrite.

        A slot admitted over a shared prefix starts with
        ``cache.lengths[slot] > 0``: only the TAIL ``prompt[start:]`` is
        run (bucketed on the tail length), reading the aliased pages
        through the block table.  The attend math, mask, and
        contraction length are those of the full prefill, so the
        returned logits row is bit-identical to prefilling the whole
        prompt fresh."""
        prompt = np.asarray(prompt, np.int32)
        n = int(prompt.shape[0])
        if n < 1:
            raise ValueError("empty prompt")
        start = int(self.cache.lengths[slot])
        if start >= n:
            raise ValueError(
                f"slot {slot} already holds {start} positions >= "
                f"prompt length {n} (a shared prefix is capped at "
                "len(prompt)-1 so the tail is never empty)"
            )
        nt = n - start
        _fi.fire("serving.prefill")
        with _obs.span("serving.prefill", slot=slot, prompt=n,
                       shared=start):
            bucket = self.prompt_bucket(nt)
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :nt] = prompt[start:]
            if self.layout == "paged":
                # copy-on-write BEFORE the compiled write: a capped
                # shared prefix puts the first written position inside
                # a still-shared page
                self.cache.cow_for_write(slot, bucket)
            if self.layout == "dense":
                k_in = self.cache.k_pages[:, slot: slot + 1]
                v_in = self.cache.v_pages[:, slot: slot + 1]
            else:
                k_in, v_in = self.cache.k_pages, self.cache.v_pages
            logits, k_out, v_out = self._fn(
                self.params, jnp.asarray(toks), k_in, v_in,
                self._tables_for([slot]),
                jnp.asarray(np.array([start], np.int32)),
            )
            if self.layout == "dense":
                self.cache.k_pages = self.cache.k_pages.at[
                    :, slot: slot + 1].set(k_out)
                self.cache.v_pages = self.cache.v_pages.at[
                    :, slot: slot + 1].set(v_out)
            else:
                self.cache.set_pages(k_out, v_out)
            self.cache.advance(slot, nt)
            return np.asarray(logits[0, nt - 1])

    def decode_step(self, tokens: np.ndarray) -> np.ndarray:
        """One token for every slot (the padded slot model: inactive
        slots run too, on the null page, and their logits are garbage
        the batcher ignores).  ``tokens``: (capacity,) int32 — each
        active slot's pending token.  Returns (capacity, vocab) logits;
        active slots' cache lengths advance by one."""
        _fi.fire("serving.decode_step")
        active = [s for s in range(self.capacity) if self.cache.active[s]]
        with _obs.span("serving.decode", active=len(active)):
            toks = jnp.asarray(
                np.asarray(tokens, np.int32).reshape(self.capacity, 1)
            )
            if self.layout == "paged":
                for s in active:
                    self.cache.cow_for_write(s, 1)
            if self.layout == "dense":
                tables = self._tables_for(list(range(self.capacity)))
            else:
                tables = self.cache.tables_array()
            logits, k_out, v_out = self._fn(
                self.params, toks, self.cache.k_pages,
                self.cache.v_pages, tables,
                self.cache.lengths_array(),
            )
            self.cache.set_pages(k_out, v_out)
            for s in active:
                self.cache.advance(s, 1)
            self.steps += 1
            return np.asarray(logits[:, 0])

    def verify_step(self, tokens: np.ndarray) -> np.ndarray:
        """Speculative verify: score ``k`` pending tokens per slot in
        ONE batched step over the same compiled program family as
        :meth:`decode_step` (shape ``(capacity, k)`` — fixed across
        join/leave, so no retrace).  ``tokens[s, j]`` is the j-th
        pending token of slot ``s``; returns ``(capacity, k, vocab)``
        logits where row ``j`` conditions on tokens ``0..j``.  Cache
        lengths do NOT advance — the caller commits the accepted count
        via :meth:`PagedKVCache.advance` (and rewinds a mirrored draft
        with :meth:`PagedKVCache.rollback`); positions written past the
        commit are overwritten by the next step's writes before its
        masked attend can read them."""
        toks = np.asarray(tokens, np.int32)
        if toks.ndim != 2 or toks.shape[0] != self.capacity:
            raise ValueError(
                f"verify_step wants (capacity, k) tokens, got {toks.shape}"
            )
        k = int(toks.shape[1])
        _fi.fire("serving.spec_verify")
        active = [s for s in range(self.capacity) if self.cache.active[s]]
        with _obs.span("serving.spec_verify", active=len(active), k=k):
            if self.layout == "paged":
                for s in active:
                    self.cache.cow_for_write(s, k)
            if self.layout == "dense":
                tables = self._tables_for(list(range(self.capacity)))
            else:
                tables = self.cache.tables_array()
            logits, k_out, v_out = self._fn(
                self.params, jnp.asarray(toks), self.cache.k_pages,
                self.cache.v_pages, tables,
                self.cache.lengths_array(),
            )
            self.cache.set_pages(k_out, v_out)
            self.steps += 1
            return np.asarray(logits)

    # -- KV handoff (disaggregated prefill/decode) ----------------------
    def export_kv(self, slot: int):
        """Gather ``slot``'s cache state into a dense handoff buffer
        (:meth:`PagedKVCache.export_kv`) under a ``kv.export`` span
        carrying the buffer's native bytes.  Paged layout only — the
        dense oracle has no block table to gather through and never
        participates in a role pool."""
        if self.layout != "paged":
            raise ValueError(
                "KV handoff is a paged-layout feature; the dense "
                "oracle serves unified"
            )
        with _obs.span("kv.export", slot=int(slot)) as sp:
            kv = self.cache.export_kv(slot)
            sp.set(tokens=kv.length,
                   bytes=int(kv.k.nbytes) + int(kv.v.nbytes))
        return kv

    def ingest_kv(self, kv, total_tokens: int,
                  slot: Optional[int] = None) -> int:
        """Admit a handoff buffer (:meth:`PagedKVCache.import_kv`) —
        fresh pages, prefixes re-registered — under a ``kv.import``
        span carrying the buffer's native bytes.  Returns the slot."""
        if self.layout != "paged":
            raise ValueError(
                "KV handoff is a paged-layout feature; the dense "
                "oracle serves unified"
            )
        if total_tokens > self.max_total:
            raise ValueError(
                f"handoff needs {total_tokens} cache positions > "
                f"max_total={self.max_total}"
            )
        with _obs.span("kv.import", tokens=int(kv.length),
                       bytes=int(kv.k.nbytes) + int(kv.v.nbytes)):
            return self.cache.import_kv(kv, int(total_tokens), slot=slot)

    def generate(self, prompt: Sequence[int], max_new_tokens: int,
                 eos_id: Optional[int] = None) -> list:
        """Single-request greedy decode (admit -> prefill -> decode
        loop -> release) — the oracle path tests and the replica's
        drain replay use.  Returns prompt + generated tokens."""
        prompt = list(int(t) for t in prompt)
        slot = self.admit(len(prompt) + max_new_tokens)
        try:
            logits = self.prefill(slot, prompt)
            out = list(prompt)
            tok = int(np.argmax(logits))
            out.append(tok)
            for _ in range(max_new_tokens - 1):
                if eos_id is not None and tok == eos_id:
                    break
                toks = np.zeros((self.capacity,), np.int32)
                toks[slot] = tok
                step_logits = self.decode_step(toks)
                tok = int(np.argmax(step_logits[slot]))
                out.append(tok)
        finally:
            self.release(slot)
        return out

    # -- analysis hooks -------------------------------------------------
    def _example_args(self, phase: str = "decode", bucket: int = 0):
        if phase == "decode":
            b, s = self.capacity, 1
        elif phase == "verify":
            # the speculative verify program: full capacity, k tokens
            b, s = self.capacity, (bucket or 4)
        else:
            b, s = 1, (bucket or self.page_size)
        toks = jnp.zeros((b, s), jnp.int32)
        if self.layout == "dense":
            tables = jnp.zeros((b, 1), jnp.int32)
            k = self.cache.k_pages[:, :b] if b < self.capacity else \
                self.cache.k_pages
            v = self.cache.v_pages[:, :b] if b < self.capacity else \
                self.cache.v_pages
        else:
            tables = jnp.zeros((b, self.pages_per_slot), jnp.int32)
            k, v = self.cache.k_pages, self.cache.v_pages
        lengths = jnp.zeros((b,), jnp.int32)
        return (self.params, toks, k, v, tables, lengths)

    def collective_trace(self, phase: str = "decode", bucket: int = 0):
        """The authored :class:`~chainermn_tpu.analysis.trace.
        CollectiveTrace` of the compiled decode (or prefill) program —
        what the ``decode_step`` budget pin enforces and the bench
        fingerprints disclose."""
        from ..analysis import trace_collectives

        fn = self._raw_fn()
        args = self._example_args(phase, bucket)
        if self.tp_axis is None:
            return trace_collectives(fn, *args)
        return trace_collectives(self._shard_mapped(fn), *args)

    def compiled_text(self, phase: str = "decode", bucket: int = 0) -> str:
        """Compiled HLO of the decode/prefill program (undonated twin)
        for the shardlint attribution check."""
        fn = self._raw_fn()
        if self.tp_axis is not None:
            fn = self._shard_mapped(fn)
        args = self._example_args(phase, bucket)
        return jax.jit(fn).lower(*args).compile().as_text()

    def attribution(self, timeline_or_report):
        """Join a telemetry export's measured collective spans to this
        engine's decode trace (``observability.attribute``) — the
        per-token latency-attribution recipe of docs/serving.md.
        Never drops: spans and records that don't pair are listed."""
        from ..observability import attribute

        return attribute(timeline_or_report, self.collective_trace())


def engine_from_trained(model: TransformerLM, params, **kw) -> DecodeEngine:
    """Engine over a model possibly trained with training-only sharding
    (sequence parallelism): materialize the dense twin — identical
    param tree — then serve it."""
    if getattr(model, "seq_axis", None) is not None:
        import dataclasses

        fields = {
            f.name: getattr(model, f.name)
            for f in dataclasses.fields(model)
            if f.name not in ("parent", "name")
        }
        fields["seq_axis"] = None
        model = type(model)(**fields)
    return DecodeEngine(model, params, **kw)

"""Disaggregated prefill/decode: role pools with codec-streamed KV
handoff.

The mixed-load problem: a long prompt's prefill steals decode
iterations from every in-flight request on the same replica — one
prefill-heavy request inflates every other request's inter-token
latency.  The attack ("Understanding and Improving Communication
Performance in Multi-node LLM Inference", PAPERS.md) is to split the
fleet into a PREFILL pool (prompt-bucket prefill only, publishes each
request's KV) and a DECODE pool (ingests published KV instead of
prefilling), with the handoff streamed through the existing wire
codecs.

The handoff lifecycle::

    client         prefill pool              decode pool
      |  submit        |                          |
      |--------------> |  claim (seq % n_prefill, |
      |                |   pool="prefill" drains) |
      |                |  prefill -> export_kv    |
      |                |  pack (codec) -> publish |
      |                |  kv_handoff/kv_<id>.npz  |
      |                |------------------------->|  claim (seq % n_decode)
      |                |                          |  load -> import_kv
      |                |                          |  decode to completion
      | <--------------------------------------- |  res_<id>.json

Three invariants make this safe:

* **Bit-identity** — ``export_kv`` copies the slot's pages by value
  (prefix-shared pages included) and ``import_kv`` admits fresh pages,
  so the decode pool's cache state after ingest equals local prefill's
  exactly; the cache dtype is bf16, so the ``none``/``bf16`` codecs are
  lossless on the wire and the served tokens are bit-identical to the
  unified oracle at 0 tolerance.  ``int8`` (per-buffer absmax, one
  scale per layer per tensor) is a measured accuracy question gated by
  greedy-token agreement, NOT a loss pin — KV ships once, so there is
  no next step for an error-feedback residual to ride.
* **Atomicity** — handoffs write tmp+rename into the journal's
  ``kv_handoff/`` area (the results contract), so a decode replica
  sees a complete handoff or none.  Publishing is idempotent: greedy
  prefill is deterministic, so two prefill replicas racing on a
  re-derived share overwrite each other with identical bytes.
* **Recoverability** — a dead prefill replica's share re-derives onto
  the healthy prefill replicas via the pool-scoped drain markers
  (``mark_draining(i, pool="prefill")``); a handoff orphaned past
  ``handoff_timeout_s`` is re-prefilled LOCALLY by the decode replica
  (greedy replay from the prompt — bit-identical), so the decode pool
  completes the stream even if the whole prefill pool dies.

Telemetry: ``kv.export`` / ``kv.ship`` / ``kv.import`` spans carry
exact byte counts, priced by ``observability.attribute.
kv_transfer_points``.  The handoff path itself issues ZERO collectives
(pinned in tests): encode/decode are jnp-pure casts and the transfer
is a file or host copy.
"""

from __future__ import annotations

import json
import os
import time
import zipfile
from collections import deque
from typing import List, NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..comm_wire.codecs import (
    HANDOFF_CODECS,
    PackedBuffer,
    pack_buffer,
    packed_wire_bytes,
    unpack_buffer,
)
from ..observability import timeline as _obs
from ..resilience.log import emit
from .batcher import FAILED, Request
from .kv_cache import KVExport
from .replica import DecodeReplica, RequestJournal, claim


# ----------------------------------------------------------------------
# wire form: per-layer codec packing of a KVExport
# ----------------------------------------------------------------------
class PackedHandoff(NamedTuple):
    """A :class:`~chainermn_tpu.serving.kv_cache.KVExport` in wire
    form: ``k``/``v`` are the per-layer codec payloads concatenated
    into one flat byte buffer each; ``meta`` carries everything needed
    to invert the pack (codec, geometry, dtypes, int8 scales) plus the
    request-level fields that ride the handoff (valid length, prefix
    chain, the prefill-produced first token)."""

    meta: dict
    k: np.ndarray
    v: np.ndarray


def _wire_dtype(codec: str, native: str) -> np.dtype:
    if codec == "none":
        return np.dtype(jnp.dtype(native))
    if codec == "int8":
        return np.dtype(np.int8)
    return np.dtype(jnp.dtype({"bf16": jnp.bfloat16,
                               "f16": jnp.float16}[codec]))


def _pack_tensor(x: np.ndarray, codec: str):
    """Per-LAYER packing of one ``(n_layers, ...)`` tensor: the int8
    codec gets one absmax grid per layer per tensor (KV magnitudes
    differ wildly across layers — a single global scale would waste
    most of the 8-bit grid on the loudest layer)."""
    payloads, scales = [], []
    for i in range(x.shape[0]):
        pb = pack_buffer(x[i], codec)
        payloads.append(np.asarray(pb.data).reshape(-1).view(np.uint8))
        scales.append(pb.scale)
    return np.concatenate(payloads), scales


def _unpack_tensor(raw: np.ndarray, codec: str, shape, native: str,
                   scales) -> np.ndarray:
    wd = _wire_dtype(codec, native)
    per = int(np.prod(shape[1:])) * wd.itemsize
    out = np.empty(tuple(shape), np.dtype(jnp.dtype(native)))
    for i in range(shape[0]):
        data = raw[i * per:(i + 1) * per].view(wd).reshape(shape[1:])
        out[i] = unpack_buffer(PackedBuffer(
            codec, data, scales[i], tuple(shape[1:]), native
        ))
    return out


def pack_handoff(kv: KVExport, first_token: int,
                 codec: str = "none") -> PackedHandoff:
    """Pack an exported KV buffer for the wire."""
    if codec not in HANDOFF_CODECS:
        raise ValueError(
            f"unknown handoff codec {codec!r}; one of {HANDOFF_CODECS}"
        )
    k = np.asarray(kv.k)
    raw_k, scales_k = _pack_tensor(k, codec)
    raw_v, scales_v = _pack_tensor(np.asarray(kv.v), codec)
    n_scales = sum(1 for s in scales_k + scales_v if s is not None)
    meta = {
        "codec": codec,
        "shape": [int(s) for s in k.shape],
        "dtype": kv.dtype,
        "length": int(kv.length),
        "page_size": int(kv.page_size),
        "prefix_chain": list(kv.prefix_chain),
        "first_token": int(first_token),
        "scales_k": scales_k,
        "scales_v": scales_v,
        # exact bytes in flight: both payloads plus 4 per int8 scale
        "wire_bytes": int(raw_k.size + raw_v.size + 4 * n_scales),
    }
    return PackedHandoff(meta, raw_k, raw_v)


def unpack_handoff(ph: PackedHandoff) -> Tuple[KVExport, int]:
    """Invert :func:`pack_handoff`: ``(KVExport, first_token)``."""
    m = ph.meta
    kv = KVExport(
        k=_unpack_tensor(ph.k, m["codec"], m["shape"], m["dtype"],
                         m["scales_k"]),
        v=_unpack_tensor(ph.v, m["codec"], m["shape"], m["dtype"],
                         m["scales_v"]),
        length=int(m["length"]),
        page_size=int(m["page_size"]),
        dtype=m["dtype"],
        prefix_chain=tuple(m["prefix_chain"]),
    )
    return kv, int(m["first_token"])


def transfer_kv(kv: KVExport, first_token: int,
                codec: str = "none") -> Tuple[KVExport, int]:
    """In-process handoff (co-located pools, no filesystem): the full
    pack -> ship -> unpack round trip under a ``kv.ship`` span with the
    exact wire bytes — what a same-host pool pair pays instead of the
    journal file."""
    with _obs.span("kv.ship", codec=codec, transport="memory") as sp:
        ph = pack_handoff(kv, first_token, codec)
        sp.set(bytes=ph.meta["wire_bytes"])
    return unpack_handoff(ph)


# ----------------------------------------------------------------------
# journal shipping (co-scheduled pools on the shared FS)
# ----------------------------------------------------------------------
def publish_handoff(journal: RequestJournal, request_id: str,
                    kv: KVExport, first_token: int,
                    codec: str = "none") -> int:
    """Pack and atomically publish a handoff into the journal's
    ``kv_handoff/`` area; returns the exact wire bytes shipped.
    tmp+rename (with fsync) — a reader sees a complete handoff or
    none.  Overwrite-safe: greedy prefill is deterministic, so a
    re-derived share republishing an id writes identical content."""
    with _obs.span("kv.ship", codec=codec, transport="journal") as sp:
        ph = pack_handoff(kv, first_token, codec)
        path = journal.handoff_path(request_id)
        tmp = os.path.join(
            os.path.dirname(path),
            f".tmp_{os.getpid()}_kv_{request_id}.npz",
        )
        meta_raw = np.frombuffer(
            json.dumps(ph.meta).encode(), np.uint8
        )
        with open(tmp, "wb") as f:
            np.savez(f, meta=meta_raw, k=ph.k, v=ph.v)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        sp.set(bytes=ph.meta["wire_bytes"])
    return int(ph.meta["wire_bytes"])


def load_handoff(journal: RequestJournal,
                 request_id: str) -> Optional[Tuple[KVExport, int, int]]:
    """Load a published handoff: ``(KVExport, first_token,
    wire_bytes)``, or ``None`` when no (complete) handoff exists —
    a missing file and a torn/corrupt one read the same, pending."""
    path = journal.handoff_path(request_id)
    try:
        with np.load(path) as z:
            meta = json.loads(z["meta"].tobytes().decode())
            ph = PackedHandoff(meta, z["k"], z["v"])
            kv, first = unpack_handoff(ph)
    except (OSError, ValueError, KeyError, zipfile.BadZipFile):
        return None
    return kv, first, int(meta.get("wire_bytes", 0))


# ----------------------------------------------------------------------
# role pools
# ----------------------------------------------------------------------
class PrefillReplica:
    """One prefill-pool member: claims its ``seq % n`` share of the
    journal (pool-scoped drains: ``draining(pool="prefill")``), runs
    prompt-bucket prefill ONLY, and publishes each request's KV + first
    token as a handoff.  It never decodes — its cache reservation is
    the prompt bucket, not the full generation budget, so a prefill
    slot is several times cheaper than a decode slot for long prompts.

    Exported slots stay RESIDENT (a sliding window over the cache
    capacity) so consecutive prompts sharing a prefix alias pages
    through the normal copy-on-write machinery; the oldest resident is
    released when admission needs room.  A claimed request that can
    never be prefilled (oversize, malformed) fails LOUDLY in the
    journal, exactly like the decode replica's contract."""

    pool = "prefill"

    def __init__(self, engine, journal: RequestJournal, *,
                 replica_index: int = 0, n_replicas: int = 1,
                 codec: str = "none"):
        if getattr(engine, "layout", "paged") != "paged":
            raise ValueError(
                "a prefill pool exports paged KV; the dense oracle "
                "serves unified"
            )
        if codec not in HANDOFF_CODECS:
            raise ValueError(
                f"unknown handoff codec {codec!r}; one of "
                f"{HANDOFF_CODECS}"
            )
        self.engine = engine
        self.journal = journal
        self.replica_index = int(replica_index)
        self.n_replicas = int(n_replicas)
        self.codec = codec
        self._resident: deque = deque()  # (slot, request_id), oldest first
        self.published = 0
        self.wire_bytes = 0
        self.drained = False

    def _claimed(self) -> List[dict]:
        todo = [d for d in self.journal.pending()
                if not self.journal.has_handoff(d["id"])]
        return claim(todo, self.replica_index, self.n_replicas,
                     draining=self.journal.draining(pool=self.pool))

    def _make_room(self, total: int, prompt) -> object:
        """Release oldest resident exports until ``total`` admits;
        returns the (re-derived) prefix match for the prompt."""
        cache = self.engine.cache
        prefix = cache.lookup_prefix(prompt)
        while (not cache.can_admit(total, prefix=prefix)
               and self._resident):
            slot, _ = self._resident.popleft()
            self.engine.release(slot)
            # releasing can drop index entries the match aliased
            prefix = cache.lookup_prefix(prompt)
        return prefix

    def prefill_one(self, d: dict) -> bool:
        """Prefill one claimed request and publish its handoff; False
        when the request failed loudly instead."""
        rid = d["id"]
        prompt = [int(t) for t in d["prompt"]]
        try:
            r = Request(prompt, d["max_new_tokens"], id=rid,
                        eos_id=d.get("eos_id"))
            # reserve the PROMPT bucket only: the decode pool owns the
            # generation budget; a too-big total still fails here so
            # the stream never wedges on it downstream
            if r.total_tokens > self.engine.max_total:
                raise ValueError(
                    f"{rid}: needs {r.total_tokens} cache positions > "
                    f"engine max_total={self.engine.max_total}"
                )
            bucket = self.engine.prompt_bucket(len(prompt))
            prefix = self._make_room(bucket, prompt)
            slot = self.engine.admit(bucket, prefix=prefix)
        except ValueError as err:
            r = Request([0], 1, id=rid)
            r.state = FAILED
            r.error = str(err)
            self.journal.write_result(r)
            emit("request_failed", "serving.disagg", request=rid,
                 why=str(err))
            return False
        logits = self.engine.prefill(slot, prompt)
        self.engine.cache.register_prefix(slot, prompt)
        kv = self.engine.export_kv(slot)
        first = int(np.argmax(logits))
        self.wire_bytes += publish_handoff(
            self.journal, rid, kv, first, codec=self.codec
        )
        self._resident.append((slot, rid))
        self.published += 1
        emit("handoff_published", "serving.disagg", request=rid,
             replica=self.replica_index, tokens=kv.length,
             codec=self.codec)
        return True

    def prefill_round(self) -> int:
        """One claim pass: prefill + publish every claimed request;
        returns how many were taken (published or failed loudly)."""
        n = 0
        for d in self._claimed():
            self.prefill_one(d)
            n += 1
        return n

    def serve(self, max_rounds: Optional[int] = None, *,
              until_complete: Optional[int] = None,
              poll_s: float = 0.05,
              timeout_s: float = 120.0) -> int:
        """Drive claim->prefill->publish rounds; returns handoffs
        published.  Same loop contract as :meth:`DecodeReplica.serve`:
        an empty share exits (or polls, in ``until_complete`` pool
        mode), and a preemption notice drains cleanly — published
        handoffs are durable, the unpublished share re-derives onto
        the healthy prefill replicas."""
        from ..resilience.errors import PreemptionError

        rounds = 0
        deadline = (time.monotonic() + timeout_s
                    if until_complete is not None else None)
        while True:
            try:
                n = self.prefill_round()
            except PreemptionError as err:
                emit("replica_preempted", "serving.disagg",
                     replica=self.replica_index, pool=self.pool,
                     error=f"{type(err).__name__}: {err}")
                self.drained = True
                return self.published
            rounds += 1
            if max_rounds is not None and rounds >= max_rounds:
                break
            if n == 0:
                if until_complete is None:
                    break
                if len(self.journal.results()) >= until_complete:
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"prefill replica {self.replica_index}: "
                        f"{len(self.journal.results())}/"
                        f"{until_complete} results after "
                        f"{timeout_s:.0f}s in pool mode"
                    )
                time.sleep(poll_s)
        return self.published


class DisaggDecodeReplica(DecodeReplica):
    """A decode-pool member: the full :class:`DecodeReplica` contract
    (claiming, drain/retry/preemption, warm start) with the admission
    path swapped — a claimed request is INGESTED from its published
    handoff instead of prefilled.

    A request whose handoff has not appeared yet stays pending (the
    serve loop polls); past ``handoff_timeout_s`` it is declared
    orphaned — its prefill replica died before publishing — and falls
    back to LOCAL prefill through the base path, which is bit-identical
    (greedy replay from the prompt).  So the decode pool completes the
    stream even if the whole prefill pool is gone; the handoff is an
    optimization with a correctness-preserving failure mode."""

    def __init__(self, engine, journal: RequestJournal, *,
                 handoff_timeout_s: float = 30.0, **kw):
        super().__init__(engine, journal, **kw)
        if getattr(engine, "layout", "paged") != "paged":
            raise ValueError(
                "a disaggregated decode pool ingests paged KV; the "
                "dense oracle serves unified"
            )
        self.handoff_timeout_s = float(handoff_timeout_s)
        self._first_seen: dict = {}
        self.ingested = 0
        self.local_prefills = 0

    def _enqueue(self, d: dict, served: dict) -> bool:
        rid = d["id"]
        got = load_handoff(self.journal, rid)
        if got is not None:
            kv, first, wire = got
            r = None
            try:
                r = Request(d["prompt"], d["max_new_tokens"], id=rid,
                            eos_id=d.get("eos_id"))
                if not self.batcher.can_ingest(r):
                    return False  # no pages free yet; next round
                self.batcher.ingest(r, kv, first)
            except ValueError as err:
                if r is None:
                    r = Request([0], 1, id=rid)
                r.state = FAILED
                r.error = str(err)
                self.journal.write_result(r)
                served[rid] = r
                emit("request_failed", "serving.replica",
                     request=rid, why=str(err))
                return True
            self._first_seen.pop(rid, None)
            self.ingested += 1
            emit("handoff_ingested", "serving.disagg", request=rid,
                 replica=self.replica_index, tokens=kv.length,
                 wire_bytes=wire)
            return True
        now = time.monotonic()
        seen = self._first_seen.setdefault(rid, now)
        if now - seen >= self.handoff_timeout_s:
            # orphaned: the prefill share owner died before publishing
            # — re-prefill locally (bit-identical greedy replay)
            self._first_seen.pop(rid, None)
            self.local_prefills += 1
            emit("handoff_orphan_reprefill", "serving.disagg",
                 request=rid, replica=self.replica_index,
                 waited=round(now - seen, 3))
            return super()._enqueue(d, served)
        return False

    def _flush_finished(self, served: dict) -> None:
        before = set(served)
        super()._flush_finished(served)
        for rid in set(served) - before:
            # results are the durable record; a consumed handoff is
            # journal litter once its result exists
            self.journal.clear_handoff(rid)

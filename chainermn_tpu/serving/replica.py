"""Elastic decode replicas over a shared-FS request journal.

The serving analogue of ``Trainer.run_elastic``: N replicas (each a
communicator world of its own — typically one process or one TP group)
serve one request stream.  The stream lives in a **journal directory**
on the shared filesystem: requests are submitted as atomic JSON files,
results written the same way — so a replica's death loses *no queued
request*, only its in-flight progress, and greedy decode replays that
bit-identically from the prompt.

Claiming is deterministic: request ``seq % n_replicas == replica_index``
(the submission sequence number, not a hash — any world agrees on the
partition without communicating).  After a world resize the survivors
re-derive the partition over the *remaining* unserved requests, so a
dead replica's share migrates without coordination.

Drain semantics: a :class:`~chainermn_tpu.resilience.errors.
PreemptionError` surfacing inside :meth:`DecodeReplica.serve` (the
injector's ``preempt`` kind, or a real reclaim notice) stops the loop
cleanly — in-flight requests stay unserved in the journal, the KV
cache snapshots through the checkpoint layer
(:meth:`DecodeReplica.drain`), and the replica reports itself drained.
A hard kill (``die``) is the same minus the snapshot.  Either way
:func:`serve_elastic` on the surviving world re-forms the communicator
(``resilience.elastic.reform_world``), re-claims, and completes the
stream; a rejoining replica warm-starts from the drain snapshot —
pages AND the in-flight request state (slot, tokens so far), so
drained requests resume decoding mid-stream from their restored pages
(``PagedKVCache.load_state_dict``) instead of replaying the prompt;
across a TP resize the pages re-split by heads via
:func:`~chainermn_tpu.serving.kv_cache.reshard_kv_state`.

Autoscale: :class:`ReplicaAutoscaler` sizes the pool from offered load
(journal queue depth + p99 token latency) by lifting and placing the
same drain markers — scale-up re-activates a drain-marked standby
(which sat polling in ``serve(until_complete=...)``), scale-down
drains the highest active slot; hysteresis mirrors ``AdaptPolicy`` so
the pool doesn't flap.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Callable, List, Optional, Sequence

from ..observability import timeline as _obs
from ..resilience import fault_injection as _fi
from ..resilience.elastic import write_manifest as _atomic_write
from ..resilience.errors import PreemptionError
from ..resilience.log import emit
from .batcher import FAILED, RUNNING, ContinuousBatcher, Request


class RequestJournal:
    """The shared-FS request/result exchange.

    ``req_<seq>_<id>.json`` files are the queue (seq = submission
    order, zero-padded so lexicographic order IS submission order);
    ``res_<id>.json`` files are the results.  Writes are tmp+rename
    atomic, so a reader never sees a torn request — the same contract
    as the checkpoint manifests."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        # pending() memo: (request names, result names) -> pending list
        self._pending_sig: Optional[tuple] = None
        self._pending_cache: List[dict] = []
        self._pending_scans = 0  # full rescans (the call-count pin)

    def submit(self, request: Request) -> None:
        # next seq = max existing + 1, parsed from the COMMITTED
        # request files only — a counting scheme would also count a
        # crashed submitter's leftover .tmp and skip seqs forever
        seqs = [int(n.split("_")[1]) for n in self._request_files()]
        seq = max(seqs) + 1 if seqs else 0
        _atomic_write(
            {"id": request.id, "seq": seq, "prompt": request.prompt,
             "max_new_tokens": request.max_new_tokens,
             "eos_id": request.eos_id},
            os.path.join(self.root, f"req_{seq:06d}_{request.id}.json"),
        )

    def submit_all(self, requests: Sequence[Request]) -> None:
        for r in requests:
            self.submit(r)

    def _request_files(self) -> List[str]:
        return sorted(
            n for n in os.listdir(self.root)
            if n.startswith("req_") and n.endswith(".json")
        )

    def requests(self) -> List[dict]:
        """All journaled requests, submission order."""
        out = []
        for name in self._request_files():
            try:
                with open(os.path.join(self.root, name)) as f:
                    out.append(json.load(f))
            except (OSError, ValueError):
                continue  # torn write in progress; next pass sees it
        return out

    def write_result(self, request: Request) -> None:
        _atomic_write(
            {"id": request.id, "state": request.state,
             "tokens": request.output, "error": request.error},
            os.path.join(self.root, f"res_{request.id}.json"),
        )

    def results(self) -> dict:
        out = {}
        # sorted: listdir order is filesystem-dependent and this scan
        # feeds cross-replica decisions (spmd-unsorted-scan)
        for name in sorted(os.listdir(self.root)):
            if not (name.startswith("res_") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.root, name)) as f:
                    doc = json.load(f)
                out[doc["id"]] = doc
            except (OSError, ValueError, KeyError):
                continue
        return out

    def pending(self) -> List[dict]:
        """Journaled requests with no result yet — what the surviving
        world still owes, submission order.

        Memoized by directory signature (the checkpoint layer's
        ``_is_complete`` trick): request/result files are write-once-
        by-rename, so the sorted NAME sets fully determine the pending
        list — one ``listdir`` per call, a full re-read of every
        request file only when a name appears or disappears.  Without
        this, every replica's claim poll re-parses the whole journal,
        and large streams make polling quadratic."""
        names = os.listdir(self.root)
        sig = (
            tuple(sorted(n for n in names
                         if n.startswith("req_") and n.endswith(".json"))),
            tuple(sorted(n for n in names
                         if n.startswith("res_") and n.endswith(".json"))),
        )
        if sig != self._pending_sig:
            self._pending_scans += 1
            done = self.results()
            self._pending_cache = [
                r for r in self.requests() if r["id"] not in done
            ]
            self._pending_sig = sig
        return list(self._pending_cache)

    # -- adaptive drain -------------------------------------------------
    # The straggler-adaptive escalation for serving (resilience.
    # adaptive.drain_replica): a drain marker is an atomic journal file,
    # so every replica observes the same draining set on its next claim
    # pass — the slow replica's seq-mod share migrates to the healthy
    # ones with no coordination beyond the shared filesystem.
    @staticmethod
    def _drain_name(replica_index: int, pool: str) -> str:
        # ``pool`` scopes the marker to one role pool (disaggregated
        # serving): a draining PREFILL replica must redirect prefill-
        # pool claims without also re-routing the decode pool's —
        # each pool reads only its own marker namespace.  The default
        # "" keeps the unified pool's historical filenames.
        if pool:
            if not re.fullmatch(r"[A-Za-z]+", pool):
                raise ValueError(
                    f"pool must be alphabetic (it embeds in the marker "
                    f"filename), got {pool!r}"
                )
            return f"drain_{pool}_{int(replica_index)}.json"
        return f"drain_{int(replica_index)}.json"

    def mark_draining(self, replica_index: int, *,
                      pool: str = "") -> None:
        """Mark a replica draining: it claims nothing new and its
        pending share re-derives onto the healthy replicas
        (:func:`claim` with ``draining=``).  ``pool`` scopes the
        marker to one role pool (``"prefill"``/``"decode"``)."""
        _atomic_write(
            {"replica": int(replica_index), "pool": pool},
            os.path.join(self.root,
                         self._drain_name(replica_index, pool)),
        )

    def clear_draining(self, replica_index: int, *,
                       pool: str = "") -> None:
        """Lift a drain marker (the replica recovered or rejoined)."""
        try:
            os.remove(os.path.join(
                self.root, self._drain_name(replica_index, pool)
            ))
        except OSError:
            pass

    def draining(self, *, pool: str = "") -> List[int]:
        """Sorted indices of replicas currently marked draining in
        ``pool`` (the unified pool by default)."""
        if pool:
            pat = rf"drain_{re.escape(pool)}_(\d+)\.json"
        else:
            pat = r"drain_(\d+)\.json"
        out = []
        for name in sorted(os.listdir(self.root)):
            m = re.fullmatch(pat, name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # -- KV handoff area (disaggregated prefill/decode) -----------------
    # Handoffs live beside the queue under ``kv_handoff/`` with the
    # journal's atomicity contract (tmp+rename — serving.disagg writes
    # them via publish_handoff): a decode replica either sees a
    # complete handoff or none, never a torn one.
    def handoff_dir(self) -> str:
        d = os.path.join(self.root, "kv_handoff")
        os.makedirs(d, exist_ok=True)
        return d

    def handoff_path(self, request_id: str) -> str:
        return os.path.join(self.handoff_dir(), f"kv_{request_id}.npz")

    def handoffs(self) -> List[str]:
        """Request ids with a published handoff."""
        out = []
        for name in sorted(os.listdir(self.handoff_dir())):
            m = re.fullmatch(r"kv_(.+)\.npz", name)
            if m:
                out.append(m.group(1))
        return sorted(out)

    def has_handoff(self, request_id: str) -> bool:
        return os.path.exists(self.handoff_path(request_id))

    def clear_handoff(self, request_id: str) -> None:
        """Drop a consumed handoff (decode-pool hygiene after ingest —
        results are the durable record, the KV buffer is not)."""
        try:
            os.remove(self.handoff_path(request_id))
        except OSError:
            pass

    # -- fleet rendezvous ----------------------------------------------
    # The journal is the replicas' only shared state, so it is also
    # their only SAFE rendezvous: polling files can never wedge on a
    # dead peer the way a collective barrier would — which is exactly
    # the property a churn scenario needs between waves.
    def _poll_until(self, getter, n: int, noun: str, timeout_s: float,
                    poll_s: float):
        deadline = time.monotonic() + timeout_s
        while True:
            got = getter()
            if len(got) >= n:
                return got
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"journal {self.root}: {len(got)}/{n} {noun} "
                    f"after {timeout_s:.0f}s"
                )
            time.sleep(poll_s)

    def wait_until(self, n: int, *, timeout_s: float = 60.0,
                   poll_s: float = 0.05) -> List[dict]:
        """Block until at least ``n`` requests are journaled; returns
        them.  Raises ``TimeoutError`` past ``timeout_s``."""
        return self._poll_until(self.requests, n, "requests",
                                timeout_s, poll_s)

    def wait_until_complete(self, n: int, *, timeout_s: float = 120.0,
                            poll_s: float = 0.05) -> dict:
        """Block until at least ``n`` results exist (how one survivor
        waits out its peers before whole-stream assertions); returns
        the results.  Raises ``TimeoutError`` past ``timeout_s``."""
        return self._poll_until(self.results, n, "results",
                                timeout_s, poll_s)

    def wait_draining_clear(self, replica_index: int, *,
                            timeout_s: float = 60.0,
                            poll_s: float = 0.05) -> None:
        """Block until ``replica_index`` is no longer drain-marked —
        how a standby replica waits for the autoscaler (or an
        operator's ``clear_draining``) to activate it.  Raises
        ``TimeoutError`` past ``timeout_s``."""
        deadline = time.monotonic() + timeout_s
        while int(replica_index) in self.draining():
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"journal {self.root}: replica {replica_index} "
                    f"still draining after {timeout_s:.0f}s"
                )
            time.sleep(poll_s)


def claim(requests: Sequence[dict], replica_index: int,
          n_replicas: int, draining: Sequence[int] = ()) -> List[dict]:
    """Deterministic share of ``requests`` for one replica: the
    journaled submission sequence number modulo the replica count.
    The seq is STABLE (stamped at submit), so concurrent replicas
    partition disjointly no matter when each one looks at the journal;
    after a world resize the survivors re-derive the partition of the
    still-pending seqs under the new count — a dead replica's share
    migrates without coordination.

    ``draining``: replica indices the adaptive layer marked draining
    (``RequestJournal.mark_draining``).  A draining replica claims
    nothing new; every request whose base owner is draining reassigns
    deterministically to ``healthy[seq % len(healthy)]`` — still a pure
    function of (seq, n_replicas, draining set), so the partition stays
    disjoint and complete on every replica without communicating.  All
    replicas draining falls back to the base partition: a degraded
    world must keep serving, not wedge."""
    dr = {int(d) for d in draining if 0 <= int(d) < n_replicas}
    healthy = [i for i in range(n_replicas) if i not in dr]
    if not healthy:
        dr = set()
    out = []
    for i, r in enumerate(requests):
        seq = r.get("seq", i) if isinstance(r, dict) else i
        owner = int(seq) % n_replicas
        if owner in dr:
            owner = healthy[int(seq) % len(healthy)]
        if owner == replica_index:
            out.append(r)
    return out


class DecodeReplica:
    """One replica: a batcher bound to a journal share.

    ``checkpointer``: optional ``create_multi_node_checkpointer``
    instance for the drain snapshot (the KV cache state rides the
    existing checkpoint layer — warm restart loads pages + lengths
    back instead of re-prefilling)."""

    def __init__(self, engine, journal: RequestJournal, *,
                 replica_index: int = 0, n_replicas: int = 1,
                 checkpointer=None, max_retries: int = 1,
                 timeout_s: Optional[float] = None,
                 batcher=None):
        self.engine = engine
        self.journal = journal
        self.replica_index = int(replica_index)
        self.n_replicas = int(n_replicas)
        self.checkpointer = checkpointer
        # an injected batcher (e.g. a SpeculativeBatcher with its
        # draft engine) replaces the default; it must wrap this same
        # engine so drain/warm-start snapshots stay coherent
        if batcher is not None:
            if batcher.engine is not engine:
                raise ValueError(
                    "injected batcher must wrap the replica's engine"
                )
            self.batcher = batcher
        else:
            self.batcher = ContinuousBatcher(
                engine, max_retries=max_retries, timeout_s=timeout_s
            )
        self.drained = False

    def _claimed(self) -> List[dict]:
        return claim(self.journal.pending(), self.replica_index,
                     self.n_replicas,
                     draining=self.journal.draining())

    def _inflight_path(self) -> str:
        return os.path.join(
            self.journal.root, f"inflight_{self.replica_index}.json"
        )

    def drain(self, step: int = 0) -> None:
        """Snapshot the KV cache through the checkpoint layer so a
        rejoining replica warm-starts its pages (across a TP resize,
        route the saved shards through ``reshard_kv_state``), plus the
        in-flight request state (slot, tokens so far) the pages belong
        to — without it a warm start would restore occupied slots no
        request owns."""
        if self.checkpointer is not None:
            self.checkpointer.save(
                step, {"kv_cache": self.engine.cache.state_dict()}
            )
            _atomic_write({
                "step": step,
                "requests": [
                    {"id": r.id, "prompt": r.prompt,
                     "max_new_tokens": r.max_new_tokens,
                     "eos_id": r.eos_id, "tokens": r.tokens,
                     "slot": slot}
                    for slot, r in self.batcher.active.items()
                ],
            }, self._inflight_path())
        emit("replica_drained", "serving.replica",
             replica=self.replica_index,
             in_flight=len(self.batcher.active))
        self.drained = True

    def warm_start(self) -> Optional[int]:
        """Load the newest drain snapshot's cache state, if any, and
        re-adopt its in-flight requests: each drained slot's request
        resumes decoding from its restored pages and tokens instead of
        replaying the prompt.  Restored-active slots without an
        adoptable owner (no in-flight record, or drained before the
        first token) are released — their requests are still pending
        in the journal and replay from the prompt; keeping the slots
        occupied would wedge admission forever."""
        if self.checkpointer is None:
            return None
        step, state = self.checkpointer.resume()
        if state is None or "kv_cache" not in state:
            return None
        cache = self.engine.cache
        cache.load_state_dict(state["kv_cache"])
        try:
            with open(self._inflight_path()) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = None
        if doc and doc.get("step") == step:
            for d in doc["requests"]:
                slot = int(d["slot"])
                # tokens==[] means it was drained mid-prefill: the
                # cache holds nothing useful for it — replay instead
                if not d["tokens"] or not cache.active[slot]:
                    continue
                r = Request(d["prompt"], d["max_new_tokens"],
                            id=d["id"], eos_id=d.get("eos_id"))
                r.tokens = [int(t) for t in d["tokens"]]
                r.slot = slot
                r.state = RUNNING
                # deadline restarts at adoption — without it a
                # configured timeout_s would never apply to resumed
                # requests (submitted_at None is exempt)
                r.submitted_at = time.monotonic()
                self.batcher.active[slot] = r
        for slot in range(cache.capacity):
            if cache.active[slot] and slot not in self.batcher.active:
                cache.release(slot)
        # sharing state does not ride the snapshot: re-register adopted
        # prompts (their pages hold exactly that content), so requests
        # claimed AFTER the warm start alias the restored pages too
        if getattr(self.batcher, "share_prefixes", False):
            for slot, r in self.batcher.active.items():
                cache.register_prefix(slot, r.prompt)
        # a speculative batcher re-admits adopted slots into its draft
        # cache (same slot ids) to restore draft/target lockstep
        if hasattr(self.batcher, "mirror_adopted"):
            self.batcher.mirror_adopted()
        return step

    def _enqueue(self, d: dict, served: dict) -> bool:
        """Admit one claimed journal request into the batcher; returns
        True when the request was taken this round (queued, or failed
        loudly).  The disaggregated decode replica overrides this with
        its handoff-ingest path and returns False to leave a request
        pending when its handoff has not been published yet."""
        r = None
        try:
            r = Request(d["prompt"], d["max_new_tokens"],
                        id=d["id"], eos_id=d.get("eos_id"))
            self.batcher.submit(r)
        except ValueError as err:
            # a journaled request this replica can never serve
            # (outsizes its cache, malformed) fails LOUDLY in the
            # journal — wedging the claim loop or crashing the
            # replica would take the whole share down with it
            if r is None:
                r = Request([0], 1, id=d["id"])
            r.state = FAILED
            r.error = str(err)
            self.journal.write_result(r)
            served[r.id] = r
            emit("request_failed", "serving.replica",
                 request=r.id, why=str(err))
        return True

    def _flush_finished(self, served: dict) -> None:
        """Write every newly finished request's result (covers both
        this round's claims and warm-start-resumed in-flight ones)."""
        for r in self.batcher.finished.values():
            if r.id not in served:
                self.journal.write_result(r)
                served[r.id] = r

    def serve(self, max_rounds: Optional[int] = None, *,
              until_complete: Optional[int] = None,
              poll_s: float = 0.05,
              timeout_s: float = 120.0) -> dict:
        """Claim -> serve -> write results, until the journal share is
        empty.  A :class:`PreemptionError` drains instead of crashing:
        already-finished results are flushed (done work never replays),
        and the loop exits cleanly with unserved requests still
        journaled (the survivors' next claim covers them).

        ``until_complete``: pool mode — an empty share POLLS the
        journal instead of exiting, until at least that many results
        exist stream-wide.  This is how a drain-marked standby stays
        resident (claiming nothing) and picks up its re-derived share
        the moment the autoscaler lifts its marker, and how an active
        replica keeps serving as load arrives.  Raises ``TimeoutError``
        past ``timeout_s`` of total serving time in pool mode."""
        rounds = 0
        served = {}
        deadline = (time.monotonic() + timeout_s
                    if until_complete is not None else None)
        while True:
            _fi.fire("serving.replica_round")
            in_flight = {r.id for r in self.batcher.active.values()}
            todo = [d for d in self._claimed()
                    if d["id"] not in in_flight]
            if not todo and not in_flight:
                if until_complete is None:
                    break
                if len(self.journal.results()) >= until_complete:
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"replica {self.replica_index}: "
                        f"{len(self.journal.results())}/"
                        f"{until_complete} results after "
                        f"{timeout_s:.0f}s in pool mode"
                    )
                time.sleep(poll_s)
                continue
            with _obs.span("serving.replica_round",
                           replica=self.replica_index,
                           n=len(todo) + len(in_flight)):
                admitted = 0
                for d in todo:
                    if self._enqueue(d, served):
                        admitted += 1
                try:
                    self.batcher.run()
                except PreemptionError as err:
                    self._flush_finished(served)
                    self.drain()
                    emit("replica_preempted", "serving.replica",
                         replica=self.replica_index,
                         error=f"{type(err).__name__}: {err}")
                    return served
                self._flush_finished(served)
            if todo and not admitted and not self.batcher.active \
                    and not self.batcher.queue:
                # claimed requests exist but none could be taken this
                # round (a disaggregated decode replica waiting on its
                # handoffs): poll instead of spinning the claim loop
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"replica {self.replica_index}: "
                        f"{len(todo)} claimed requests unadmittable "
                        f"after {timeout_s:.0f}s"
                    )
                time.sleep(poll_s)
            rounds += 1
            if max_rounds is not None and rounds >= max_rounds:
                break
        return served


def serve_elastic(build: Callable, journal_root: str, *,
                  communicator_name: str = "tpu", devices=None,
                  replica_index: int = 0, n_replicas: int = 1,
                  comm_kwargs: Optional[dict] = None) -> DecodeReplica:
    """Re-form the world from the survivors and finish the stream —
    ``Trainer.run_elastic``'s shape for the serving tier.

    ``build(comm) -> DecodeReplica`` constructs the replica in the new
    world (engine, journal binding, optional checkpointer for warm
    start).  The journal's pending list re-partitions over the new
    replica count by construction, so a dead replica's share migrates
    to the survivors without dropping a single queued request."""
    from ..resilience import elastic as _elastic

    comm = _elastic.reform_world(
        communicator_name, devices=devices, **(comm_kwargs or {})
    )
    replica = build(comm)
    replica.replica_index = int(replica_index)
    replica.n_replicas = int(n_replicas)
    restored = replica.warm_start()
    emit("replica_elastic_restart", "serving.serve_elastic",
         replica=replica_index, n_replicas=n_replicas,
         warm_start_step=restored, world=int(comm.size))
    replica.serve()
    return replica


class ReplicaAutoscaler:
    """Load-driven sizing of a replica pool — the serving half of the
    scale-up story, with :class:`~chainermn_tpu.resilience.adaptive.
    AdaptPolicy`'s hysteresis shape (direction streaks + action
    cooldown) pointed at the pool so it doesn't flap.

    Pool model: ``pool_size`` replica slots exist (already-launched
    processes); INACTIVE slots are drain-marked in the journal, so the
    deterministic ``seq % n`` claim routes around them and a standby
    polls idle in ``DecodeReplica.serve(until_complete=...)``.  Scale
    UP lifts the lowest drain marker (``clear_draining`` — the standby
    re-derives its share on its next claim pass); scale DOWN marks the
    highest active slot draining (its share migrates to the survivors,
    in-flight work finishes).  Exactly ONE decision maker calls
    ``observe`` once per decision window; the atomic drain markers ARE
    the broadcast — the same no-coordination contract as claiming.

    Signals (both already measured): journal queue depth
    (``pending()``) and the p99 token latency
    (``ContinuousBatcher.latency_report``).  Pressure = queue deeper
    than ``queue_per_replica`` per active replica, or p99 above
    ``p99_high_s``; relief = the queue would still fit after shedding
    one replica and p99 is fine.  A direction must persist
    ``scale_after`` consecutive windows (a neutral or opposite window
    resets the streak) and every action arms ``cooldown_windows`` of
    backoff before the next."""

    def __init__(self, journal: RequestJournal, pool_size: int, *,
                 min_replicas: int = 1, queue_per_replica: int = 4,
                 p99_high_s: Optional[float] = None,
                 scale_after: int = 2, cooldown_windows: int = 1):
        if pool_size < 1:
            raise ValueError(f"pool_size must be >= 1, got {pool_size}")
        if not 1 <= min_replicas <= pool_size:
            raise ValueError(
                f"min_replicas must be in [1, pool_size], got "
                f"{min_replicas} for pool_size={pool_size}"
            )
        if queue_per_replica < 1:
            raise ValueError(
                f"queue_per_replica must be >= 1, got {queue_per_replica}"
            )
        if scale_after < 1:
            raise ValueError(
                f"scale_after must be >= 1, got {scale_after}"
            )
        if cooldown_windows < 0:
            raise ValueError(
                f"cooldown_windows must be >= 0, got {cooldown_windows}"
            )
        self.journal = journal
        self.pool_size = int(pool_size)
        self.min_replicas = int(min_replicas)
        self.queue_per_replica = int(queue_per_replica)
        self.p99_high_s = (None if p99_high_s is None
                           else float(p99_high_s))
        self.scale_after = int(scale_after)
        self.cooldown_windows = int(cooldown_windows)
        self.streaks = {"up": 0, "down": 0}
        self.cooldown = 0
        self.windows = 0
        self.totals = {"scale_up": 0, "scale_down": 0}

    def active(self) -> List[int]:
        """Slots currently serving (pool minus the drain-marked)."""
        dr = set(self.journal.draining())
        return [i for i in range(self.pool_size) if i not in dr]

    def observe(self, *, queue_depth: Optional[int] = None,
                p99_token_s: Optional[float] = None) -> Optional[dict]:
        """One decision window: read the load signals, advance the
        hysteresis, and — when a direction's streak clears
        ``scale_after`` off cooldown — apply ONE slot's worth of
        change through the journal markers.  Returns the action dict
        (``{"action": "scale_up"|"scale_down", "replica": slot, ...}``)
        or ``None``."""
        self.windows += 1
        if queue_depth is None:
            queue_depth = len(self.journal.pending())
        queue_depth = int(queue_depth)
        active = self.active()
        n = max(len(active), 1)
        hot = (self.p99_high_s is not None and p99_token_s is not None
               and float(p99_token_s) > self.p99_high_s)
        pressure = (queue_depth > self.queue_per_replica * n) or hot
        relief = (not hot
                  and queue_depth <= self.queue_per_replica * (n - 1))
        # streaks only accumulate toward a move the pool can make
        if pressure and len(active) < self.pool_size:
            self.streaks["up"] += 1
            self.streaks["down"] = 0
        elif relief and len(active) > self.min_replicas:
            self.streaks["down"] += 1
            self.streaks["up"] = 0
        else:
            self.streaks["up"] = self.streaks["down"] = 0
        on_cooldown = self.cooldown > 0
        if self.cooldown > 0:
            self.cooldown -= 1
        if on_cooldown:
            return None
        if self.streaks["up"] >= self.scale_after:
            standby = [i for i in self.journal.draining()
                       if i < self.pool_size]
            if not standby:
                return None
            slot = min(standby)  # lowest standby activates first
            self.journal.clear_draining(slot)
            self.streaks["up"] = self.streaks["down"] = 0
            self.cooldown = self.cooldown_windows
            self.totals["scale_up"] += 1
            action = {"action": "scale_up", "replica": int(slot),
                      "active": len(active) + 1,
                      "queue_depth": queue_depth}
            emit("autoscale_decision", "serving.autoscale",
                 action="scale_up", replica=int(slot),
                 queue_depth=queue_depth, active=len(active) + 1,
                 p99_token_s=p99_token_s)
            emit("autoscale_action", "serving.autoscale",
                 action="scale_up", replica=int(slot))
            return action
        if self.streaks["down"] >= self.scale_after:
            slot = max(active)  # highest active sheds first
            self.journal.mark_draining(slot)
            self.streaks["up"] = self.streaks["down"] = 0
            self.cooldown = self.cooldown_windows
            self.totals["scale_down"] += 1
            action = {"action": "scale_down", "replica": int(slot),
                      "active": len(active) - 1,
                      "queue_depth": queue_depth}
            emit("autoscale_decision", "serving.autoscale",
                 action="scale_down", replica=int(slot),
                 queue_depth=queue_depth, active=len(active) - 1,
                 p99_token_s=p99_token_s)
            emit("autoscale_action", "serving.autoscale",
                 action="scale_down", replica=int(slot))
            return action
        return None

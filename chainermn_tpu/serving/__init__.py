"""Serving tier: continuous-batching LLM decode over a paged KV cache.

The first subsystem built on all four prior tentpoles at once: the
decode step's collective count is pinned by the static analyzer
(``decode_step`` in ``analysis.budgets``) and attributed by shardlint;
its latency is measured by the ``observability`` span timeline and
priced per collective via ``attribute()``; request-level failures ride
the ``resilience`` taxonomy (retry/timeout/preemption); and replica
worlds re-form through ``resilience.elastic``.

* :mod:`.kv_cache` — the paged KV cache: fixed-size pages from one
  pool, per-slot block tables, a deterministic reserve-at-admit
  allocator, checkpoint round-trip, TP heads resharding.
* :mod:`.decode` — :class:`DecodeEngine`: the single-token decode /
  prompt-prefill programs over the paged cache, consuming a trained
  ``TransformerLM``'s parameters verbatim; a dense contiguous-cache
  oracle layout the paged step is bit-identical to; a decode-geometry
  Pallas fast path (``ops.flash_decode``).
* :mod:`.batcher` — :class:`ContinuousBatcher`: the request queue and
  the padded-slot iteration loop (join/leave between decode steps,
  request retry/timeout, per-token latency histograms), with
  copy-on-write prefix sharing across requests by default.
* :mod:`.speculative` — :class:`SpeculativeBatcher`: draft-propose /
  target-verify decode (k tokens per 2-psum/layer verify step,
  greedy-exact acceptance, bit-identical to plain decode).
* :mod:`.replica` — elastic decode replicas over a shared-FS request
  journal: deterministic request claiming, drain on preemption,
  ``serve_elastic`` world re-formation, KV-page warm start.
* :mod:`.disagg` — disaggregated prefill/decode role pools:
  :class:`PrefillReplica` prefills and publishes codec-packed KV
  handoffs into the journal's ``kv_handoff/`` area;
  :class:`DisaggDecodeReplica` ingests them instead of prefilling
  (bit-identical for lossless codecs, orphan-safe local re-prefill).

See docs/serving.md for the architecture and the latency-attribution
recipe.
"""

from .kv_cache import (  # noqa: F401
    CacheAdmissionError,
    KVExport,
    NULL_PAGE,
    PagedKVCache,
    PrefixMatch,
    pages_needed,
    reshard_kv_state,
)
from .decode import (  # noqa: F401
    DecodeEngine,
    PagedLM,
    engine_from_trained,
)
from .batcher import (  # noqa: F401
    ContinuousBatcher,
    Request,
)
from .speculative import SpeculativeBatcher  # noqa: F401
from .replica import (  # noqa: F401
    DecodeReplica,
    ReplicaAutoscaler,
    RequestJournal,
    serve_elastic,
)
from .disagg import (  # noqa: F401
    DisaggDecodeReplica,
    PrefillReplica,
    load_handoff,
    pack_handoff,
    publish_handoff,
    transfer_kv,
    unpack_handoff,
)

"""Continuous batching over the paged decode engine.

The serving loop: a request queue feeding a fixed set of decode slots
(the **padded slot model** — the compiled decode program always runs
the full capacity; empty slots decode the null page and their logits
are ignored), with requests joining and leaving **between** decode
iterations.  One compiled program per (capacity, prompt bucket) —
membership churn never retraces.

Resilience semantics (the request-level slice of the taxonomy):

* A *recoverable* :class:`~chainermn_tpu.resilience.errors.
  ResilienceError` escaping a prefill/decode step (injected transient,
  exhausted obj-store retries under a TP world, a preemption notice)
  evicts the in-flight slots and **re-queues** their requests — greedy
  decode replays bit-identically from the prompt, so a retried request
  returns the same tokens it would have (pinned by test).  Per-request
  ``retries`` are bounded by ``max_retries``; exhaustion fails the
  request (recorded, never raised) while the batch keeps serving.
* A per-request ``timeout_s`` deadline (monotonic clock) fails
  overdue requests between iterations, recorded as a
  ``request_timeout`` resilience event.  Replica-local only: a
  multi-process TP world rejects ``timeout_s`` at construction (the
  clock is rank-local — ranks straddling the deadline would diverge
  their admission schedules and deadlock the decode psums).
* Non-recoverable errors propagate — they are program bugs, not load.

Instrumentation: ``serving.step`` / ``serving.prefill`` /
``serving.decode`` spans land in the active telemetry timeline (the
engine emits the inner two), and the batcher always keeps its own
:class:`~chainermn_tpu.observability.metrics.MetricsRegistry` —
``serving.token_latency`` (one sample per decode iteration: every
active request got one token), ``serving.ttft`` (submit -> first
token), ``serving.prefill_latency`` — so p50/p99 exist even with
telemetry off.  ``latency_report()`` summarizes;
``DecodeEngine.attribution(timeline)`` joins a telemetry export to the
decode trace per collective (docs/serving.md has the recipe).
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..observability import timeline as _obs
from ..observability.metrics import MetricsRegistry
from ..resilience.errors import PreemptionError, ResilienceError
from ..resilience.log import emit

_ids = itertools.count()

QUEUED, RUNNING, DONE, FAILED = "queued", "running", "done", "failed"


class Request:
    """One generation request and its runtime state."""

    def __init__(self, prompt: Sequence[int], max_new_tokens: int, *,
                 id: Optional[str] = None, eos_id: Optional[int] = None):
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.prompt = [int(t) for t in prompt]
        if not self.prompt:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.id = id if id is not None else f"req{next(_ids)}"
        self.state = QUEUED
        self.tokens: List[int] = []
        self.slot: Optional[int] = None
        # cache positions inherited from a prefix-shared admission
        # (0 = cold prefill of the whole prompt)
        self.shared_len = 0
        self.retries = 0
        self.error: Optional[str] = None
        self.submitted_at: Optional[float] = None
        self.prefill_started_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.done_at: Optional[float] = None

    @property
    def total_tokens(self) -> int:
        return len(self.prompt) + self.max_new_tokens

    @property
    def output(self) -> List[int]:
        return self.prompt + self.tokens

    def _finished(self) -> bool:
        if len(self.tokens) >= self.max_new_tokens:
            return True
        return (self.eos_id is not None and self.tokens
                and self.tokens[-1] == self.eos_id)

    def __repr__(self):
        return (f"<Request {self.id} {self.state} prompt={len(self.prompt)}"
                f" generated={len(self.tokens)}/{self.max_new_tokens}>")


class ContinuousBatcher:
    """The iteration loop: admit joins, one decode step for the whole
    slot set, retire leaves — repeat."""

    def __init__(self, engine, *, max_retries: int = 1,
                 timeout_s: Optional[float] = None,
                 share_prefixes: bool = True):
        comm = getattr(engine, "comm", None)
        if (timeout_s is not None and comm is not None
                and getattr(comm, "process_count", 1) > 1):
            # the deadline reads each process's LOCAL monotonic clock:
            # two ranks straddling it would time out a request
            # differently, diverge their admission schedules, and
            # deadlock the decode step's psums.  Every admission
            # decision must stay a deterministic function of shared
            # state — enforce deadlines at the journal/client layer
            # instead.
            raise ValueError(
                "timeout_s is wall-clock-local and cannot be used in a "
                "multi-process TP world (ranks could time out a "
                "request differently and desynchronize the admission "
                "schedule); enforce request deadlines outside the "
                "batcher"
            )
        self.engine = engine
        self.max_retries = int(max_retries)
        self.timeout_s = timeout_s
        # prefix sharing is pure deterministic allocator bookkeeping,
        # so it is on by default — except under the dense-oracle
        # layout, whose per-slot contiguous cache has no block table
        # to alias (the oracle must stay the UNSHARED reference)
        self.share_prefixes = (
            bool(share_prefixes)
            and getattr(engine, "layout", "paged") == "paged"
        )
        self.queue: deque = deque()
        self.active: Dict[int, Request] = {}
        self.finished: Dict[str, Request] = {}
        self.registry = MetricsRegistry()
        self.steps = 0
        self.tokens_generated = 0
        self.prefix_hits = 0
        self.prefix_tokens_shared = 0

    # -- submission -----------------------------------------------------
    def submit(self, request: Request) -> Request:
        if request.total_tokens > self.engine.max_total:
            raise ValueError(
                f"{request.id}: needs {request.total_tokens} cache "
                f"positions > engine max_total={self.engine.max_total}"
            )
        request.state = QUEUED
        request.submitted_at = time.monotonic()
        self.queue.append(request)
        return request

    def _sync_submissions(self) -> None:
        """Multi-process TP world: every rank must run the same
        admission schedule.  The chief's queue is broadcast once (the
        per-request state rides the obj store); after that every
        decision is a deterministic function of shared state."""
        comm = getattr(self.engine, "comm", None)
        if comm is None or comm.process_count <= 1:
            return
        payload = [
            (r.id, r.prompt, r.max_new_tokens, r.eos_id)
            for r in self.queue
        ]
        payload = comm.bcast_obj(payload)
        if comm.process_index != 0:
            self.queue = deque(
                Request(p, m, id=i, eos_id=e) for i, p, m, e in payload
            )
            now = time.monotonic()
            for r in self.queue:
                r.submitted_at = now

    # -- one iteration --------------------------------------------------
    def _admit_joins(self, limit: Optional[int] = None) -> List[Request]:
        joins = []
        while self.queue and (limit is None or len(joins) < limit):
            r = self.queue[0]
            prefix = (
                self.engine.cache.lookup_prefix(r.prompt)
                if self.share_prefixes else None
            )
            if not self.engine.cache.can_admit(r.total_tokens,
                                               prefix=prefix):
                break
            self.queue.popleft()
            r.slot = self.engine.admit(r.total_tokens, prefix=prefix)
            r.shared_len = prefix.shared_len if prefix else 0
            if prefix is not None:
                self.prefix_hits += 1
                self.prefix_tokens_shared += prefix.shared_len
            r.state = RUNNING
            self.active[r.slot] = r
            joins.append(r)
        return joins

    # -- engine hooks (SpeculativeBatcher mirrors these onto its draft
    # engine's allocator, so the hook is the ONLY place slots move) ----
    def _release_slot(self, slot: int) -> None:
        self.engine.release(slot)

    def _evict_slot(self, slot: int) -> None:
        self.engine.cache.evict(slot)

    def _prefill_one(self, r: Request) -> np.ndarray:
        logits = self.engine.prefill(r.slot, r.prompt)
        if self.share_prefixes:
            self.engine.cache.register_prefix(r.slot, r.prompt)
        return logits

    def _retire(self, r: Request) -> None:
        slot = r.slot
        self._release_slot(slot)
        del self.active[slot]
        r.slot = None
        r.state = DONE
        r.done_at = time.monotonic()
        self.finished[r.id] = r

    def _fail(self, r: Request, why: str) -> None:
        if r.slot is not None and r.slot in self.active:
            slot = r.slot
            self._evict_slot(slot)
            del self.active[slot]
            r.slot = None
        r.state = FAILED
        r.error = why
        r.done_at = time.monotonic()
        self.finished[r.id] = r
        emit("request_failed", "serving.batcher", request=r.id, why=why)

    def _requeue(self, r: Request, why: str) -> None:
        """Retry path: evict, reset generated tokens (greedy decode
        replays bit-identically from the prompt) and re-queue at the
        front — bounded by ``max_retries``."""
        if r.slot is not None and r.slot in self.active:
            slot = r.slot
            self._evict_slot(slot)
            del self.active[slot]
            r.slot = None
        r.retries += 1
        if r.retries > self.max_retries:
            self._fail(r, f"retries exhausted after: {why}")
            return
        r.tokens = []
        r.state = QUEUED
        self.queue.appendleft(r)
        emit("request_retry", "serving.batcher", request=r.id,
             attempt=r.retries, why=why)

    def _check_timeouts(self) -> None:
        if self.timeout_s is None:
            return
        now = time.monotonic()
        overdue = [
            r for r in list(self.active.values()) + list(self.queue)
            if r.submitted_at is not None
            and now - r.submitted_at > self.timeout_s
        ]
        for r in overdue:
            if r in self.queue:
                self.queue.remove(r)
            emit("request_timeout", "serving.batcher", request=r.id,
                 waited=round(now - r.submitted_at, 3))
            self._fail(r, f"timeout after {self.timeout_s}s")

    def _decode_once(self) -> None:
        """One compiled decode step for the whole slot set, appending
        one token per active request (``SpeculativeBatcher`` overrides
        this with the draft-propose / target-verify iteration)."""
        toks = np.zeros((self.engine.capacity,), np.int32)
        for slot, r in self.active.items():
            toks[slot] = r.tokens[-1] if r.tokens else 0
        t0 = time.monotonic()
        logits = self.engine.decode_step(toks)
        t1 = time.monotonic()
        # every active request received one token this iteration: the
        # iteration wall IS the per-token latency sample
        for slot, r in list(self.active.items()):
            self.registry.histogram(
                "serving.token_latency").observe(t1 - t0)
            self._append_token(r, int(np.argmax(logits[slot])), t1)
            if r._finished():
                self._retire(r)

    def _append_token(self, r: Request, tok: int, t_now: float) -> None:
        r.tokens.append(int(tok))
        self.tokens_generated += 1
        if r.first_token_at is None:
            r.first_token_at = t_now
            if r.submitted_at is not None:
                self.registry.histogram("serving.ttft").observe(
                    t_now - r.submitted_at
                )
                # TTFT decomposes into time-in-queue (submit -> the
                # prefill/ingest starting) and time-in-prefill — the
                # split that tells disaggregation's A/B bench WHICH
                # term a role-pool change moved
                if r.prefill_started_at is not None:
                    self.registry.histogram(
                        "serving.ttft.queue").observe(max(
                            r.prefill_started_at - r.submitted_at, 0.0))
                    self.registry.histogram(
                        "serving.ttft.prefill").observe(max(
                            t_now - r.prefill_started_at, 0.0))

    def step(self) -> bool:
        """One serving iteration; returns True while work remains."""
        if not self.queue and not self.active:
            return False
        with _obs.span("serving.step", queued=len(self.queue),
                       active=len(self.active)):
            self._check_timeouts()
            try:
                # admit-and-prefill ONE request at a time: the prefill
                # registers the prompt's prefix chains, so later
                # requests in the same join wave already alias them
                # (a batch of identical system prompts shares from the
                # second request on, not from the next iteration)
                while True:
                    joins = self._admit_joins(limit=1)
                    if not joins:
                        break
                    r = joins[0]
                    t0 = time.monotonic()
                    r.prefill_started_at = t0
                    logits = self._prefill_one(r)
                    t1 = time.monotonic()
                    self.registry.histogram(
                        "serving.prefill_latency").observe(t1 - t0)
                    self._append_token(r, int(np.argmax(logits)), t1)
                    if r._finished():
                        self._retire(r)
                if self.active:
                    self._decode_once()
                    self.steps += 1
            except PreemptionError:
                # a preemption NOTICE is not a retryable fault — it is
                # the replica's drain signal.  In-flight slots stay
                # allocated (the drain snapshot wants the warm pages);
                # their requests stay unserved in the journal, so the
                # surviving world's next claim covers them.
                raise
            except ResilienceError as err:
                if not err.recoverable:
                    raise
                for r in list(self.active.values()):
                    self._requeue(r, f"{type(err).__name__}: {err}")
        return bool(self.queue or self.active)

    # -- handoff ingest (disaggregated serving) -------------------------
    def can_ingest(self, r: Request) -> bool:
        """Can a published handoff for ``r`` be admitted right now?
        (Fresh pages for the full reservation — imports never alias the
        exporter's pool, so there is no prefix discount to probe.)"""
        return self.engine.cache.can_admit(r.total_tokens)

    def ingest(self, r: Request, kv, first_token: int) -> Request:
        """Admit a prefill-pool handoff instead of prefilling: fresh
        pages, the exported KV copied in (prefix chain re-registered by
        the cache), and the prefill-produced first token appended — the
        request starts decoding exactly where a local prefill would
        have left it, so greedy decode is bit-identical from here.

        The ingest is the decode pool's prefill-phase analogue, so it
        stamps ``prefill_started_at`` (the TTFT split's second term)
        and lands in ``serving.ingest_latency``."""
        if r.total_tokens > self.engine.max_total:
            raise ValueError(
                f"{r.id}: needs {r.total_tokens} cache positions > "
                f"engine max_total={self.engine.max_total}"
            )
        t0 = time.monotonic()
        if r.submitted_at is None:
            r.submitted_at = t0
        r.prefill_started_at = t0
        slot = self.engine.ingest_kv(kv, r.total_tokens)
        r.slot = slot
        r.shared_len = 0
        r.state = RUNNING
        self.active[slot] = r
        t1 = time.monotonic()
        self.registry.histogram("serving.ingest_latency").observe(t1 - t0)
        self._append_token(r, int(first_token), t1)
        if r._finished():
            self._retire(r)
        return r

    # -- driving --------------------------------------------------------
    def run(self, max_steps: Optional[int] = None) -> Dict[str, Request]:
        """Drive :meth:`step` until the queue drains (or ``max_steps``
        iterations); returns finished requests by id."""
        n = 0
        self._sync_submissions()
        while self.step():
            n += 1
            if max_steps is not None and n >= max_steps:
                break
        return dict(self.finished)

    def serve(self, requests: Sequence[Request]) -> List[Request]:
        """Submit-and-run convenience; results in submission order."""
        reqs = list(requests)
        for r in reqs:
            self.submit(r)
        self.run()
        return [self.finished.get(r.id, r) for r in reqs]

    # -- reporting ------------------------------------------------------
    def latency_report(self) -> dict:
        """p50/p99 per serving phase from the batcher's own registry
        (present regardless of telemetry), plus the token/request
        counters — the fields decode_bench's rows and docs/serving.md's
        recipe read."""
        out = {
            "steps": self.steps,
            "tokens_generated": self.tokens_generated,
            "done": sum(1 for r in self.finished.values()
                        if r.state == DONE),
            "failed": sum(1 for r in self.finished.values()
                          if r.state == FAILED),
            "prefix_hits": self.prefix_hits,
            "prefix_tokens_shared": self.prefix_tokens_shared,
        }
        for name in ("serving.token_latency", "serving.ttft",
                     "serving.ttft.queue", "serving.ttft.prefill",
                     "serving.prefill_latency",
                     "serving.ingest_latency"):
            if not self.registry.has_histogram(name):
                continue
            h = self.registry.histogram(name)
            if len(h) == 0:
                continue
            out[name] = {
                "p50_ms": round(h.percentile(50) * 1e3, 4),
                "p99_ms": round(h.percentile(99) * 1e3, 4),
                "n": len(h),
            }
        return out

"""Speculative decode over the continuous batcher.

A small **draft** model proposes ``k`` greedy tokens per active slot;
the **target** model scores all ``k`` in ONE batched
:meth:`~chainermn_tpu.serving.decode.DecodeEngine.verify_step` over the
padded-slot program (shape ``(capacity, k)`` — fixed across request
join/leave, so membership churn never retraces, exactly like the plain
decode step).  Acceptance is **greedy-exact**: per slot the target's
argmax chain ``g_0..g_{k-1}`` is compared against the draft's
proposals, and the committed tokens are the longest matching prefix
plus the target's one corrected token — every committed token is a
TARGET argmax, so the served output is bit-identical to plain decode
**by construction**, whatever the draft proposes.  The draft only
moves the ACCEPTANCE RATE, i.e. how many of the 2-psum/layer verify
steps each output token amortizes.

Mechanics:

* **The draft rides the same allocator.**  The draft engine's
  :class:`~chainermn_tpu.serving.kv_cache.PagedKVCache` has the same
  geometry (capacity / page_size / pages_per_slot / num_pages) and
  receives the SAME deterministic op sequence (admit with the same
  prefix shape, release, evict) through the batcher's slot hooks, so
  draft and target agree on slot ids at every point — including under
  prefix sharing, where both caches maintain their own (structurally
  identical) prefix index.
* **Proposal.**  ``k`` single-token draft steps, run against the draft
  engine's compiled program directly; each step's advance is CLAMPED
  to the draft slot's reservation (a proposal past the reservation
  writes the null page — harmless garbage that verification simply
  rejects or truncation discards).
* **Rollback.**  The draft wrote ``[pending, proposals[:-1]]`` at
  positions ``base..base+k-1``; after the target commits ``a`` tokens
  the draft rewinds to ``base + a`` via
  :meth:`~chainermn_tpu.serving.kv_cache.PagedKVCache.rollback` —
  committed positions hold exactly the committed tokens (a committed
  token beyond the first IS its matching proposal), rejected positions
  are overwritten by the next iteration's writes before any masked
  attend can read them.  Target lengths advance by ``a`` the same way
  (``verify_step`` never auto-advances), keeping both caches in
  lockstep: ``lengths = prompt + len(tokens) - 1`` on both sides.
* **Warm start.**  A replica that warm-started its target cache from a
  drain snapshot calls :meth:`SpeculativeBatcher.mirror_adopted` —
  adopted slots are re-admitted into the draft cache AT the same slot
  id and re-prefilled with their committed token history, restoring
  the lockstep invariant without touching the target's bit-exact
  state.

The verify program's collective cost is pinned in
``analysis.budgets`` as ``spec_verify_step`` — still exactly 2
all-reduces per layer (the k tokens amortize the same psums), which is
the entire point: one verify step's collectives buy up to ``k``
tokens.
"""

from __future__ import annotations

import time
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..resilience import fault_injection as _fi
from .batcher import ContinuousBatcher, Request

_GEOMETRY = ("capacity", "page_size", "pages_per_slot")


class SpeculativeBatcher(ContinuousBatcher):
    """Continuous batching with draft-propose / target-verify decode.

    ``engine``: the target :class:`DecodeEngine` (paged layout).
    ``draft``: a second, typically much smaller ``DecodeEngine`` whose
    cache geometry matches the target's exactly.  ``k``: draft tokens
    proposed (and verify rows scored) per iteration; ``k=1`` degrades
    to plain decode plus a wasted draft step (useful as an A/B
    control).
    """

    def __init__(self, engine, draft, *, k: int = 4, **kw):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if getattr(engine, "layout", "paged") != "paged" or \
                getattr(draft, "layout", "paged") != "paged":
            raise ValueError(
                "speculative decode serves the paged layout (the dense "
                "oracle stays the plain-decode reference)"
            )
        for name in _GEOMETRY:
            a, b = getattr(engine, name), getattr(draft, name)
            if a != b:
                raise ValueError(
                    f"draft cache geometry must match target: "
                    f"{name}={b} vs target {a}"
                )
        if engine.cache.num_pages != draft.cache.num_pages:
            raise ValueError(
                f"draft cache geometry must match target: num_pages="
                f"{draft.cache.num_pages} vs target "
                f"{engine.cache.num_pages}"
            )
        super().__init__(engine, **kw)
        self.draft = draft
        self.k = int(k)
        # acceptance accounting: of the k proposals per slot-iteration,
        # k-1 are verifiable (row j checks proposal j-1); `accepted`
        # counts matches, so a draft that equals the target scores 1.0
        self.tokens_proposed = 0
        self.tokens_accepted = 0
        self.verify_steps = 0

    @property
    def acceptance_rate(self) -> float:
        return self.tokens_accepted / max(self.tokens_proposed, 1)

    # -- mirrored allocator hooks --------------------------------------
    def _admit_joins(self, limit: Optional[int] = None):
        joins = super()._admit_joins(limit=limit)
        for r in joins:
            prefix = (
                self.draft.cache.lookup_prefix(r.prompt)
                if self.share_prefixes else None
            )
            dslot = self.draft.admit(r.total_tokens, prefix=prefix)
            if dslot != r.slot:
                raise AssertionError(
                    f"draft allocator desynchronized: slot {dslot} "
                    f"vs target {r.slot}"
                )
        return joins

    def _release_slot(self, slot: int) -> None:
        super()._release_slot(slot)
        self.draft.release(slot)

    def _evict_slot(self, slot: int) -> None:
        super()._evict_slot(slot)
        self.draft.cache.evict(slot)

    def _prefill_one(self, r: Request) -> np.ndarray:
        logits = super()._prefill_one(r)
        self.draft.prefill(r.slot, r.prompt)
        if self.share_prefixes:
            self.draft.cache.register_prefix(r.slot, r.prompt)
        return logits

    def mirror_adopted(self) -> int:
        """Restore draft/target lockstep after a replica warm start:
        every target slot adopted from the drain snapshot is admitted
        into the draft cache at the SAME slot id and re-prefilled with
        its prompt + committed tokens (all but the pending last, which
        the next iteration feeds).  Returns the number of slots
        mirrored.  The target cache is not touched — its warm pages
        stay bit-exact."""
        mirrored = 0
        for slot in self.engine.cache._admit_order:
            r = self.active.get(slot)
            if r is None or self.draft.cache.active[slot]:
                continue
            self.draft.admit(r.total_tokens, slot=slot)
            history = r.prompt + r.tokens[:-1] if r.tokens else r.prompt
            self.draft.prefill(slot, history)
            if self.share_prefixes:
                self.draft.cache.register_prefix(slot, r.prompt)
            mirrored += 1
        return mirrored

    # -- the speculative iteration -------------------------------------
    def _draft_propose(self, cur: np.ndarray, active) -> np.ndarray:
        """One single-token draft step (direct program call: the
        advance is clamped to each slot's reservation, so end-of-
        request proposals overflow into the null page instead of
        raising — their garbage is rejected or truncated anyway)."""
        _fi.fire("serving.draft_step")
        d = self.draft
        toks = jnp.asarray(cur.reshape(d.capacity, 1))
        if d.layout == "paged":
            for s in active:
                d.cache.cow_for_write(s, 1)
        logits, k_out, v_out = d._fn(
            d.params, toks, d.cache.k_pages, d.cache.v_pages,
            d.cache.tables_array(), d.cache.lengths_array(),
        )
        d.cache.set_pages(k_out, v_out)
        for s in active:
            room = (len(d.cache._slot_pages[s]) * d.cache.page_size
                    - int(d.cache.lengths[s]))
            if room > 0:
                d.cache.advance(s, 1)
        return np.asarray(logits[:, 0])

    def _decode_once(self) -> None:
        active = dict(self.active)
        cap, k = self.engine.capacity, self.k
        dbase = {s: int(self.draft.cache.lengths[s]) for s in active}
        t0 = time.monotonic()
        # 1. draft proposes k greedy tokens per slot
        pending = np.zeros((cap,), np.int32)
        for s, r in active.items():
            pending[s] = r.tokens[-1] if r.tokens else 0
        proposals = np.zeros((cap, k), np.int32)
        cur = pending.copy()
        for j in range(k):
            dlogits = self._draft_propose(cur, active)
            for s in active:
                cur[s] = int(np.argmax(dlogits[s]))
                proposals[s, j] = cur[s]
        # 2. target scores all k rows in one batched step: row j
        #    conditions on [pending, proposals[:j]]
        ver = np.zeros((cap, k), np.int32)
        ver[:, 0] = pending
        if k > 1:
            ver[:, 1:] = proposals[:, : k - 1]
        logits = self.engine.verify_step(ver)
        t1 = time.monotonic()
        self.verify_steps += 1
        # 3. greedy-exact acceptance + lockstep advance/rollback
        for s, r in list(active.items()):
            g = [int(np.argmax(logits[s, j])) for j in range(k)]
            commit = [g[0]]
            for j in range(1, k):
                if int(proposals[s, j - 1]) != g[j - 1]:
                    break
                commit.append(g[j])
            self.tokens_proposed += k - 1
            self.tokens_accepted += len(commit) - 1
            appended = 0
            for tok in commit:
                if r._finished():
                    break
                self.registry.histogram(
                    "serving.token_latency").observe(t1 - t0)
                self._append_token(r, tok, t1)
                appended += 1
            self.engine.cache.advance(s, appended)
            self.draft.cache.rollback(s, dbase[s] + appended)
            if r._finished():
                self._retire(r)

    # -- reporting ------------------------------------------------------
    def latency_report(self) -> dict:
        out = super().latency_report()
        out["speculative"] = {
            "k": self.k,
            "verify_steps": self.verify_steps,
            "tokens_proposed": self.tokens_proposed,
            "tokens_accepted": self.tokens_accepted,
            "acceptance_rate": round(self.acceptance_rate, 4),
        }
        return out

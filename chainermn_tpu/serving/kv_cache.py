"""Paged KV cache for the serving tier.

vLLM-style paged attention state, TPU-shaped: the per-request KV cache
is not a contiguous ``(max_len, heads, d)`` buffer but a set of
fixed-size **pages** drawn from one shared pool, addressed through a
per-slot **block table**.  Continuous batching (``serving.batcher``)
needs exactly this: requests of wildly different lengths share one
compiled decode program (fixed slot count, fixed page pool) and memory
is bounded by the pool, not by ``capacity * max_len``.

Design points:

* **One stacked array per tensor.**  ``k_pages`` / ``v_pages`` are
  ``(n_layers, num_pages, page_size, n_heads, d_head)`` — a single
  pytree leaf, so the compiled decode step takes the whole cache as one
  donated operand and the checkpoint layer sees plain arrays.
* **Page 0 is the null page.**  Never allocated; inactive slots' block
  tables point at it, so the padded-slot decode program always reads
  and writes in-bounds (garbage it never uses) instead of branching.
* **Deterministic allocator.**  The free list is kept sorted ascending
  and admission reserves ``ceil(total_tokens / page_size)`` pages up
  front — the same request stream produces the same tables on every
  rank and every run (the block tables ride the compiled program's
  inputs, so nondeterminism here would desynchronize SPMD replicas).
  Reservation at admit also means a running request can never hit a
  mid-stream out-of-pages condition; the only failure point is
  admission, where the batcher can queue.  Pages are unit-granularity,
  so the pool cannot fragment: ``can_admit`` is exactly "enough free
  pages and a free slot" (pinned by test).
* **Copy-on-write prefix sharing.**  Pages carry refcounts.  A request
  whose prompt's page-aligned prefix hashes to an already-prefilled
  page run (``lookup_prefix`` over the ``register_prefix`` index) is
  admitted with its block table ALIASING those pages (refcount++) and
  only the tail freshly allocated — ``lengths`` starts at the shared
  length, so the batcher prefills only the remainder.  Writes into a
  still-shared page (the capped final page of a fully-matched prompt)
  go through ``cow_for_write``: the page is copied to a page reserved
  at admission, the writer's table entry swaps to the copy, and the
  original's refcount drops — a reader never observes another
  request's writes.  ``release``/``evict`` decrement and return a page
  to the free list only at refcount 0.  Sharing is pure host
  bookkeeping over the same deterministic allocator, so SPMD replicas
  stay in lockstep and the shared-prefix serve is bit-identical to the
  unshared oracle while ``used_pages`` (distinct pages) drops.
* **Deterministic eviction.**  ``choose_victim()`` names the most
  recently admitted active slot whose pages are ALL unshared
  (refcount 1) — LIFO over unshared slots only, so evicting the
  victim can never free or disturb a page a live request still reads
  (``check_invariants`` pins that a victim holds no refcount>1 page).
  ``evict()`` releases a slot's pages and returns them to the sorted
  free list; the batcher re-queues the request (greedy decode replays
  bit-identically from the prompt).
* **Checkpoint round-trip.**  ``state_dict()`` is a flat dict of
  arrays that the existing checkpoint layer
  (``extensions.checkpoint``) snapshots as-is; ``load_state_dict``
  reconstructs the allocator's host state (free list, per-slot page
  ownership) from the saved tables — a replica warm-starts with its
  pages and in-flight lengths intact.
* **TP resharding.**  Pages shard over the tensor-parallel axis by
  heads (dimension 3).  :func:`reshard_kv_state` re-splits a saved
  N-shard cache onto M shards bit-identically to a fresh split of the
  concatenated global cache — the serving analogue of
  ``resilience.elastic.reshard_state``'s ZeRO block rule.
* **Delta snapshots.**  Every mutation path marks the pages it touches
  dirty (``admit``'s fresh+CoW-reserve pages, ``cow_for_write``'s copy
  target, ``advance``'s written range, ``import_kv``'s copied pages);
  :meth:`delta_state_dict` ships ONLY the pages dirtied since the last
  marker — plus the full host accounting (tables, refcounts, CoW
  reserves), which is tiny — under a sha256 digest, and
  :meth:`apply_delta` installs it onto a replica at the same base
  marker, bit-identical to a full snapshot.  This is what rides the
  peer-RAM recovery tier (``resilience.peer_ckpt``): a serving replica
  re-replicates per drain window at delta cost, not pool cost.
"""

from __future__ import annotations

import hashlib
import math
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

NULL_PAGE = 0


class KVExport(NamedTuple):
    """One request's KV state, gathered out of the page pool into a
    dense contiguous handoff buffer (:meth:`PagedKVCache.export_kv`).

    ``k``/``v`` are page-major ``(n_layers, n_pages, page_size,
    n_heads, d_head)`` host arrays holding the slot's pages BY VALUE in
    table order — prefix-shared pages are copied like private ones, so
    the buffer is self-contained and the importer owes the exporter's
    pool nothing.  ``length`` is the valid cache positions (positions
    past it are prefill-bucket padding the masked attend never reads).
    ``prefix_chain`` is the page-aligned sha1 chain-hash run registered
    for this slot's prompt (possibly empty, always prefix-closed), so
    an importer can re-register sharing without re-hashing tokens."""

    k: "np.ndarray"
    v: "np.ndarray"
    length: int
    page_size: int
    dtype: str
    prefix_chain: Tuple[str, ...]


class PrefixMatch(NamedTuple):
    """A prefix-index hit: the page run to alias at admission.

    ``pages``: the existing pages, in table order.  ``shared_len``:
    cache positions the aliasing slot starts with (its ``lengths``
    value at admit — capped at one BELOW the new prompt's length so the
    tail prefill always has a token to produce logits from).  ``cow``:
    the cap landed mid-page, so the final aliased page will be written
    and a copy-on-write page must be reserved at admission."""

    pages: Tuple[int, ...]
    shared_len: int
    cow: bool


def _chain_hash(prev: str, chunk: Sequence[int]) -> str:
    """Deterministic cumulative hash of page-aligned token chunks
    (sha1, not ``hash()`` — PYTHONHASHSEED must not desynchronize SPMD
    replicas' admission schedules)."""
    data = prev + ":" + ",".join(str(int(t)) for t in chunk)
    return hashlib.sha1(data.encode()).hexdigest()


class CacheAdmissionError(RuntimeError):
    """A request was admitted past ``can_admit`` — pool or slots
    exhausted.  The batcher never triggers this (it checks first); a
    direct caller sees a loud error instead of a corrupted table."""


def pages_needed(total_tokens: int, page_size: int) -> int:
    """Pages a request occupying ``total_tokens`` cache positions needs
    (its prompt plus every generated token except the last, which is
    sampled but never written — callers pass prompt + max_new_tokens
    and over-reserve by at most one token's worth)."""
    return max(1, math.ceil(total_tokens / page_size))


class PagedKVCache:
    """The page pool, block tables, and allocator for one replica.

    ``capacity`` decode slots share ``num_pages`` pages of
    ``page_size`` tokens each (page 0 reserved as the null page).
    ``pages_per_slot`` bounds one request's table row — the static
    width of the compiled program's table operand.
    """

    def __init__(self, *, n_layers: int, n_heads: int, d_head: int,
                 capacity: int, page_size: int = 16,
                 num_pages: Optional[int] = None,
                 pages_per_slot: Optional[int] = None,
                 dtype=jnp.bfloat16):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.n_layers = int(n_layers)
        self.n_heads = int(n_heads)
        self.d_head = int(d_head)
        self.capacity = int(capacity)
        self.page_size = int(page_size)
        if pages_per_slot is None:
            pages_per_slot = 8
        self.pages_per_slot = int(pages_per_slot)
        if num_pages is None:
            # enough for every slot to hold a full-length request, + null
            num_pages = capacity * self.pages_per_slot + 1
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is null)")
        self.num_pages = int(num_pages)
        self.dtype = dtype
        shape = (self.n_layers, self.num_pages, self.page_size,
                 self.n_heads, self.d_head)
        self.k_pages = jnp.zeros(shape, dtype)
        self.v_pages = jnp.zeros(shape, dtype)
        # host-side allocator state (numpy: tables ship as step inputs)
        self.block_tables = np.full(
            (self.capacity, self.pages_per_slot), NULL_PAGE, np.int32
        )
        self.lengths = np.zeros((self.capacity,), np.int32)
        self.active = np.zeros((self.capacity,), bool)
        self._free_pages: List[int] = list(range(1, self.num_pages))
        self._slot_pages: Dict[int, List[int]] = {}
        # admission order (slot ids, oldest first) — the deterministic
        # eviction victim is the tail
        self._admit_order: List[int] = []
        # per-page refcounts: 0 = free, 1 = privately owned, >1 =
        # prefix-shared across slots.  Pages return to the free list
        # only at refcount 0.
        self._refcounts = np.zeros((self.num_pages,), np.int32)
        # prefix index: chain hash of page-aligned prompt chunks ->
        # (page run, token count).  Entries drop when any of their
        # pages is freed (the content is gone).
        self._prefix_index: Dict[str, Tuple[Tuple[int, ...], int]] = {}
        # per-slot page reserved at a capped alias-admission for the
        # inevitable copy-on-write into the final shared page —
        # earmarked so a running request never hits mid-stream
        # out-of-pages (the allocator's no-midstream-failure contract)
        self._cow_reserve: Dict[int, int] = {}
        # delta-snapshot tracking: pages whose CONTENT may have changed
        # since the last delta marker.  Over-inclusive marking is safe
        # (a clean page shipped twice is wasted bytes); under-inclusive
        # is corruption — so every mutation path marks eagerly.
        self._dirty: set = set()
        self._delta_marker = 0

    # -- pool accounting ------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free_pages)

    @property
    def used_pages(self) -> int:
        """DISTINCT pages currently allocated (a prefix-shared page
        counts once however many block tables alias it) — the quantity
        prefix sharing exists to shrink."""
        return self.num_pages - 1 - len(self._free_pages)

    @property
    def free_slots(self) -> List[int]:
        return [s for s in range(self.capacity) if not self.active[s]]

    def utilization(self) -> float:
        """Fraction of allocatable pages currently owned by a slot."""
        return self.used_pages / max(self.num_pages - 1, 1)

    def check_invariants(self) -> None:
        """Allocator invariants, asserted by tests after every op mix:
        refcounts match table ownership exactly, null page never owned,
        conservation (distinct owned + CoW reserves + free == pool),
        free list sorted (determinism), tables consistent with
        ownership, the prefix index only names live pages — and the
        deterministic eviction victim never holds a shared page, so
        evicting it can never free a refcount>1 page."""
        owner_count: Dict[int, int] = {}
        for slot, pages in self._slot_pages.items():
            assert self.active[slot], f"slot {slot} owns pages inactive"
            assert NULL_PAGE not in pages, "null page allocated"
            assert len(set(pages)) == len(pages), "page twice in a slot"
            assert list(self.block_tables[slot][: len(pages)]) == pages
            for p in pages:
                owner_count[p] = owner_count.get(p, 0) + 1
        reserved = set(self._cow_reserve.values())
        assert len(reserved) == len(self._cow_reserve)
        for slot, p in self._cow_reserve.items():
            assert slot in self._slot_pages, "CoW reserve w/o slot"
            assert p != NULL_PAGE and p not in owner_count
            assert int(self._refcounts[p]) == 1
        for p, n in owner_count.items():
            assert int(self._refcounts[p]) == n, f"refcount drift: {p}"
        free = set(self._free_pages)
        assert not free & set(owner_count), "free page owned"
        assert not free & reserved, "free page reserved"
        assert all(int(self._refcounts[p]) == 0 for p in free)
        assert (len(owner_count) + len(reserved) + len(free)
                == self.num_pages - 1)
        assert self._free_pages == sorted(self._free_pages)
        assert sorted(self._admit_order) == sorted(self._slot_pages)
        victim = self.choose_victim()
        if victim is not None:
            assert all(int(self._refcounts[p]) == 1
                       for p in self._slot_pages[victim]), \
                "eviction victim holds a shared page"
        for h, (pages, ntok) in self._prefix_index.items():
            assert ntok % self.page_size == 0
            assert len(pages) == ntok // self.page_size
            assert all(int(self._refcounts[p]) >= 1 for p in pages), \
                "prefix index names a freed page"

    # -- prefix index ---------------------------------------------------
    def register_prefix(self, slot: int, tokens: Sequence[int]) -> int:
        """Index ``slot``'s page-aligned prompt prefixes for future
        cross-request sharing (call after the prompt is prefilled, so
        the pages actually hold the hashed tokens).  Every fully
        page-aligned prefix is registered — all such pages sit strictly
        below the slot's write frontier (prefill writes all
        ``len(tokens)`` prompt positions; decode writes continue AT
        position ``len(tokens)``), so registered pages are immutable
        for the registrant's lifetime and only ALIASING slots — which
        carry a CoW reserve from admission — can ever need
        copy-on-write.  First registration of a chain wins; returns
        the number of NEW chain entries."""
        if slot not in self._slot_pages:
            raise KeyError(f"slot {slot} owns no pages")
        tokens = [int(t) for t in tokens]
        pages = self._slot_pages[slot]
        added, h = 0, ""
        for m in range(1, len(tokens) // self.page_size + 1):
            h = _chain_hash(
                h, tokens[(m - 1) * self.page_size: m * self.page_size]
            )
            if h not in self._prefix_index:
                self._prefix_index[h] = (
                    tuple(pages[:m]), m * self.page_size
                )
                added += 1
        return added

    def lookup_prefix(self, tokens: Sequence[int]) -> Optional[PrefixMatch]:
        """Longest indexed page-aligned prefix of ``tokens``, capped at
        ``len(tokens) - 1`` so the tail prefill always has at least one
        token (a fully-matched prompt aliases ALL its pages but starts
        one position short and copy-on-writes the final page).  Chains
        are prefix-closed (``register_prefix`` adds every prefix), so
        the scan stops at the first missing link."""
        tokens = [int(t) for t in tokens]
        if len(tokens) < 2 or not self._prefix_index:
            return None
        best, h = None, ""
        for m in range(1, len(tokens) // self.page_size + 1):
            h = _chain_hash(
                h, tokens[(m - 1) * self.page_size: m * self.page_size]
            )
            hit = self._prefix_index.get(h)
            if hit is None:
                break
            best = hit
        if best is None:
            return None
        pages, ntok = best
        shared_len = min(ntok, len(tokens) - 1)
        return PrefixMatch(tuple(pages), shared_len,
                           shared_len % self.page_size != 0)

    def _drop_index_entries(self, freed: Sequence[int]) -> None:
        gone = set(freed)
        if not gone:
            return
        self._prefix_index = {
            h: e for h, e in self._prefix_index.items()
            if not gone & set(e[0])
        }

    # -- admission ------------------------------------------------------
    def can_admit(self, total_tokens: int,
                  prefix: Optional[PrefixMatch] = None) -> bool:
        need = pages_needed(total_tokens, self.page_size)
        if need > self.pages_per_slot:
            return False
        if prefix is not None:
            need = need - len(prefix.pages) + (1 if prefix.cow else 0)
        return bool(self.free_slots) and need <= len(self._free_pages)

    def admit(self, total_tokens: int,
              prefix: Optional[PrefixMatch] = None,
              slot: Optional[int] = None) -> int:
        """Reserve a slot and its pages; returns the slot id.  The
        lowest free slot and the lowest free pages are taken (sorted
        free list), so admission is a pure function of allocator
        state.  With ``prefix`` (a :meth:`lookup_prefix` hit), the
        slot's table ALIASES the matched pages (refcount++), only the
        tail is freshly allocated, and ``lengths`` starts at the
        shared length — the caller prefills just the remainder.  A
        capped match additionally earmarks one copy-on-write page.
        An explicit ``slot`` (must be free) overrides the lowest-free
        choice — the speculative batcher uses it to mirror a
        warm-started target's slot layout onto its draft cache."""
        need = pages_needed(total_tokens, self.page_size)
        if need > self.pages_per_slot:
            raise CacheAdmissionError(
                f"request needs {need} pages > pages_per_slot="
                f"{self.pages_per_slot} (total_tokens={total_tokens})"
            )
        free = self.free_slots
        if not free:
            raise CacheAdmissionError("no free decode slot")
        if slot is not None:
            if slot not in free:
                raise CacheAdmissionError(f"slot {slot} is not free")
            free = [int(slot)]
        shared: List[int] = []
        shared_len = 0
        reserve: Optional[int] = None
        if prefix is not None:
            shared = list(prefix.pages)
            shared_len = int(prefix.shared_len)
            if shared_len >= total_tokens or len(shared) > need:
                raise CacheAdmissionError(
                    f"prefix ({len(shared)} pages / {shared_len} "
                    f"tokens) does not fit total_tokens={total_tokens}"
                )
            if any(int(self._refcounts[p]) < 1 for p in shared):
                raise CacheAdmissionError(
                    "stale prefix: an aliased page was freed"
                )
        n_fresh = need - len(shared)
        n_take = n_fresh + (1 if prefix is not None and prefix.cow else 0)
        if n_take > len(self._free_pages):
            raise CacheAdmissionError(
                f"need {n_take} pages, {len(self._free_pages)} free"
            )
        slot = free[0]
        fresh = self._free_pages[:n_fresh]
        if prefix is not None and prefix.cow:
            reserve = self._free_pages[n_fresh]
        self._free_pages = self._free_pages[n_take:]
        pages = shared + fresh
        for p in shared:
            self._refcounts[p] += 1
        for p in fresh:
            self._refcounts[p] = 1
        if reserve is not None:
            self._cow_reserve[slot] = reserve
            self._refcounts[reserve] = 1
        # fresh pages (and the CoW reserve) will be written by the
        # admitting request's prefill/decode — dirty from admission;
        # aliased prefix pages stay clean (their content predates this
        # admit and is never written through this slot un-copied)
        self._dirty.update(fresh)
        if reserve is not None:
            self._dirty.add(reserve)
        self._slot_pages[slot] = pages
        self.block_tables[slot, :] = NULL_PAGE
        self.block_tables[slot, : len(pages)] = pages
        self.lengths[slot] = shared_len
        self.active[slot] = True
        self._admit_order.append(slot)
        return slot

    def release(self, slot: int) -> None:
        """Decrement the slot's pages; return refcount-0 pages (and the
        slot's unspent CoW reserve) to the pool.  Prefix-index entries
        naming a freed page are dropped — the content is gone."""
        if not self.active[slot]:
            raise KeyError(f"slot {slot} is not active")
        pages = self._slot_pages.pop(slot)
        freed: List[int] = []
        for p in pages:
            self._refcounts[p] -= 1
            if int(self._refcounts[p]) == 0:
                freed.append(p)
        reserve = self._cow_reserve.pop(slot, None)
        if reserve is not None:
            self._refcounts[reserve] = 0
            freed.append(reserve)
        self._free_pages = sorted(self._free_pages + freed)
        self._drop_index_entries(freed)
        self.block_tables[slot, :] = NULL_PAGE
        self.lengths[slot] = 0
        self.active[slot] = False
        self._admit_order.remove(slot)

    def choose_victim(self) -> Optional[int]:
        """Deterministic eviction victim: the most recently admitted
        active slot whose pages are ALL unshared (refcount 1) — LIFO
        over unshared slots only, so eviction never disturbs a page
        another live request reads.  ``None`` when every active slot
        holds a shared page (the batcher queues instead)."""
        for slot in reversed(self._admit_order):
            if all(int(self._refcounts[p]) == 1
                   for p in self._slot_pages[slot]):
                return slot
        return None

    def evict(self, slot: int) -> None:
        """Same pool effect as :meth:`release`; named separately so the
        batcher's logs distinguish retire from preempt."""
        self.release(slot)

    def cow_for_write(self, slot: int, n: int = 1) -> bool:
        """Copy-on-write hook: call BEFORE a compiled step writes ``n``
        cache positions at ``lengths[slot]``.  If any written position
        lands in a refcount>1 page, that page is copied into the
        reserve earmarked at admission, the slot's table entry swaps to
        the copy, and the original's refcount drops — other aliasing
        slots keep reading the original untouched.  Returns True if a
        copy happened.  Only the capped final page of an aliased run
        can ever be shared at write time (fresh tail pages are private
        by construction), so one reserve per slot suffices."""
        if not self.active[slot]:
            raise KeyError(f"slot {slot} is not active")
        pages = self._slot_pages[slot]
        start = int(self.lengths[slot])
        first_pg = start // self.page_size
        last_pg = min((start + int(n) - 1) // self.page_size,
                      len(pages) - 1)
        copied = False
        for i in range(first_pg, last_pg + 1):
            p = pages[i]
            if int(self._refcounts[p]) <= 1:
                continue
            q = self._cow_reserve.pop(slot, None)
            if q is None:
                raise CacheAdmissionError(
                    f"slot {slot} must write shared page {p} but holds "
                    "no CoW reserve"
                )
            self.k_pages = self.k_pages.at[:, q].set(self.k_pages[:, p])
            self.v_pages = self.v_pages.at[:, q].set(self.v_pages[:, p])
            pages[i] = q
            self.block_tables[slot, i] = q
            self._refcounts[p] -= 1
            self._dirty.add(q)
            copied = True
        return copied

    def advance(self, slot: int, n: int = 1) -> None:
        """Account ``n`` more cache positions written for ``slot``.
        Tripwire: the written range must not cover a still-shared page
        (the engine calls :meth:`cow_for_write` before the step)."""
        if not self.active[slot]:
            raise KeyError(f"slot {slot} is not active")
        old = int(self.lengths[slot])
        new = old + n
        pages = self._slot_pages[slot]
        if new > len(pages) * self.page_size:
            raise CacheAdmissionError(
                f"slot {slot} advanced past its {len(pages)}"
                f"-page reservation ({new} tokens)"
            )
        for i in range(old // self.page_size,
                       (max(new - 1, old)) // self.page_size + 1):
            if int(self._refcounts[pages[i]]) > 1:
                raise CacheAdmissionError(
                    f"slot {slot} wrote into shared page {pages[i]} "
                    "without copy-on-write"
                )
            # the advanced-over range was just written by the step
            self._dirty.add(int(pages[i]))
        self.lengths[slot] = new

    def rollback(self, slot: int, length: int) -> None:
        """Rewind ``lengths[slot]`` to ``length`` (< current) —
        speculative decode discards rejected draft positions.  Pages
        are NOT freed (the reservation is untouched; stale positions
        are simply overwritten by the next write, exactly as the padded
        decode program already overwrites junk past ``lengths``)."""
        if not self.active[slot]:
            raise KeyError(f"slot {slot} is not active")
        length = int(length)
        if length < 0 or length > int(self.lengths[slot]):
            raise ValueError(
                f"rollback to {length} outside [0, {int(self.lengths[slot])}]"
            )
        self.lengths[slot] = length

    # -- prefill/decode handoff (disaggregated serving) ----------------
    def export_kv(self, slot: int) -> KVExport:
        """Gather ``slot``'s pages — through the block table, prefix-
        shared pages included by value — into a dense contiguous
        :class:`KVExport` handoff buffer.  The slot itself is untouched
        (still active, still owning its pages): export is a read, so a
        prefill replica can publish the handoff and only then release.

        The prefix chain rides along so the importer can re-register
        page-aligned sharing (:meth:`import_kv`): for each page-aligned
        prefix depth of the slot's valid positions, the chain hash this
        cache's index maps to exactly that page run.  The scan stops at
        the first unindexed depth — chains must stay prefix-closed or
        ``lookup_prefix``'s first-missing-link scan would never reach
        the deeper entries."""
        if not self.active[slot]:
            raise KeyError(f"slot {slot} is not active")
        pages = self._slot_pages[slot]
        length = int(self.lengths[slot])
        idx = np.asarray(pages, np.int64)
        k = np.asarray(self.k_pages[:, idx])
        v = np.asarray(self.v_pages[:, idx])
        by_entry = {e: h for h, e in self._prefix_index.items()}
        chain: List[str] = []
        for m in range(1, length // self.page_size + 1):
            h = by_entry.get((tuple(pages[:m]), m * self.page_size))
            if h is None:
                break
            chain.append(h)
        return KVExport(
            k=k, v=v, length=length, page_size=self.page_size,
            dtype=jnp.dtype(self.dtype).name,
            prefix_chain=tuple(chain),
        )

    def import_kv(self, kv: KVExport, total_tokens: int,
                  slot: Optional[int] = None) -> int:
        """Admit a handoff into THIS cache: a fresh reservation for
        ``total_tokens`` (the request's prompt + max_new budget, same
        number the exporter admitted with), the buffer's pages copied
        in by value, ``lengths`` set to the exported valid positions —
        bit-identical to having prefilled locally.  The exported prefix
        chain re-registers against the NEW pages (first registration
        wins, exactly like :meth:`register_prefix`), so later requests
        admitted here alias the imported pages without re-prefilling.
        Returns the slot id; raises :class:`CacheAdmissionError` via
        :meth:`admit` when the pool cannot take it (callers gate on
        :meth:`can_admit`)."""
        if int(kv.page_size) != self.page_size:
            raise ValueError(
                f"handoff page_size {kv.page_size} != this cache's "
                f"{self.page_size} (role pools must share page geometry)"
            )
        if jnp.dtype(kv.dtype) != jnp.dtype(self.dtype):
            raise ValueError(
                f"handoff dtype {kv.dtype} != cache dtype "
                f"{jnp.dtype(self.dtype).name}"
            )
        want = (self.n_layers, self.page_size, self.n_heads, self.d_head)
        got = tuple(np.shape(kv.k))
        if len(got) != 5 or (got[0], got[2], got[3], got[4]) != want:
            raise ValueError(
                f"handoff buffer shape {got} does not match cache "
                f"geometry (n_layers, *, page_size, n_heads, d_head)="
                f"{want}"
            )
        length = int(kv.length)
        if length > int(total_tokens):
            raise ValueError(
                f"handoff holds {length} positions > total_tokens="
                f"{total_tokens}"
            )
        if pages_needed(length, self.page_size) > got[1]:
            raise ValueError(
                f"handoff claims {length} positions but ships only "
                f"{got[1]} pages"
            )
        slot = self.admit(int(total_tokens), slot=slot)
        pages = self._slot_pages[slot]
        n_copy = min(len(pages), got[1])
        self._dirty.update(int(p) for p in pages[:n_copy])
        idx = np.asarray(pages[:n_copy], np.int64)
        self.k_pages = self.k_pages.at[:, idx].set(
            jnp.asarray(kv.k[:, :n_copy], self.dtype)
        )
        self.v_pages = self.v_pages.at[:, idx].set(
            jnp.asarray(kv.v[:, :n_copy], self.dtype)
        )
        self.lengths[slot] = length
        for m, h in enumerate(kv.prefix_chain, start=1):
            if m > n_copy or m * self.page_size > length:
                break
            if h not in self._prefix_index:
                self._prefix_index[h] = (
                    tuple(pages[:m]), m * self.page_size
                )
        return slot

    # -- arrays for the compiled step ----------------------------------
    def tables_array(self) -> jnp.ndarray:
        return jnp.asarray(self.block_tables)

    def lengths_array(self) -> jnp.ndarray:
        return jnp.asarray(self.lengths)

    def set_pages(self, k_pages, v_pages) -> None:
        """Install the decode step's updated page arrays (functional
        update — the step returns fresh arrays)."""
        self.k_pages, self.v_pages = k_pages, v_pages

    # -- checkpoint round-trip -----------------------------------------
    def state_dict(self) -> dict:
        """Flat array dict the checkpoint layer snapshots as-is.  Slot
        page counts make the table rows reconstructible (a table row is
        padded with the null page, which a real reservation never
        contains)."""
        counts = np.array(
            [len(self._slot_pages.get(s, ())) for s in range(self.capacity)],
            np.int32,
        )
        order = np.array(self._admit_order, np.int32)
        # fixed (capacity, 2) shape — NEVER zero-size (a 0-row array
        # fails the orbax backend, silently degrading the checkpoint
        # to the npz fallback, which cannot round-trip bfloat16 pages)
        reserve = np.full((self.capacity, 2), -1, np.int32)
        for i, (s, p) in enumerate(sorted(self._cow_reserve.items())):
            reserve[i] = (s, p)
        return {
            "k_pages": self.k_pages,
            "v_pages": self.v_pages,
            "block_tables": self.block_tables.copy(),
            "lengths": self.lengths.copy(),
            "active": self.active.astype(np.int8),
            "slot_page_counts": counts,
            "admit_order": order,
            # prefix sharing: refcounts are derivable from table
            # multiplicity + reserves, but saved anyway so warm start
            # cross-checks the snapshot (and readers can inspect
            # sharing without replaying the allocator)
            "page_refcounts": self._refcounts.copy(),
            "cow_reserve": reserve,
        }

    def load_state_dict(self, state: dict) -> None:
        """Rebuild pool + allocator from a snapshot (warm start)."""
        k = state["k_pages"]
        # validate against the CURRENT pool arrays, not the configured
        # paged geometry — the dense-oracle engine replaces the pool
        # with its contiguous per-slot layout, and its own snapshot
        # must round-trip too
        want = tuple(np.shape(self.k_pages))
        if tuple(np.shape(k)) != want:
            raise ValueError(
                f"cache shape mismatch: snapshot {tuple(np.shape(k))} "
                f"vs this cache's {want}"
            )
        self.k_pages = jnp.asarray(k, self.dtype)
        self.v_pages = jnp.asarray(state["v_pages"], self.dtype)
        self._load_host_accounting(state)
        self.check_invariants()

    def _load_host_accounting(self, state: dict) -> None:
        """Rebuild the allocator's host state (tables, free list, slot
        ownership, refcounts with cross-check) from a snapshot's
        accounting arrays — shared by the full and delta restore
        paths, so the two cannot drift apart."""
        self.block_tables = np.asarray(
            state["block_tables"], np.int32
        ).reshape(self.capacity, self.pages_per_slot).copy()
        self.lengths = np.asarray(
            state["lengths"], np.int32).reshape(self.capacity).copy()
        self.active = np.asarray(
            state["active"]).reshape(self.capacity).astype(bool)
        counts = np.asarray(state["slot_page_counts"], np.int32)
        self._slot_pages = {
            s: [int(p) for p in self.block_tables[s, : int(counts[s])]]
            for s in range(self.capacity) if self.active[s]
        }
        reserve = np.asarray(
            state.get("cow_reserve", np.zeros((0, 2))), np.int32
        ).reshape(-1, 2)
        self._cow_reserve = {int(s): int(p) for s, p in reserve
                             if int(s) >= 0}
        # refcounts are DERIVED from table multiplicity + reserves (the
        # tables are the ground truth a legacy snapshot also carries);
        # a snapshot that saved them is cross-checked below
        self._refcounts = np.zeros((self.num_pages,), np.int32)
        for pages in self._slot_pages.values():
            for p in pages:
                self._refcounts[p] += 1
        for p in self._cow_reserve.values():
            self._refcounts[p] = 1
        if "page_refcounts" in state:
            saved = np.asarray(
                state["page_refcounts"], np.int32
            ).reshape(self.num_pages)
            if not np.array_equal(saved, self._refcounts):
                raise ValueError(
                    "snapshot page_refcounts disagree with block tables"
                )
        used = {p for pages in self._slot_pages.values() for p in pages}
        used |= set(self._cow_reserve.values())
        self._free_pages = sorted(
            set(range(1, self.num_pages)) - used
        )
        self._admit_order = [
            int(s) for s in np.asarray(state["admit_order"], np.int32)
        ]
        # the prefix index is NOT snapshotted: entries are an optimistic
        # lookup structure over live pages, and a warm-started replica
        # rebuilds them as adopted requests re-register (replica layer)
        self._prefix_index = {}

    # -- delta snapshots -----------------------------------------------
    _DELTA_ACCOUNTING = ("block_tables", "lengths", "active",
                         "slot_page_counts", "admit_order",
                         "page_refcounts", "cow_reserve")

    def delta_base_mark(self, value: Optional[int] = None) -> int:
        """Establish a delta base: the point deltas ship FROM.  With no
        ``value``, advance this cache's marker and clear the dirty set
        (call right after taking/holding a full snapshot); with one,
        adopt the sender's marker (call right after installing that
        full snapshot on a replica) — both sides then agree on what
        "since the last marker" means.  Returns the marker."""
        if value is None:
            self._delta_marker += 1
        else:
            self._delta_marker = int(value)
        self._dirty.clear()
        return self._delta_marker

    def _delta_digest(self, delta: dict) -> str:
        """sha256 over the delta's exact content in a fixed key order —
        the integrity check :meth:`apply_delta` verifies, mirroring the
        snapshot tier's per-file digests."""
        h = hashlib.sha256()
        h.update(f"base={int(delta['base_marker'])}"
                 f":marker={int(delta['marker'])}".encode())
        for name in ("page_ids", "k_delta", "v_delta",
                     *self._DELTA_ACCOUNTING):
            arr = np.ascontiguousarray(np.asarray(delta[name]))
            h.update(f":{name}:{arr.shape}:{arr.dtype.str}:".encode())
            h.update(arr.tobytes())
        return h.hexdigest()

    def delta_state_dict(self) -> dict:
        """Incremental snapshot: ONLY the pages dirtied since the last
        marker (content), plus the complete host accounting (tables,
        lengths, refcounts, CoW reserves — tiny next to page bytes) and
        a sha256 digest over the exact shipped content.  Advances the
        marker: the next delta ships on top of this one, and a replica
        applies deltas in marker order (:meth:`apply_delta` rejects a
        base mismatch loudly)."""
        ids = np.asarray(sorted(int(p) for p in self._dirty), np.int64)
        full = self.state_dict()
        delta = {
            "base_marker": int(self._delta_marker),
            "marker": int(self._delta_marker) + 1,
            "page_ids": ids,
            "k_delta": np.asarray(self.k_pages)[:, ids],
            "v_delta": np.asarray(self.v_pages)[:, ids],
            **{name: full[name] for name in self._DELTA_ACCOUNTING},
        }
        delta["digest"] = self._delta_digest(delta)
        self._delta_marker += 1
        self._dirty.clear()
        return delta

    def apply_delta(self, delta: dict) -> None:
        """Install a :meth:`delta_state_dict` onto this cache.  The
        digest is verified first (a tampered or torn delta raises
        ``ValueError`` before any state mutates), then the base marker
        must equal this cache's marker (deltas apply in order on top of
        the snapshot they were cut from), then the shipped pages land
        at their ids, the host accounting is rebuilt exactly as a full
        restore would, and the invariants are re-checked.  The result
        is bit-identical to loading the sender's full ``state_dict``
        (pinned by test)."""
        if self._delta_digest(delta) != delta.get("digest"):
            raise ValueError(
                "delta digest mismatch: snapshot delta is torn or "
                "tampered"
            )
        if int(delta["base_marker"]) != int(self._delta_marker):
            raise ValueError(
                f"delta base marker {int(delta['base_marker'])} does "
                f"not match this cache's marker {self._delta_marker}: "
                "deltas apply in order on top of their base snapshot"
            )
        ids = np.asarray(delta["page_ids"], np.int64)
        if ids.size:
            self.k_pages = self.k_pages.at[:, ids].set(
                jnp.asarray(delta["k_delta"], self.dtype)
            )
            self.v_pages = self.v_pages.at[:, ids].set(
                jnp.asarray(delta["v_delta"], self.dtype)
            )
        self._load_host_accounting(delta)
        self._delta_marker = int(delta["marker"])
        self._dirty.clear()
        self.check_invariants()


def reshard_kv_state(states: Sequence[dict], new_world: int) -> List[dict]:
    """Re-split an N-shard paged cache (heads axis) onto M shards.

    ``states``: one :meth:`PagedKVCache.state_dict` per old TP rank, in
    rank order (each holding ``H/N`` heads of the same pool).  The host
    allocator state (tables, lengths, free list) is replicated across
    TP ranks by construction, so rank 0's is kept.  The result is
    bit-identical to splitting the concatenated global cache fresh —
    pages are re-cut on the heads dimension only, block tables never
    move (pinned by test)."""
    if not states:
        raise ValueError("reshard_kv_state needs at least one shard")
    new_world = int(new_world)
    k_full = np.concatenate(
        [np.asarray(s["k_pages"]) for s in states], axis=3
    )
    v_full = np.concatenate(
        [np.asarray(s["v_pages"]) for s in states], axis=3
    )
    heads = k_full.shape[3]
    if heads % new_world:
        raise ValueError(
            f"{heads} global heads do not split over {new_world} shards"
        )
    out = []
    for r in range(new_world):
        sl = slice(r * heads // new_world, (r + 1) * heads // new_world)
        shard = dict(states[0])
        shard["k_pages"] = k_full[:, :, :, sl]
        shard["v_pages"] = v_full[:, :, :, sl]
        out.append(shard)
    return out

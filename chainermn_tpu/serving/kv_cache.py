"""Paged KV cache for the serving tier.

vLLM-style paged attention state, TPU-shaped: the per-request KV cache
is not a contiguous ``(max_len, heads, d)`` buffer but a set of
fixed-size **pages** drawn from one shared pool, addressed through a
per-slot **block table**.  Continuous batching (``serving.batcher``)
needs exactly this: requests of wildly different lengths share one
compiled decode program (fixed slot count, fixed page pool) and memory
is bounded by the pool, not by ``capacity * max_len``.

Design points:

* **One stacked array per tensor.**  ``k_pages`` / ``v_pages`` are
  ``(n_layers, num_pages, page_size, n_heads, d_head)`` — a single
  pytree leaf, so the compiled decode step takes the whole cache as one
  donated operand and the checkpoint layer sees plain arrays.
* **Page 0 is the null page.**  Never allocated; inactive slots' block
  tables point at it, so the padded-slot decode program always reads
  and writes in-bounds (garbage it never uses) instead of branching.
* **Deterministic allocator.**  The free list is kept sorted ascending
  and admission reserves ``ceil(total_tokens / page_size)`` pages up
  front — the same request stream produces the same tables on every
  rank and every run (the block tables ride the compiled program's
  inputs, so nondeterminism here would desynchronize SPMD replicas).
  Reservation at admit also means a running request can never hit a
  mid-stream out-of-pages condition; the only failure point is
  admission, where the batcher can queue.  Pages are unit-granularity,
  so the pool cannot fragment: ``can_admit`` is exactly "enough free
  pages and a free slot" (pinned by test).
* **Deterministic eviction.**  ``choose_victim()`` names the most
  recently admitted active slot (LIFO — the request that joined last
  has done the least work).  ``evict()`` releases a slot's pages and
  returns them to the sorted free list; the batcher re-queues the
  request (greedy decode replays bit-identically from the prompt).
* **Checkpoint round-trip.**  ``state_dict()`` is a flat dict of
  arrays that the existing checkpoint layer
  (``extensions.checkpoint``) snapshots as-is; ``load_state_dict``
  reconstructs the allocator's host state (free list, per-slot page
  ownership) from the saved tables — a replica warm-starts with its
  pages and in-flight lengths intact.
* **TP resharding.**  Pages shard over the tensor-parallel axis by
  heads (dimension 3).  :func:`reshard_kv_state` re-splits a saved
  N-shard cache onto M shards bit-identically to a fresh split of the
  concatenated global cache — the serving analogue of
  ``resilience.elastic.reshard_state``'s ZeRO block rule.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

NULL_PAGE = 0


class CacheAdmissionError(RuntimeError):
    """A request was admitted past ``can_admit`` — pool or slots
    exhausted.  The batcher never triggers this (it checks first); a
    direct caller sees a loud error instead of a corrupted table."""


def pages_needed(total_tokens: int, page_size: int) -> int:
    """Pages a request occupying ``total_tokens`` cache positions needs
    (its prompt plus every generated token except the last, which is
    sampled but never written — callers pass prompt + max_new_tokens
    and over-reserve by at most one token's worth)."""
    return max(1, math.ceil(total_tokens / page_size))


class PagedKVCache:
    """The page pool, block tables, and allocator for one replica.

    ``capacity`` decode slots share ``num_pages`` pages of
    ``page_size`` tokens each (page 0 reserved as the null page).
    ``pages_per_slot`` bounds one request's table row — the static
    width of the compiled program's table operand.
    """

    def __init__(self, *, n_layers: int, n_heads: int, d_head: int,
                 capacity: int, page_size: int = 16,
                 num_pages: Optional[int] = None,
                 pages_per_slot: Optional[int] = None,
                 dtype=jnp.bfloat16):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.n_layers = int(n_layers)
        self.n_heads = int(n_heads)
        self.d_head = int(d_head)
        self.capacity = int(capacity)
        self.page_size = int(page_size)
        if pages_per_slot is None:
            pages_per_slot = 8
        self.pages_per_slot = int(pages_per_slot)
        if num_pages is None:
            # enough for every slot to hold a full-length request, + null
            num_pages = capacity * self.pages_per_slot + 1
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is null)")
        self.num_pages = int(num_pages)
        self.dtype = dtype
        shape = (self.n_layers, self.num_pages, self.page_size,
                 self.n_heads, self.d_head)
        self.k_pages = jnp.zeros(shape, dtype)
        self.v_pages = jnp.zeros(shape, dtype)
        # host-side allocator state (numpy: tables ship as step inputs)
        self.block_tables = np.full(
            (self.capacity, self.pages_per_slot), NULL_PAGE, np.int32
        )
        self.lengths = np.zeros((self.capacity,), np.int32)
        self.active = np.zeros((self.capacity,), bool)
        self._free_pages: List[int] = list(range(1, self.num_pages))
        self._slot_pages: Dict[int, List[int]] = {}
        # admission order (slot ids, oldest first) — the deterministic
        # eviction victim is the tail
        self._admit_order: List[int] = []

    # -- pool accounting ------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free_pages)

    @property
    def used_pages(self) -> int:
        return sum(len(p) for p in self._slot_pages.values())

    @property
    def free_slots(self) -> List[int]:
        return [s for s in range(self.capacity) if not self.active[s]]

    def utilization(self) -> float:
        """Fraction of allocatable pages currently owned by a slot."""
        return self.used_pages / max(self.num_pages - 1, 1)

    def check_invariants(self) -> None:
        """Allocator invariants, asserted by tests after every op mix:
        page sets disjoint, null page never owned, conservation (free +
        used == pool), free list sorted (determinism), tables consistent
        with ownership."""
        owned: List[int] = []
        for slot, pages in self._slot_pages.items():
            assert self.active[slot], f"slot {slot} owns pages inactive"
            assert NULL_PAGE not in pages, "null page allocated"
            assert list(self.block_tables[slot][: len(pages)]) == pages
            owned += pages
        assert len(set(owned)) == len(owned), "page double-owned"
        assert not set(owned) & set(self._free_pages), "free page owned"
        assert len(owned) + len(self._free_pages) == self.num_pages - 1
        assert self._free_pages == sorted(self._free_pages)
        assert sorted(self._admit_order) == sorted(self._slot_pages)

    # -- admission ------------------------------------------------------
    def can_admit(self, total_tokens: int) -> bool:
        need = pages_needed(total_tokens, self.page_size)
        if need > self.pages_per_slot:
            return False
        return bool(self.free_slots) and need <= len(self._free_pages)

    def admit(self, total_tokens: int) -> int:
        """Reserve a slot and its pages; returns the slot id.  The
        lowest free slot and the lowest free pages are taken (sorted
        free list), so admission is a pure function of allocator
        state."""
        need = pages_needed(total_tokens, self.page_size)
        if need > self.pages_per_slot:
            raise CacheAdmissionError(
                f"request needs {need} pages > pages_per_slot="
                f"{self.pages_per_slot} (total_tokens={total_tokens})"
            )
        free = self.free_slots
        if not free:
            raise CacheAdmissionError("no free decode slot")
        if need > len(self._free_pages):
            raise CacheAdmissionError(
                f"need {need} pages, {len(self._free_pages)} free"
            )
        slot = free[0]
        pages, self._free_pages = (
            self._free_pages[:need], self._free_pages[need:]
        )
        self._slot_pages[slot] = pages
        self.block_tables[slot, :] = NULL_PAGE
        self.block_tables[slot, : len(pages)] = pages
        self.lengths[slot] = 0
        self.active[slot] = True
        self._admit_order.append(slot)
        return slot

    def release(self, slot: int) -> None:
        """Return a slot's pages to the pool (request finished)."""
        if not self.active[slot]:
            raise KeyError(f"slot {slot} is not active")
        pages = self._slot_pages.pop(slot)
        self._free_pages = sorted(self._free_pages + pages)
        self.block_tables[slot, :] = NULL_PAGE
        self.lengths[slot] = 0
        self.active[slot] = False
        self._admit_order.remove(slot)

    def choose_victim(self) -> Optional[int]:
        """Deterministic eviction victim: the most recently admitted
        active slot (least progress lost on replay)."""
        return self._admit_order[-1] if self._admit_order else None

    def evict(self, slot: int) -> None:
        """Same pool effect as :meth:`release`; named separately so the
        batcher's logs distinguish retire from preempt."""
        self.release(slot)

    def advance(self, slot: int, n: int = 1) -> None:
        """Account ``n`` more cache positions written for ``slot``."""
        if not self.active[slot]:
            raise KeyError(f"slot {slot} is not active")
        new = int(self.lengths[slot]) + n
        if new > len(self._slot_pages[slot]) * self.page_size:
            raise CacheAdmissionError(
                f"slot {slot} advanced past its {len(self._slot_pages[slot])}"
                f"-page reservation ({new} tokens)"
            )
        self.lengths[slot] = new

    # -- arrays for the compiled step ----------------------------------
    def tables_array(self) -> jnp.ndarray:
        return jnp.asarray(self.block_tables)

    def lengths_array(self) -> jnp.ndarray:
        return jnp.asarray(self.lengths)

    def set_pages(self, k_pages, v_pages) -> None:
        """Install the decode step's updated page arrays (functional
        update — the step returns fresh arrays)."""
        self.k_pages, self.v_pages = k_pages, v_pages

    # -- checkpoint round-trip -----------------------------------------
    def state_dict(self) -> dict:
        """Flat array dict the checkpoint layer snapshots as-is.  Slot
        page counts make the table rows reconstructible (a table row is
        padded with the null page, which a real reservation never
        contains)."""
        counts = np.array(
            [len(self._slot_pages.get(s, ())) for s in range(self.capacity)],
            np.int32,
        )
        order = np.array(self._admit_order, np.int32)
        return {
            "k_pages": self.k_pages,
            "v_pages": self.v_pages,
            "block_tables": self.block_tables.copy(),
            "lengths": self.lengths.copy(),
            "active": self.active.astype(np.int8),
            "slot_page_counts": counts,
            "admit_order": order,
        }

    def load_state_dict(self, state: dict) -> None:
        """Rebuild pool + allocator from a snapshot (warm start)."""
        k = state["k_pages"]
        # validate against the CURRENT pool arrays, not the configured
        # paged geometry — the dense-oracle engine replaces the pool
        # with its contiguous per-slot layout, and its own snapshot
        # must round-trip too
        want = tuple(np.shape(self.k_pages))
        if tuple(np.shape(k)) != want:
            raise ValueError(
                f"cache shape mismatch: snapshot {tuple(np.shape(k))} "
                f"vs this cache's {want}"
            )
        self.k_pages = jnp.asarray(k, self.dtype)
        self.v_pages = jnp.asarray(state["v_pages"], self.dtype)
        self.block_tables = np.asarray(
            state["block_tables"], np.int32
        ).reshape(self.capacity, self.pages_per_slot).copy()
        self.lengths = np.asarray(
            state["lengths"], np.int32).reshape(self.capacity).copy()
        self.active = np.asarray(
            state["active"]).reshape(self.capacity).astype(bool)
        counts = np.asarray(state["slot_page_counts"], np.int32)
        self._slot_pages = {
            s: [int(p) for p in self.block_tables[s, : int(counts[s])]]
            for s in range(self.capacity) if self.active[s]
        }
        used = {p for pages in self._slot_pages.values() for p in pages}
        self._free_pages = sorted(
            set(range(1, self.num_pages)) - used
        )
        self._admit_order = [
            int(s) for s in np.asarray(state["admit_order"], np.int32)
        ]
        self.check_invariants()


def reshard_kv_state(states: Sequence[dict], new_world: int) -> List[dict]:
    """Re-split an N-shard paged cache (heads axis) onto M shards.

    ``states``: one :meth:`PagedKVCache.state_dict` per old TP rank, in
    rank order (each holding ``H/N`` heads of the same pool).  The host
    allocator state (tables, lengths, free list) is replicated across
    TP ranks by construction, so rank 0's is kept.  The result is
    bit-identical to splitting the concatenated global cache fresh —
    pages are re-cut on the heads dimension only, block tables never
    move (pinned by test)."""
    if not states:
        raise ValueError("reshard_kv_state needs at least one shard")
    new_world = int(new_world)
    k_full = np.concatenate(
        [np.asarray(s["k_pages"]) for s in states], axis=3
    )
    v_full = np.concatenate(
        [np.asarray(s["v_pages"]) for s in states], axis=3
    )
    heads = k_full.shape[3]
    if heads % new_world:
        raise ValueError(
            f"{heads} global heads do not split over {new_world} shards"
        )
    out = []
    for r in range(new_world):
        sl = slice(r * heads // new_world, (r + 1) * heads // new_world)
        shard = dict(states[0])
        shard["k_pages"] = k_full[:, :, :, sl]
        shard["v_pages"] = v_full[:, :, :, sl]
        out.append(shard)
    return out

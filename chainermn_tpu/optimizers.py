"""Multi-node optimizer wrapper.

Reference parity: ``chainermn/optimizers.py`` —
``create_multi_node_optimizer(actual_optimizer, communicator,
double_buffering=False)``; ``_MultiNodeOptimizer.update()`` = backward ->
``communicator.allreduce_grad(target)`` -> ``actual_optimizer.update()``;
``_DoubleBufferingOptimizer`` overlaps the allreduce of step *i* with the
compute of step *i+1* using a background thread and applies stale-by-one
gradients.

TPU-native redesign
-------------------
The wrapped object is an ``optax.GradientTransformation`` rather than a
Chainer optimizer, and the gradient sync is a ``lax.pmean`` over the
communicator's mesh axes *inside the compiled step*:

* Under ``shard_map`` (per-device SPMD code), ``update`` pmean-s the
  incoming gradients over ``comm.axis_names`` — the literal analogue of
  ``allreduce_grad`` but fused into the step program, where XLA overlaps it
  with surrounding compute.
* Under plain ``jit`` + sharded batch (GSPMD), cross-device gradient
  averaging already falls out of differentiating the global-mean loss; the
  wrapper detects that no mesh axis is bound and passes gradients through
  unchanged.
* Eagerly (ChainerMN-shaped scripts), stacked per-rank gradients go through
  ``comm.allreduce_grad``.

Double buffering becomes a *functional* state machine: the transform's state
carries the previous step's local gradients; ``update`` applies the
*synchronized previous* gradients while the current ones merely enter the
state.  The allreduce of step *i*'s gradients is thus issued in step
*i+1*'s program with no data dependency on that program's forward pass —
XLA's latency-hiding scheduler overlaps it with compute, which is the
reference's background-thread trick without threads.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax


def _axes_bound(axis_names) -> bool:
    """True when called under a trace with ``axis_names`` bound (shard_map).

    ``lax.axis_index`` on an unbound axis raises ``NameError`` ("Found an
    unbound axis name ...") at trace time; only that exception means "not
    under shard_map".  Anything else is a real error and must propagate —
    swallowing it would silently disable gradient sync.
    """
    try:
        for a in axis_names:
            lax.axis_index(a)
        return True
    except NameError:
        return False


def _no_exchange(comm) -> bool:
    """DummyCommunicator at the compiled tier: the step program is built
    identically (shard_map, batch sharding, loss pmean) but the gradient
    exchange is omitted — the reference's subtraction methodology
    (``DummyCommunicator``, SURVEY.md section 5.1) applied to the jitted
    path.  `(t_sync - t_dummy)` is the exposed cost of gradient sync."""
    return bool(getattr(comm, "no_exchange", False))


def _axis_size(comm, axes) -> int:
    n = 1
    shape = dict(comm.mesh.shape)
    for a in axes:
        n *= shape[a]
    return n


def _sync_grads_per_leaf(grads, comm, comm_dtype=None, axes=None):
    """Legacy wire: one collective PER GRADIENT LEAF (267 for
    ResNet-50).  Kept as the `wire="per_leaf"` escape hatch and the
    A/B baseline for the bucketed path (`benchmarks/comm_overlap_bench
    .py wire_perleaf_*`)."""
    axes = comm.axis_names if axes is None else tuple(axes)
    n = _axis_size(comm, axes)

    def one(g):
        if comm_dtype is not None:
            # divide AFTER casting off the wire: dividing while still in
            # comm_dtype added a second low-precision rounding per
            # element for no wire-byte saving (comm_wire.codecs doc)
            return lax.psum(g.astype(comm_dtype), axes).astype(g.dtype) / n
        return lax.pmean(g, axes)

    return jax.tree_util.tree_map(one, grads)


def _sync_grads_wire(grads, comm, wire, axes=None, residuals=None,
                     profile=None):
    """Bucketed wire gradient sync: flatten the grad pytree into the
    deterministic bucket plan, reduce each bucket under its planner-
    chosen collective schedule (``comm_wire.schedules`` — ONE flat psum
    per bucket, or the hier rs→ar→ag triple with the codec on the
    inter hop only), unflatten.

    Returns ``(synced_tree, new_residuals)``; ``new_residuals`` is ()
    unless ``wire.error_feedback``.  Element order within a bucket is
    tree-flatten order, so the uncompressed flat-scheduled psum is
    bit-identical to the per-leaf psum (elementwise reduction — grouping
    changes neither summands nor rank order; pinned at 0 tolerance by
    tests/test_comm_wire.py).  The hier schedule reassociates the
    reduction tree (per-slice partial sums), which is exact on
    exactly-representable data (pinned at 0 tolerance by
    tests/test_schedules.py) and differs only by summation rounding
    order otherwise."""
    from . import comm_wire as _cw

    axes = comm.axis_names if axes is None else tuple(axes)
    n = _axis_size(comm, axes)
    wplan = _cw.plan_wire(grads, wire, comm.mesh, axes, profile=profile)
    buckets = _cw.flatten_to_buckets(wplan.plan, grads)
    means, new_res = _cw.reduce_wire(
        buckets, wplan, n, wire, residuals if residuals else None
    )
    return (
        _cw.unflatten_from_buckets(wplan.plan, means, grads),
        tuple(new_res),
    )


def _sync_grads(grads, comm, comm_dtype=None, axes=None, wire="auto"):
    """Gradient sync over mesh axes (compiled path).

    Default: bucketed flat wire (the tentpole path — collective count =
    bucket count, not leaf count) with the codec implied by
    ``comm_dtype``.  ``wire="per_leaf"`` selects the legacy
    one-psum-per-leaf lowering.  ``axes`` defaults to the communicator's
    full axis set; hybrid DP x TP steps pass the data axes only.
    """
    from .comm_wire import codec_of_dtype, resolve_wire

    cfg = resolve_wire(wire, comm)  # validates explicit WireConfigs too
    if cfg is None:
        return _sync_grads_per_leaf(grads, comm, comm_dtype, axes)
    if comm_dtype is not None and wire in (None, "auto"):
        try:
            cfg = cfg._replace(codec=codec_of_dtype(comm_dtype))
        except ValueError:
            # an explicit comm_dtype with no wire codec (e.g. float64)
            # gets the same treatment as the communicator's own
            # allreduce_grad_dtype under "auto": the legacy per-leaf
            # cast keeps working instead of raising at trace time
            return _sync_grads_per_leaf(grads, comm, comm_dtype, axes)
    synced, _ = _sync_grads_wire(grads, comm, cfg, axes)
    return synced


def _tree_all_finite(grads):
    """Scalar bool: every inexact gradient leaf is fully finite."""
    flags = [
        jnp.all(jnp.isfinite(g))
        for g in jax.tree_util.tree_leaves(grads)
        if jnp.issubdtype(jnp.asarray(g).dtype, jnp.inexact)
    ]
    if not flags:
        return jnp.ones((), jnp.bool_)
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_and(out, f)
    return out


class _ProfiledPlanToken(NamedTuple):
    """Agreement token for the mesh-less comm path: a bare
    ``BucketPlan`` plus the bandwidth-profile content hash, combined
    with the same ``|profile=`` folding as ``WirePlan.plan_hash`` (the
    mesh path) — one spelling of "the plan AND what tuned it" for
    ``plan_agreement`` to exchange."""

    plan: Any
    profile_hash: str

    def plan_hash(self) -> str:
        import hashlib

        h = hashlib.sha256()
        h.update(self.plan.plan_hash().encode())
        h.update(f"|profile={self.profile_hash}".encode())
        return h.hexdigest()


class MultiNodeOptimizerState(NamedTuple):
    inner_state: Any
    step: jnp.ndarray
    # error-feedback residual (flat wire buckets) when the wire codec is
    # lossy and error_feedback is on; () otherwise — compressed rounding
    # error is re-injected into the NEXT step's gradient instead of lost
    wire_residual: Any = ()


class DoubleBufferingState(NamedTuple):
    inner_state: Any
    step: jnp.ndarray
    # local grads of the previous step (pre-sync).  On the bucketed wire
    # this is the tuple of FLAT buckets in the wire's storage dtype —
    # smaller state than a full param-shaped tree for cast codecs, and
    # step i+1 issues a handful of large collectives instead of a leaf
    # storm.  The legacy per-leaf wire keeps the param-shaped tree.
    prev_grads: Any


class _MultiNodeOptimizer:
    """Attribute-delegating wrapper (parity: ``_MultiNodeOptimizer``'s
    ``__getattr__`` delegation to the actual optimizer).

    ``wire`` selects the gradient wire (see ``create_multi_node_
    optimizer``): "auto" derives the codec from the communicator's
    ``allreduce_grad_dtype``; "per_leaf" is the legacy one-collective-
    per-leaf path; a codec name or ``comm_wire.WireConfig`` selects
    explicitly.
    """

    # the program SHAPE the measured tuner prices candidates as (ISSUE
    # 12): the plain wrapper syncs with the flat psum / hier triple;
    # ZeRO overrides to "zero" (rs+ag down/up) so its bucket sizing is
    # minimized against the collectives it actually issues
    _wire_shape = "allreduce"

    def __init__(self, actual_optimizer: optax.GradientTransformation,
                 comm, wire="auto", overlap="none", tune_trace=None,
                 profile=None):
        from .comm_wire import resolve_overlap, resolve_wire
        from .comm_wire.autotune import resolve_profile
        from .comm_wire.planner import tune_wire_for_trace

        self._opt = actual_optimizer
        self._comm = comm
        self._wire = resolve_wire(wire, comm)  # None => per-leaf legacy
        # ISSUE 12: resolve the profile HERE, at construction — a rank
        # whose launch env lost the profile file raises
        # ProfileMissingError before any collective (or plan exchange)
        # runs, instead of silently planning with the constants while
        # its peers tune
        self._profile = resolve_profile(profile)
        if self._profile is not None and self._wire is None:
            # the legacy per-leaf path has no plan to tune and no
            # WirePlan hash to disclose the profile through — accepting
            # it would be untracked analytic behavior the user believes
            # is measured-tuned (same fail-at-the-cause contract as
            # ProfileMissingError)
            raise ValueError(
                "profile= requires the bucketed wire: "
                f"wire={wire!r} resolved to the legacy per-leaf path, "
                "which consults no plan the profile could tune (and no "
                "plan hash that would disclose it); drop the profile "
                "or select a bucketed wire"
            )
        if self._profile is not None:
            mesh = getattr(comm, "mesh", None)
            if mesh is not None and not self._profile.matches_mesh(mesh):
                # the documented guarantee: a wrong-topology profile can
                # NEVER silently tune a mesh.  Every rank loading the
                # same stale capture would pass plan agreement (hashes
                # identical) while pricing this mesh's hops through
                # foreign curves — so the signature check must live
                # HERE, at construction, not only in the hash.
                from .comm_wire.autotune import BandwidthProfile

                raise ValueError(
                    "wire profile was captured on mesh "
                    f"{self._profile.mesh_axes} but this "
                    "communicator's mesh is "
                    f"{BandwidthProfile.mesh_signature(mesh)}: a "
                    "wrong-topology profile would silently tune with "
                    "foreign curves on every rank at once — "
                    "recalibrate on this topology (python -m "
                    "chainermn_tpu.comm_wire.autotune --calibrate); "
                    "for a telemetry-scraped profile of THIS mesh, "
                    "note profile_from_attribution defaults its "
                    "signature to the axes the trace's collectives "
                    "crossed — on a hybrid (e.g. DP x TP) mesh pass "
                    "mesh= explicitly so the full topology is stamped"
                )
        if (
            self._wire is not None
            and tune_trace is not None
            and wire in (None, "auto")
        ):
            # ISSUE 11 satellite: `wire="auto"` with a measured trace in
            # hand consults the cost-model tuner (PR 6's
            # tune_wire_for_trace — built but production-unconsumed
            # until now) instead of the fixed 4 MiB/6-bucket constants:
            # the byte target scales with the worst hop class the
            # trace's reductions cross, and a small total collapses the
            # slot budget to 1.  With a profile (ISSUE 12) the sizing
            # is measured instead: predicted sync time minimized over
            # candidate slot budgets.
            records = getattr(tune_trace, "records", tune_trace)
            bucket_bytes, max_buckets = tune_wire_for_trace(
                records, profile=self._profile,
                schedule=getattr(self._wire, "schedule", "auto"),
                shape=self._wire_shape,
            )
            self._wire = self._wire._replace(
                bucket_bytes=bucket_bytes, max_buckets=max_buckets
            )
        self._overlap = resolve_overlap(overlap)

    @property
    def communicator(self):
        return self._comm

    @property
    def wire(self):
        """Resolved ``comm_wire.WireConfig`` (None on the legacy path)."""
        return self._wire

    @property
    def profile(self):
        """Resolved ``comm_wire.autotune.BandwidthProfile`` driving the
        measured bucket sizing + schedule decisions (None = analytic)."""
        return self._profile

    def wire_plan(self, tree, axes=None):
        """The schedule-aware :class:`~chainermn_tpu.comm_wire.
        WirePlan` this optimizer's sync derives for ``tree`` — profile
        included, so its ``plan_hash()`` is exactly what
        ``plan_agreement`` exchanges (bench fingerprints and tests read
        the wire through this one path)."""
        from . import comm_wire as _cw

        if self._wire is None:
            raise ValueError("the legacy per-leaf wire has no plan")
        mesh = getattr(self._comm, "mesh", None)
        if mesh is None:
            # mesh-less comms sync through plan_of_tree (see
            # _check_plan_agreement / _zero_residuals) — there is no
            # schedule-aware plan to hand back, and plan_wire would
            # die deep in schedules.py on dict(None)
            raise ValueError(
                "wire_plan needs the communicator's mesh to derive "
                "schedules, and this communicator has none; the "
                "mesh-less layout is comm_wire.plan_of_tree(tree)"
            )
        return _cw.plan_wire(
            tree, self._wire, mesh, axes,
            profile=self._profile, shape=self._wire_shape,
        )

    @property
    def overlap(self) -> str:
        """Overlap mode: "none" (synchronous sync at the program tail)
        or "bucket" (``comm_wire.overlap`` reschedules the compiled
        step so each bucket's psum issues as soon as its leaves are
        produced).  ``build_train_step`` reads this."""
        return self._overlap

    @property
    def actual_optimizer(self):
        return self._opt

    def _zero_residuals(self, params):
        from . import comm_wire as _cw

        w = self._wire
        if w is None or not w.error_feedback:
            return ()
        if getattr(self._comm, "mesh", None) is None:
            # mesh-less comms have nothing to stage: residuals at full
            # bucket width, exactly the pre-schedule shapes (the same
            # comm shape _check_plan_agreement's plan_of_tree branch
            # serves)
            plan = _cw.plan_of_tree(params, w.bucket_bytes,
                                    w.max_buckets)
            return _cw.zero_residuals(plan, params)
        # schedule-aware shapes: a hier bucket's residual lives at the
        # compression point (the inter hop's scattered shard), not at
        # full bucket width
        wplan = self.wire_plan(params)
        return _cw.zero_residuals_wire(wplan)

    def _check_plan_agreement(self, params):
        """Cross-process plan guard at init time: in a multi-controller
        world a divergent bucket plan (the processes built different
        models) would deadlock or silently mix wire layouts at the
        first bucketed collective — fail loudly with
        ``WirePlanMismatchError`` here instead.  Skipped under tracing
        (the eager obj-store exchange is impossible) and in
        single-process worlds (nothing to disagree with)."""
        from . import comm_wire as _cw

        w, comm = self._wire, self._comm
        if w is None or getattr(comm, "process_count", 1) <= 1:
            return
        leaves = jax.tree_util.tree_leaves(params)
        if any(isinstance(l, jax.core.Tracer) for l in leaves):
            return
        # the exchanged hash covers bucket layout AND the per-bucket
        # collective schedule AND (ISSUE 12) the bandwidth-profile
        # content hash (WirePlan.plan_hash): ranks scheduling or TUNING
        # apart would mis-pair collectives exactly like a layout split
        mesh = getattr(comm, "mesh", None)
        if mesh is not None:
            plan = self.wire_plan(params)
        else:
            plan = _cw.plan_of_tree(params, w.bucket_bytes, w.max_buckets)
            if self._profile is not None:
                # mesh-less comms must not tune apart either: fold the
                # profile content hash into the exchanged token exactly
                # as WirePlan.plan_hash does, so two ranks whose
                # analytic layouts coincide but whose profiles differ
                # still mismatch here instead of diverging on the next
                # profile-sensitive decision
                plan = _ProfiledPlanToken(
                    plan, self._profile.profile_hash()
                )
        _cw.plan_agreement(comm, plan)

    def init(self, params):
        self._check_plan_agreement(params)
        return MultiNodeOptimizerState(
            inner_state=self._opt.init(params),
            step=jnp.zeros((), jnp.int32),
            wire_residual=self._zero_residuals(params),
        )

    def update(self, grads, state, params=None, sync_axes=None):
        """``sync_axes``: mesh axes to average gradients over.  ``None``
        means the communicator's full axis set; ``()`` skips the sync
        (hybrid steps whose autodiff already produced global grads)."""
        comm = self._comm
        axes = comm.axis_names if sync_axes is None else tuple(sync_axes)
        residual = getattr(state, "wire_residual", ())
        if axes and _axes_bound(axes) and not _no_exchange(comm):
            if residual and axes != tuple(comm.axis_names):
                # The residual carry was shaped by init against the
                # FULL mesh axes; a different sync-axis set can
                # re-schedule a bucket between hier (shard-width
                # residual) and flat (full-width), silently mis-shaping
                # the add.  Only an ACTUAL shape flip is an error —
                # meshes where neither axis set can stage keep their
                # axes-independent flat residuals and stay legal — and
                # the check lives INSIDE the sync branch: a skipped
                # sync (no-exchange A/B, eager path) never touches the
                # residual, so it must not raise (trace-time cost only).
                def res_shapes(wp):
                    return tuple(
                        wp.shard_size(i) for i in range(wp.n_buckets)
                    )

                full = self.wire_plan(grads)
                sub = self.wire_plan(grads, axes)
                if res_shapes(full) != res_shapes(sub):
                    raise ValueError(
                        "error_feedback cannot sync over the axis "
                        f"subset {axes}: the residual carry was "
                        "planned against the full mesh axes "
                        f"{tuple(comm.axis_names)}, and the subset "
                        "re-schedules the buckets onto different "
                        "residual shapes "
                        f"({res_shapes(full)} vs {res_shapes(sub)})"
                    )
            if self._wire is None:
                grads = _sync_grads_per_leaf(
                    grads, comm, comm.allreduce_grad_dtype, axes=axes
                )
            else:
                grads, residual = _sync_grads_wire(
                    grads, comm, self._wire, axes=axes,
                    residuals=residual, profile=self._profile,
                )
        updates, inner = self._opt.update(grads, state.inner_state, params)
        return updates, MultiNodeOptimizerState(
            inner, state.step + 1, residual
        )

    # optax-compatible alias pair so the wrapper *is* a GradientTransformation
    def __iter__(self):
        yield self.init
        yield self.update

    def apply_gradients(self, *, grads, state, params):
        """Convenience: sync + update + apply in one call."""
        updates, state = self.update(grads, state, params)
        return optax.apply_updates(params, updates), state


class _DoubleBufferingOptimizer(_MultiNodeOptimizer):
    """Stale-by-one gradient application (parity: the double-buffering mode
    of chainermn/optimizers.py, which required PureNcclCommunicator).

    ``update(grads_i)`` returns updates computed from ``pmean(grads_{i-1})``
    and stores ``grads_i`` for the next call.  Step 0 applies zeros (the
    reference's first iteration similarly produced no synced update until a
    buffer swap).
    """

    def _plan(self, tree, axes=None):
        """Schedule-aware wire plan (``WirePlan``): the stale buckets
        are stored flat either way, but the SYNC of the previous step's
        buckets follows the planner-chosen schedule like the plain
        wrapper's."""
        return self.wire_plan(tree, axes)

    def _store(self, wplan, tree):
        """Flatten grads into the stale-grad buffer: flat buckets in the
        wire's storage dtype (half the state bytes for cast codecs)."""
        from . import comm_wire as _cw

        buckets = _cw.flatten_to_buckets(wplan.plan, tree)
        return tuple(
            b.astype(_cw.storage_dtype(self._wire, spec.dtype))
            for b, spec in zip(buckets, wplan.buckets)
        )

    def init(self, params):
        self._check_plan_agreement(params)
        if self._wire is None:  # legacy per-leaf wire: param-shaped tree
            prev = jax.tree_util.tree_map(jnp.zeros_like, params)
        else:
            wplan = self._plan(params)
            prev = self._store(wplan, jax.tree_util.tree_map(
                jnp.zeros_like, params
            ))
        return DoubleBufferingState(
            inner_state=self._opt.init(params),
            step=jnp.zeros((), jnp.int32),
            prev_grads=prev,
        )

    def update(self, grads, state, params=None, sync_axes=None):
        from . import comm_wire as _cw

        comm = self._comm
        axes = comm.axis_names if sync_axes is None else tuple(sync_axes)
        do_sync = axes and _axes_bound(axes) and not _no_exchange(comm)
        if self._wire is None:
            prev = state.prev_grads
            if do_sync:
                prev = _sync_grads_per_leaf(
                    prev, comm, comm.allreduce_grad_dtype, axes=axes
                )
            new_prev = grads
        else:
            wplan = self._plan(grads, axes)
            # stored buckets back to the plan's native dtype: the codec
            # re-casts onto the wire itself, the decode stays native
            prev_buckets = [
                b.astype(jnp.dtype(spec.dtype))
                for b, spec in zip(state.prev_grads, wplan.buckets)
            ]
            if do_sync:
                prev_buckets, _ = _cw.reduce_wire(
                    prev_buckets, wplan, _axis_size(comm, axes),
                    self._wire,
                )
            prev = _cw.unflatten_from_buckets(
                wplan.plan, prev_buckets, grads
            )
            new_prev = self._store(wplan, grads)
        updates, inner = self._opt.update(prev, state.inner_state, params)
        return updates, DoubleBufferingState(inner, state.step + 1, new_prev)


def _to_blocks(x, n):
    """Flatten ``x``, zero-pad to a multiple of ``n``, reshape to (n, k)."""
    flat = x.reshape(-1)
    k = -(-flat.size // n)
    pad = n * k - flat.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(n, k)


def _from_blocks(x, like):
    return x.reshape(-1)[: like.size].reshape(like.shape)


class _ZeroRedundancyOptimizer(_MultiNodeOptimizer):
    """ZeRO stage-1: optimizer state sharded over the communicator.

    Every parameter leaf is viewed as ``size`` equal blocks; each chip owns
    exactly one block of the inner optimizer's state (Adam moments etc.), so
    per-chip optimizer memory is ``1/size`` of the replicated wrapper's.
    The step becomes: ``psum_scatter`` the gradients (each chip receives the
    reduced block it owns — half the wire traffic of a full allreduce),
    update the local block, ``all_gather`` the *updates* back to full width.
    On TPU both collectives ride ICI; an allreduce is reduce-scatter +
    all-gather internally, so the wire cost is identical to plain DP while
    the update compute and state memory drop by ``1/size``.

    Works with any elementwise optax transform (sgd/adam/adamw/...).
    Shape-coupled transforms (e.g. factored Adafactor statistics) see
    ``(size, k)`` blocks instead of the true parameter shapes and will be
    numerically different — use the plain wrapper for those.

    State sharding is declared via :meth:`state_partition_spec`, which
    ``build_train_step`` consumes to lay the state out over the mesh.
    """

    _wire_shape = "zero"  # measured tuning prices rs+ag, not one psum

    def _blocks(self, tree):
        n = self._comm.size
        return jax.tree_util.tree_map(lambda x: _to_blocks(x, n), tree)

    def init(self, params):
        self._check_plan_agreement(params)
        return MultiNodeOptimizerState(
            inner_state=self._opt.init(self._blocks(params)),
            step=jnp.zeros((), jnp.int32),
        )

    def state_partition_spec(self, opt_state):
        """PartitionSpec pytree for ``opt_state``: block-major leaves are
        sharded over the communicator's mesh axes, scalars replicated."""
        from jax.sharding import PartitionSpec as P

        n = self._comm.size
        axes = self._comm.axis_names

        def spec(leaf):
            if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == n:
                return P(axes)
            return P()

        return jax.tree_util.tree_map(spec, opt_state)

    def reshard_state(self, opt_state, old_world: int, params):
        """Re-partition an optimizer state saved at ``old_world`` ranks
        onto THIS communicator's world (elastic N→M restart).

        Gather-to-global then re-split, template-driven by a fresh
        ``init(params)``: every blocked ``(n, k)`` leaf
        :meth:`state_partition_spec` declares sharded is re-blocked
        **bit-identically** to a fresh partition of the gathered global
        state (the blocking's zero padding lives at the tail, so
        truncate/pad is exact — ``resilience.elastic``
        ``reshard_blocked_leaf``).  ``init`` also re-runs the wire
        ``plan_agreement`` in multi-process worlds, so the plan hash is
        re-agreed for the new world as a side effect.  Checkpoint
        ``resume()`` routes here automatically via the world manifest;
        this method is the direct form.
        """
        from .resilience import elastic as _elastic
        from .resilience.errors import WorldResizeRequiredError

        template = self.init(params)
        out = _elastic.reshard_state(
            opt_state, template, int(old_world), int(self._comm.size),
            label="zero_opt_state",
        )
        # cross-check against the exported layout: the resharded state
        # must declare the SAME partitioning as a fresh init (a leaf the
        # spec shards that came out unblocked means the resharder and
        # the layout drifted apart)
        if self.state_partition_spec(out) != self.state_partition_spec(
            template
        ):
            raise WorldResizeRequiredError(
                "resharded ZeRO state disagrees with "
                "state_partition_spec's layout for this world — the "
                "saved state's structure does not match this optimizer",
                site="optimizers.reshard_state",
            )
        return out

    def hbm_bytes_per_rank(self, params, opt_state=None) -> dict:
        """``{"params": bytes, "opt_state": bytes}`` one rank actually
        holds — params replicated (full copy per rank), state leaves
        divided by exactly the axes :meth:`state_partition_spec`
        shards them over (the SAME spec tree that places the state, so
        this closed form cannot drift from the layout).  The other
        half of the HBM-estimator cross-check: the analyzer's
        live-range walk over the shard_map body must see these sizes
        on the step's invars."""
        def leaf_bytes(l):
            return int(np.prod(np.shape(l)) * np.dtype(
                getattr(l, "dtype", np.float32)
            ).itemsize)

        p_bytes = sum(
            leaf_bytes(l) for l in jax.tree_util.tree_leaves(params)
        )
        o_bytes = 0
        if opt_state is not None:
            shape = dict(self._comm.mesh.shape)
            leaves, treedef = jax.tree_util.tree_flatten(opt_state)
            specs = treedef.flatten_up_to(
                self.state_partition_spec(opt_state)
            )
            for l, spec in zip(leaves, specs):
                nb = leaf_bytes(l)
                for part in tuple(spec):
                    if part is None:
                        continue
                    axes = part if isinstance(part, tuple) else (part,)
                    for a in axes:
                        nb //= shape.get(a, 1)
                o_bytes += nb
        return {"params": p_bytes, "opt_state": o_bytes}

    def _wire_groups(self, blocked_leaves):
        """Group blocked ``(n, k)`` leaves into wire buckets (same
        greedy dtype-homogeneous planner as the flat-wire path, applied
        to the blocked view).  Returns the plan whose slots index into
        ``blocked_leaves``; column offsets are reconstructed from the
        per-leaf widths at pack time."""
        from . import comm_wire as _cw

        w = self._wire or _cw.WireConfig()
        return _cw.make_plan(blocked_leaves, w.bucket_bytes, w.max_buckets)

    def update(self, grads, state, params=None):
        from .comm_wire import codecs as _codecs

        comm = self._comm
        n = comm.size
        axes = comm.axis_names
        if self._wire is not None:
            if self._wire.codec == "int8":
                raise ValueError(
                    "int8 wire is not supported on the zero_redundancy "
                    "path (the reduce-scatter would need per-shard "
                    "scale agreement); use bf16/f16"
                )
            wire_dtype = _codecs._CAST_WIRE.get(self._wire.codec)
        else:
            wire_dtype = comm.allreduce_grad_dtype
        tree_map = jax.tree_util.tree_map
        g_blocks = self._blocks(grads)
        p_blocks = self._blocks(params) if params is not None else None
        if _axes_bound(axes):
            idx = lax.axis_index(axes)

            # ISSUE 11: ZeRO's blocked path grows the same per-bucket
            # schedule choice as the flat wire.  A hier-scheduled
            # scatter stages intra-slice first (full precision, ICI)
            # and crosses the inter (DCN-class) links only with the
            # 1/K-reduced partial — wire-cast on that hop alone — via a
            # LOCAL block transpose that keeps ownership linear (rank
            # i*K+j still owns block i*K+j), so the state layout, the
            # elastic resharder, and state_partition_spec are untouched.
            from .comm_wire import (
                axis_split as _axis_split,
                mesh_axis_sizes as _mesh_sizes,
                schedule_for_bucket as _sched_for,
            )

            split = _axis_split(axes, _mesh_sizes(comm.mesh, axes))
            requested = (
                getattr(self._wire, "schedule", "auto")
                if self._wire is not None else "flat"
            )
            if requested == "hier_rs_ag" and split is None:
                import warnings

                warnings.warn(
                    "zero_redundancy wire schedule 'hier_rs_ag' "
                    f"requested but axes {axes} carry no genuine "
                    "(inter, intra) split (width-1 'mn_inter' ragged "
                    "fallback or flat mesh); collapsing to 'flat'."
                )
            sizes_env = dict(zip(axes, _mesh_sizes(comm.mesh, axes)))

            def _hier(payload_bytes: int) -> bool:
                if self._wire is None or split is None:
                    return False
                # shape="zero": the measured comparison prices the
                # rs+ag-down/up programs this path actually issues,
                # not the gradient wire's psum-vs-triple
                return _sched_for(
                    payload_bytes, sizes_env, axes=axes,
                    requested=requested, profile=self._profile,
                    shape="zero",
                ) == "hier_rs_ag"

            def _y_order(g):
                # y[j*I+i] = g[i*K+j]: after intra-then-inter staged
                # scatters, rank (i, j) lands on y-row j*I+i = its own
                # linear block i*K+j — ownership unchanged
                i_, k_ = split.inter_size, split.intra_size
                return g.reshape(i_, k_, -1).transpose(1, 0, 2).reshape(
                    g.shape[0], -1
                )

            def scatter(g, hier=False):
                if hier:
                    part = lax.psum_scatter(  # intra hop, full precision
                        _y_order(g), split.intra, scatter_dimension=0,
                        tiled=True,
                    )
                    pw = (
                        part.astype(wire_dtype)
                        if wire_dtype is not None else part
                    )
                    local = lax.psum_scatter(  # inter hop, on the wire
                        pw, split.inter, scatter_dimension=0, tiled=False
                    )
                    return (local.astype(g.dtype) / n)[None]
                gw = g.astype(wire_dtype) if wire_dtype is not None else g
                local = lax.psum_scatter(
                    gw, axes, scatter_dimension=0, tiled=False
                )
                # mean in the native dtype, not on the wire
                return (local.astype(g.dtype) / n)[None]

            def gather(u, hier=False):
                if hier:
                    i_, k_ = split.inter_size, split.intra_size
                    a = lax.all_gather(  # inter hop: rebuild the chunk
                        jnp.squeeze(u, 0), split.inter, axis=0,
                        tiled=False,
                    )
                    z = lax.all_gather(  # intra hop: rebuild y-order
                        a, split.intra, axis=0, tiled=True
                    )
                    return z.reshape(k_, i_, -1).transpose(
                        1, 0, 2
                    ).reshape(z.shape[0], -1)
                return lax.all_gather(u, axes, axis=0, tiled=True)

            def _leaf_hier(g):
                return _hier(int(np.prod(g.shape)) * g.dtype.itemsize)

            leaves, treedef = jax.tree_util.tree_flatten(g_blocks)
            if self._wire is None or len(leaves) <= 1:
                local_g = tree_map(
                    lambda g: scatter(g, _leaf_hier(g)), g_blocks
                )
                gather_blocks = lambda upd: tree_map(  # noqa: E731
                    lambda u, g: gather(u, _leaf_hier(g)), upd, g_blocks
                )
            else:
                # Bucketed wire: concatenate blocked leaves column-wise
                # into dtype-homogeneous buckets -> ONE reduce-scatter
                # per bucket down, ONE all-gather per bucket up (the
                # allreduce split in halves, per bucket instead of per
                # leaf).  Columns here are the blocked width s.shape[1]
                # (the (n, k) view must survive the scatter dimension),
                # so comm_wire.pack_stacked's flat (size, -1) layout
                # does not apply.
                plan = self._wire_groups(leaves)

                def _bucket_hier(b):
                    return _hier(
                        int(b.size) * jnp.dtype(b.dtype).itemsize
                    )

                local_leaves = [None] * len(leaves)
                packed = []
                for b in plan.buckets:
                    cat = jnp.concatenate(
                        [leaves[s.index] for s in b.slots], axis=1
                    )
                    packed.append((b, scatter(cat, _bucket_hier(b))))
                for b, loc in packed:  # loc: (1, K)
                    col = 0
                    for s in b.slots:
                        k = s.shape[1]
                        local_leaves[s.index] = loc[:, col : col + k]
                        col += k
                local_g = jax.tree_util.tree_unflatten(
                    treedef, local_leaves
                )

                def gather_blocks(upd):
                    up_leaves = treedef.flatten_up_to(upd)
                    out = [None] * len(up_leaves)
                    for b in plan.buckets:
                        cat = gather(jnp.concatenate(
                            [up_leaves[s.index] for s in b.slots], axis=1
                        ), _bucket_hier(b))
                        col = 0
                        for s in b.slots:
                            k = s.shape[1]
                            out[s.index] = cat[:, col : col + k]
                            col += k
                    return jax.tree_util.tree_unflatten(treedef, out)

            local_p = (
                tree_map(
                    lambda p: lax.dynamic_slice_in_dim(p, idx, 1, axis=0),
                    p_blocks,
                )
                if p_blocks is not None
                else None
            )
            upd_local, inner = self._opt.update(
                local_g, state.inner_state, local_p
            )
            upd_blocks = gather_blocks(upd_local)
        else:
            # Eager / GSPMD path: full-width block update — identical
            # numerics for elementwise transforms, state shape unchanged.
            upd_blocks, inner = self._opt.update(
                g_blocks, state.inner_state, p_blocks
            )
        updates = tree_map(_from_blocks, upd_blocks, grads)
        return updates, MultiNodeOptimizerState(inner, state.step + 1)


def create_multi_node_optimizer(
    actual_optimizer: optax.GradientTransformation,
    communicator,
    double_buffering: bool = False,
    zero_redundancy: bool = False,
    wire="auto",
    overlap="none",
    tune_trace=None,
    profile=None,
) -> _MultiNodeOptimizer:
    """Wrap an optax optimizer for multi-chip training.

    Parity: ``chainermn.create_multi_node_optimizer``.  ``zero_redundancy``
    shards the optimizer state across the communicator (ZeRO-1) — a TPU-era
    capability beyond the reference's feature set.

    ``wire`` selects the gradient wire (``chainermn_tpu.comm_wire``):

    * ``"auto"`` (default) — bucketed flat wire, codec derived from the
      communicator's ``allreduce_grad_dtype`` (None -> ``none``,
      bfloat16 -> ``bf16``, float16 -> ``f16`` — the reference's
      ``PureNcclCommunicator(allreduce_grad_dtype=...)`` knob mapped
      onto codecs).  The compiled step issues ONE collective per bucket
      (default: 4 MiB targets coalesced into at most 6 buckets) instead
      of one per gradient leaf.
    * ``"per_leaf"`` — the pre-wire lowering (one psum per leaf), kept
      as the A/B baseline and escape hatch.
    * a codec name (``"none"``/``"f32"``/``"bf16"``/``"f16"``/
      ``"int8"``) or a :class:`~chainermn_tpu.comm_wire.WireConfig`
      (codec + bucket_bytes + max_buckets + error_feedback +
      schedule) — explicit control.  ``int8`` ships 1 byte/element
      plus one f32 scale per bucket; combine with
      ``error_feedback=True`` so rounding error is carried into the
      next step (fp32-equivalent convergence, pinned by the MLP
      convergence test).

    ``WireConfig.schedule`` (``"auto"``/``"flat"``/``"hier_rs_ag"``)
    selects the per-bucket collective schedule
    (``comm_wire.schedules``): on a hierarchical
    (``mn_inter`` × ``mn_intra``) mesh, ``hier_rs_ag`` replaces each
    bucket's flat psum with a full-precision intra-slice
    reduce-scatter, a codec-compressed inter-slice all-reduce on the
    1/K shard (the codec — and the error-feedback residual — applies
    to that hop only, DynamiQ-style), and an intra all-gather; the
    ``auto`` decision stages a bucket exactly when the ring-formula
    inter-hop byte savings clear the launch-latency threshold.  The
    chosen schedule is part of the agreed plan hash, so ranks cannot
    schedule apart.  Meshes with no genuine split (incl. the ragged
    width-1 ``mn_inter`` fallback) collapse an explicit ``hier_rs_ag``
    to ``flat`` with a logged warning.

    ``tune_trace``: a :class:`~chainermn_tpu.analysis.trace.
    CollectiveTrace` (or its records) of the step that will ship these
    gradients.  With ``wire="auto"``, the bucket byte target and slot
    budget are then tuned by ``comm_wire.tune_wire_for_trace`` from
    the trace's per-collective cost model (``bytes_on_wire`` + hop
    class) instead of the fixed 4 MiB / 6-bucket constants — the
    production consumer of the PR 6 tuner.  Typical use: build a step,
    ``tr = step.collective_trace(p, o, batch)``, then rebuild the
    optimizer with ``tune_trace=tr``.

    ``profile``: a measured :class:`~chainermn_tpu.comm_wire.autotune.
    BandwidthProfile` — or a path to one, or ``"auto"`` to load the
    path named by ``CHAINERMN_TPU_WIRE_PROFILE`` — that closes the
    telemetry→planner loop (ISSUE 12).  Every wire plan's
    ``schedule="auto"`` flat-vs-hier decision is then made by
    *predicted time* (interpolated achieved bandwidth + per-hop launch
    latency) instead of the analytic byte heuristic, and with
    ``tune_trace`` the bucket byte target / slot budget minimize
    predicted sync time.  The profile's content hash is folded into
    the ``WirePlan.plan_hash()`` exchanged by ``plan_agreement``, so
    two ranks holding different profiles raise
    ``WirePlanMismatchError`` before the first collective — and a rank
    that cannot load the named profile raises
    ``comm_wire.ProfileMissingError`` at construction rather than
    silently planning with the constants.  Tuned plans only ever
    REDUCE collective counts (candidates stay under ``max_buckets``),
    so every ``analysis.budgets`` ceiling holds for any tune.

    ``overlap`` (``"none"``/``"bucket"``): the bucket-granularity
    comm/compute overlap engine (``comm_wire.overlap``).  With
    ``"bucket"``, ``build_train_step`` reschedules the compiled step so
    each wire bucket's fused psum is dispatched the moment its bucket's
    leaves are produced by backward — communication hides under the
    remaining backward segments instead of queueing at the program
    tail.  Bit-identical to ``"none"`` (same buckets, codec, and
    reduction order — the pass only reorders equations) and the
    collective census is unchanged, so every analysis budget pin holds
    either way.  Works with every wire (incl. ``"per_leaf"``) and the
    ZeRO path; not combinable with ``double_buffering`` (staleness and
    in-step overlap are competing answers to the same latency — see
    below).

    ``double_buffering`` (stale-by-one gradients, reference parity):
    LEAVE IT OFF unless you have measured a win on your topology.  On a
    single chip and on the virtual mesh the A/B shows no benefit — on
    chip the compiled psum already overlaps with backward compute, and
    the virtual-mesh measurement was 16 % SLOWER with it on
    (docs/performance.md "Double-buffering, measured"); its design
    target (DCN-crossing topologies where gradient sync rides a slow
    link) is the one place it can pay.  ``overlap="bucket"`` hides the
    same sync without applying stale gradients — prefer it.
    """
    from .comm_wire import resolve_overlap

    if resolve_overlap(overlap) == "bucket" and double_buffering:
        raise ValueError(
            "overlap='bucket' cannot be combined with double_buffering: "
            "double buffering hides sync by applying one-step-stale "
            "gradients, the overlap engine hides it inside the same "
            "step with exact gradients — combining would pay staleness "
            "for nothing"
        )
    if zero_redundancy and double_buffering:
        raise ValueError(
            "zero_redundancy and double_buffering cannot be combined: "
            "double buffering stores full-width stale gradients, which "
            "defeats the sharded-state memory saving"
        )
    if zero_redundancy:
        cls = _ZeroRedundancyOptimizer
    elif double_buffering:
        cls = _DoubleBufferingOptimizer
    else:
        cls = _MultiNodeOptimizer
    opt = cls(actual_optimizer, communicator, wire=wire, overlap=overlap,
              tune_trace=tune_trace, profile=profile)
    cfg = opt.wire  # resolved + validated ONCE, by the constructor
    if cfg is not None and cfg.error_feedback:
        if double_buffering:
            raise ValueError(
                "error_feedback cannot be combined with double_buffering: "
                "the residual would correct a gradient that is already "
                "one step stale by the time it ships"
            )
        if zero_redundancy:
            raise ValueError(
                "error_feedback is not supported on the zero_redundancy "
                "path (the residual of a reduce-scattered bucket lives "
                "on no single rank)"
            )
    if zero_redundancy and cfg is not None and cfg.codec == "int8":
        raise ValueError(
            "int8 wire is not supported on the zero_redundancy path; "
            "use bf16/f16"
        )
    return opt


# ----------------------------------------------------------------------
# Compiled data-parallel train step builder — the performance path the
# reference reached via Trainer + _MultiNodeOptimizer (SURVEY.md section
# 3.2: "the entire box under optimizer.update becomes ONE jitted function").
# ----------------------------------------------------------------------
def build_train_step(
    comm,
    loss_fn,
    optimizer,
    *,
    data_axes: Optional[tuple] = None,
    param_specs=None,
    batch_specs=None,
    accum_steps: int = 1,
    remat=False,
    donate: bool = True,
    use_shard_map: bool = True,
    has_aux: bool = False,
    merge_aux=None,
    nonfinite: Optional[str] = None,
):
    """Build a jitted SPMD data-parallel training step.

    ``loss_fn(params, batch) -> scalar loss`` written for a *local* batch.
    The returned ``step(params, opt_state, batch) -> (params, opt_state,
    metrics)`` runs on the communicator's full mesh: the batch is sharded
    along its leading axis over every mesh axis, parameters are replicated,
    and gradient averaging is a ``psum`` compiled into the program (riding
    ICI, overlapped with backward compute by XLA's scheduler).

    With ``use_shard_map=False`` the step is plain ``jit`` + GSPMD sharding
    annotations (gradient sync via the compiler's partitioner) — same
    numerics, useful to A/B the two lowering styles.

    Mutable model state (flax BatchNorm ``batch_stats`` etc.): pass
    ``has_aux=True`` and write ``loss_fn(params, batch) -> (loss, aux)``.
    The aux pytree is mean-reduced across the mesh so the carried state
    stays replicated (for BN, the running-average EMAs are averaged — an
    approximation: the mean of per-shard variances underestimates global
    variance when shard means differ).  Training-time *normalization*
    still uses each shard's local batch statistics; for true sync-BN
    (global statistics inside the forward pass) use
    MultiNodeBatchNormalization / ``create_mnbn_model`` (SURVEY.md
    section 2 #21).  If ``merge_aux(params, aux) -> params`` is given, the
    reduced aux is folded back into the returned params *after* the
    optimizer update (so optimizer updates to non-trainable state are
    overwritten, never accumulated).

    Hybrid DP x TP (``param_specs``): on a 2-D mesh (e.g.
    ``HybridCommunicator``'s ``('mn_data', 'mn_model')``), pass
    ``data_axes=comm.data_axis_names`` and a ``param_specs`` pytree (or
    ``fn(params) -> pytree``) of PartitionSpecs declaring each parameter's
    layout — tensor-parallel kernels sharded over the model axis,
    everything else ``P()``.  The step then runs under vma-checked
    ``shard_map``: autodiff itself inserts every needed collective (psum
    of replicated-param cotangents over the model axis, data-axis
    reduction through the in-loss ``pmean``), so gradients are globally
    correct for sharded AND replicated parameters with no manual sync —
    the Megatron recipe as generated code.  Optimizer state follows the
    parameter layout automatically (Adam moments of a TP kernel are
    sharded like the kernel).  ``loss_fn`` may use the model axis freely
    (e.g. ColumnParallelDense/RowParallelDense); its returned loss must
    be model-axis-invariant (end TP blocks with their row-parallel psum).
    Not combinable with ``zero_redundancy`` optimizers or
    ``allreduce_grad_dtype`` wire compression (sync happens inside
    autodiff at full precision).

    ``batch_specs``: override the default leading-axis-over-data-axes
    batch layout with an explicit PartitionSpec (applied to every batch
    leaf).  The composed-parallelism case: a sequence-parallel LM on a
    ``MeshCommunicator`` shards tokens ``(batch, seq)`` as
    ``P('mn_data', 'mn_seq')`` — batch rows over the data axis AND
    sequence positions over the seq axis.

    ``accum_steps``: gradient accumulation — each chip's local batch is
    split into this many microbatches processed sequentially
    (``lax.scan``) inside the SAME compiled step, gradients averaged
    before the single optimizer update.  Activation memory drops to one
    microbatch's worth while the effective batch (and, for mean-style
    losses over equal microbatches, the numerics) match the unaccumulated
    step; gradient sync still happens once per step.  The per-chip batch
    must divide by it.

    ``nonfinite``: cross-rank non-finite-step guard (``None`` = off, no
    change to the compiled program).  With a policy set (``"skip"``,
    ``"abort"``, ``"warn"``), the step computes a single
    all-gradients-finite flag and — under ``shard_map`` — ``pmin``-s it
    over EVERY mesh axis, so all ranks agree bit-identically on whether
    the step was finite.  That agreement is the point: the classic
    divergence is one rank skipping a NaN step while the others apply
    it, after which the next collective deadlocks or silently mixes
    divergent parameter histories.  ``"skip"`` and ``"abort"`` select
    the PREVIOUS params/opt_state when the flag is down (an agreed
    no-op step, compiled as two ``where``-selects); ``"warn"`` applies
    the update anyway.  The flag is returned in the metrics as
    ``grads_finite`` (1.0/0.0); host-side policy (raising
    ``StepDivergedError`` for ``"abort"``, warning/logging) lives in
    ``training.trainer.Trainer``, which reads the step's
    ``nonfinite_policy`` attribute.

    ``remat``: rematerialize the forward pass in the backward
    (``jax.checkpoint`` around ``loss_fn``) — trade FLOPs for HBM.
    ``True`` uses JAX's default policy; pass a
    ``jax.checkpoint_policies`` policy (e.g.
    ``dots_with_no_batch_dims_saveable``) for finer control.  Composes
    with ``accum_steps`` (remat inside each microbatch).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = comm.mesh
    axes = tuple(data_axes or comm.axis_names)
    batch_spec = P(axes) if batch_specs is None else batch_specs
    rep = NamedSharding(mesh, P())
    batch_sharding = NamedSharding(mesh, batch_spec)

    from .comm_wire import resolve_overlap as _resolve_overlap
    from .comm_wire.overlap import OverlappedStep

    is_mn = isinstance(optimizer, _MultiNodeOptimizer)
    hybrid = param_specs is not None
    overlap_mode = _resolve_overlap(getattr(optimizer, "overlap", "none"))
    if overlap_mode == "bucket" and not use_shard_map:
        raise ValueError(
            "overlap='bucket' requires use_shard_map=True: on the GSPMD "
            "path the gradient collectives are inserted by the "
            "partitioner after lowering, so there is no authored psum "
            "for the overlap scheduler to move"
        )

    def _finish_build(sharded):
        """jit (or overlap-schedule) one built shard_map step."""
        if overlap_mode == "bucket":
            # comm_wire.overlap: trace -> reorder eqns so each bucket
            # psum issues at its dependency frontier -> jit.  Bit-
            # identical (pure reordering); donation maps to the flat
            # params/opt_state leaves.
            return OverlappedStep(
                sharded,
                donate_subtrees=2 if donate else 0,
                label="train_step",
            )
        return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())
    if hybrid and isinstance(optimizer, _ZeroRedundancyOptimizer):
        raise ValueError(
            "param_specs (hybrid DP x TP) cannot be combined with a "
            "zero_redundancy optimizer: ZeRO blocks shard over the full "
            "communicator, which would mix tensor-parallel kernel blocks"
        )
    if hybrid and getattr(comm, "allreduce_grad_dtype", None) is not None:
        raise ValueError(
            "param_specs (hybrid DP x TP) cannot honor "
            "allreduce_grad_dtype: gradient reduction happens inside "
            "vma-checked autodiff at full precision; create the hybrid "
            "communicator without a wire dtype"
        )
    if hybrid and _no_exchange(comm):
        raise ValueError(
            "a no-exchange (dummy) communicator cannot drive the hybrid "
            "param_specs path: its gradient collectives are generated "
            "by autodiff from the in-loss pmean, so there is no "
            "exchange to omit — the 'subtraction' would silently "
            "measure zero.  Use the dummy communicator on the "
            "data-parallel path only."
        )

    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if nonfinite not in (None, "skip", "abort", "warn"):
        raise ValueError(
            f"nonfinite must be None, 'skip', 'abort' or 'warn'; "
            f"got {nonfinite!r}"
        )
    if remat:
        loss_fn = (
            jax.checkpoint(loss_fn)
            if remat is True
            else jax.checkpoint(loss_fn, policy=remat)
        )

    def _value_and_grad(fn, params, batch):
        """value_and_grad of ``fn``, microbatched over ``accum_steps``
        splits of the local batch (scan keeps one microbatch's
        activations live).  Inexact outputs (loss, numeric aux leaves)
        are averaged; other aux leaves keep the last microbatch's value.
        """
        vg = jax.value_and_grad(fn, has_aux=has_aux)
        if accum_steps == 1:
            return vg(params, batch)
        tree_map = jax.tree_util.tree_map

        def split(x):
            b = x.shape[0]
            if b % accum_steps:
                raise ValueError(
                    f"per-chip batch {b} not divisible by "
                    f"accum_steps={accum_steps}"
                )
            return x.reshape((accum_steps, b // accum_steps) + x.shape[1:])

        mbs = tree_map(split, batch)
        # zero-seeded carry from abstract shapes: the model is traced
        # ONCE (inside the scan body) instead of once inline + once in
        # the scan — halves the step's HLO for large models
        first = tree_map(lambda x: x[0], mbs)
        out_sd, grads_sd = jax.eval_shape(vg, params, first)
        zeros = functools.partial(
            tree_map, lambda s: jnp.zeros(s.shape, s.dtype)
        )

        def add(a, b):
            a = jnp.asarray(a)
            # inexact leaves accumulate; others keep the latest value
            return a + b if jnp.issubdtype(a.dtype, jnp.inexact) else b

        def body(carry, mb):
            c_out, c_grads = carry
            out, grads = vg(params, mb)
            return (
                tree_map(add, c_out, out),
                tree_map(jnp.add, c_grads, grads),
            ), None

        (out_sum, grad_sum), _ = lax.scan(
            body, (zeros(out_sd), zeros(grads_sd)), mbs
        )

        def mean(a):
            a = jnp.asarray(a)
            return (
                a / accum_steps
                if jnp.issubdtype(a.dtype, jnp.inexact)
                else a
            )

        return (
            tree_map(mean, out_sum),
            tree_map(lambda g: g / accum_steps, grad_sum),
        )

    def _param_spec_tree(params):
        return param_specs(params) if callable(param_specs) else param_specs

    all_axes = tuple(comm.axis_names)

    def _guarded_apply(params, opt_state, grads, do_update, *, bound):
        """Run ``do_update(grads) -> (params', opt_state')`` under the
        cross-rank non-finite guard.  ``bound``: whether mesh axes are
        bound (shard_map) — then the finite flag is ``pmin``-ed over
        every axis so ALL ranks agree to skip or apply, preventing the
        skip-on-one-rank / apply-on-the-rest deadlock.  Returns
        ``(params', opt_state', metrics_extra)``."""
        if nonfinite is None:
            p, s = do_update(grads)
            return p, s, {}
        finite = _tree_all_finite(grads)
        if bound:
            finite = lax.pmin(finite.astype(jnp.int32), all_axes) > 0
        new_p, new_s = do_update(grads)
        if nonfinite != "warn":
            def sel(n, o):
                return jnp.where(finite, n, o)

            new_p = jax.tree_util.tree_map(sel, new_p, params)
            new_s = jax.tree_util.tree_map(sel, new_s, opt_state)
        return new_p, new_s, {"grads_finite": finite.astype(jnp.float32)}

    # ZeRO-style optimizers declare per-leaf state sharding; the concrete
    # spec tree depends on the state's structure, so the program is built
    # lazily at first call and cached by state treedef.
    state_spec_fn = getattr(optimizer, "state_partition_spec", None)

    def _state_specs(opt_state, params=None):
        if hybrid:
            # optimizer state mirrors the parameter layout: every
            # param-shaped leaf (Adam moments etc.) inherits its
            # parameter's spec, the rest (counts) replicate
            pspecs = _param_spec_tree(params)
            return optax.tree_map_params(
                optimizer,
                lambda _leaf, spec: spec,
                opt_state,
                pspecs,
                transform_non_params=lambda _leaf: P(),
            )
        if state_spec_fn is None:
            return P()
        return state_spec_fn(opt_state)

    def _spec_to_sharding(specs):
        if isinstance(specs, P):
            return NamedSharding(mesh, specs)
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            specs,
            is_leaf=lambda x: isinstance(x, P),
        )

    def _state_shardings(opt_state, params=None):
        if not hybrid and state_spec_fn is None:
            return rep
        return _spec_to_sharding(_state_specs(opt_state, params))

    # Old-shard_map jax tier: autodiff under check_rep=False returns the
    # UNSUMMED per-shard cotangent for every leaf, so each gradient must
    # be psummed over exactly the mesh axes its parameter does NOT span
    # (replicated leaves: all axes; TP-sharded kernels: the data axes).
    # On current jax the vma machinery inserts these psums itself.
    from . import _compat as _jax_compat

    def _manual_rep_sum(grads, pspecs):
        axis_order = tuple(mesh.axis_names)

        def spec_axes(spec):
            out = set()
            for part in tuple(spec):
                if part is None:
                    continue
                for a in (part if isinstance(part, tuple) else (part,)):
                    out.add(a)
            return out

        def fix(g, spec):
            missing = tuple(
                a for a in axis_order if a not in spec_axes(spec)
            )
            return lax.psum(g, missing) if missing else g

        # flatten_up_to: PartitionSpec may itself flatten as a pytree,
        # so pair specs to gradient LEAVES by the gradients' structure
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        specs = treedef.flatten_up_to(pspecs)
        return treedef.unflatten(
            [fix(g, s) for g, s in zip(leaves, specs)]
        )

    def _make_do_update(params, opt_state, aux, *, hybrid_sync=False):
        """The update/apply/merge_aux tail shared by all three step
        bodies (one definition so the nonfinite where-select ordering
        cannot diverge between lowering paths).  ``hybrid_sync``: the
        hybrid path's autodiff already produced globally-synced grads,
        so a multi-node optimizer must skip its own sync."""
        def do_update(g):
            if hybrid_sync and is_mn:
                updates, new_state = optimizer.update(
                    g, opt_state, params, sync_axes=()
                )
            else:
                updates, new_state = optimizer.update(
                    g, opt_state, params
                )
            p = optax.apply_updates(params, updates)
            if aux is not None and merge_aux is not None:
                p = merge_aux(p, aux)
            return p, new_state

        return do_update

    if use_shard_map and hybrid:
        def _step(params, opt_state, batch):
            # Differentiate the GLOBAL loss (pmean over the data axes is
            # part of the objective); vma-checked shard_map autodiff then
            # emits every collective the mixed replicated/sharded layout
            # needs — no manual gradient sync anywhere.
            def global_loss(p, b):
                out = loss_fn(p, b)
                if has_aux:
                    l, aux = out
                    return lax.pmean(l, axes), aux
                return lax.pmean(out, axes)

            loss, grads = _value_and_grad(global_loss, params, batch)
            if _jax_compat.OLD_SHARD_MAP:
                grads = _manual_rep_sum(grads, _param_spec_tree(params))
            aux = None
            if has_aux:
                loss, aux = loss
                aux = jax.tree_util.tree_map(
                    lambda a: lax.pmean(a, axes)
                    if jnp.issubdtype(jnp.asarray(a).dtype, jnp.inexact)
                    else a,
                    aux,
                )
            params, opt_state, extra = _guarded_apply(
                params, opt_state, grads,
                _make_do_update(params, opt_state, aux, hybrid_sync=True),
                bound=True,
            )
            return params, opt_state, {"loss": loss, **extra}

        def _build(state_specs, pspecs):
            sharded = jax.shard_map(
                _step,
                mesh=mesh,
                in_specs=(pspecs, state_specs, batch_spec),
                out_specs=(pspecs, state_specs, P()),
                # vma checking ON: it is what makes the autodiff insert
                # the replication-correct psums
            )
            return _finish_build(sharded)
    elif use_shard_map:
        def _step(params, opt_state, batch):
            loss, grads = _value_and_grad(loss_fn, params, batch)
            aux = None
            if has_aux:
                loss, aux = loss
                aux = jax.tree_util.tree_map(
                    lambda a: lax.pmean(a, axes)
                    if jnp.issubdtype(jnp.asarray(a).dtype, jnp.inexact)
                    else a,
                    aux,
                )
            if not is_mn and not _no_exchange(comm):
                grads = _sync_grads(grads, comm)
            params, opt_state, extra = _guarded_apply(
                params, opt_state, grads,
                _make_do_update(params, opt_state, aux),
                bound=True,
            )
            loss = lax.pmean(loss, axes)
            return params, opt_state, {"loss": loss, **extra}

        def _build(state_specs, pspecs=None):
            del pspecs
            sharded = jax.shard_map(
                _step,
                mesh=mesh,
                in_specs=(P(), state_specs, batch_spec),
                out_specs=(P(), state_specs, P()),
                check_vma=False,
            )
            return _finish_build(sharded)
    else:
        def _step(params, opt_state, batch):
            loss, grads = _value_and_grad(loss_fn, params, batch)
            aux = None
            if has_aux:
                loss, aux = loss

            # GSPMD path: grads are global arrays, so the finite flag is
            # already globally agreed — no pmin needed (axes unbound).
            params, opt_state, extra = _guarded_apply(
                params, opt_state, grads,
                _make_do_update(params, opt_state, aux),
                bound=False,
            )
            return params, opt_state, {"loss": loss, **extra}

        def _build(state_shardings, pshardings=None):
            pshardings = rep if pshardings is None else pshardings
            return jax.jit(
                _step,
                donate_argnums=(0, 1) if donate else (),
                in_shardings=(pshardings, state_shardings, batch_sharding),
                out_shardings=(pshardings, state_shardings, rep),
            )

    def _axis_prod(names):
        if names is None:
            return 1
        if isinstance(names, str):
            names = (names,)
        n = 1
        for a in names:
            n *= dict(mesh.shape)[a]
        return n

    if batch_specs is None:
        n_shards = _axis_prod(axes)
    else:  # leading-dim divisibility is set by the spec's first entry
        n_shards = _axis_prod(batch_spec[0] if len(batch_spec) else None)
    n_procs = comm.process_count
    local_shards = max(n_shards // n_procs, 1)

    def _check_batch(batch, divisor, kind):
        leaves = jax.tree_util.tree_leaves(batch)
        if leaves and hasattr(leaves[0], "shape") and leaves[0].ndim:
            b = leaves[0].shape[0]
            if b % divisor:
                raise ValueError(
                    f"{kind} batch size {b} is not divisible by the "
                    f"{divisor} chips it feeds; pick a batch size that is "
                    f"a multiple of {divisor} (iterators with "
                    "drop_last=True and scatter_dataset's equalized shards "
                    "guarantee this)"
                )

    def _place_batch(batch):
        """Place a batch as a global array.

        Single controller: the array IS the global batch; device_put shards
        it.  Multi-process: each controller holds its *local* rows, so the
        global array is assembled from per-process shards.
        """
        if n_procs > 1:
            from jax.experimental import multihost_utils

            _check_batch(batch, local_shards, "per-process")
            return jax.tree_util.tree_map(
                lambda x: jax.make_array_from_process_local_data(
                    batch_sharding, np.asarray(x)
                ),
                batch,
            )
        _check_batch(batch, n_shards, "global")
        return jax.device_put(batch, batch_sharding)

    def _is_placed(batch):
        """True iff every leaf is already a global array laid out per
        this step's batch sharding — only then is re-placement safely
        skippable.  A default-device jnp array is a jax.Array too, but
        NOT 'placed' (it still needs the shard layout), so the check
        compares shardings, not just types."""
        def ok(l):
            if not isinstance(l, jax.Array):
                return False
            try:
                return l.sharding.is_equivalent_to(batch_sharding, l.ndim)
            except Exception:
                return l.sharding == batch_sharding

        leaves = jax.tree_util.tree_leaves(batch)
        return bool(leaves) and all(ok(l) for l in leaves)

    compiled: dict = {}

    # -- collective divergence guard (chainermn_tpu.analysis) ----------
    # In a multi-process world, the first dispatch of EVERY compiled
    # program variant (keyed by params/opt_state structure AND batch
    # avals — anything that can retrace into a different collective
    # sequence) first walks the step's jaxpr into its ordered
    # CollectiveTrace and exchanges the canonical hash over the host
    # control plane (like comm_wire's plan_agreement): rank-divergent
    # collective sequences raise CollectiveTraceMismatchError loudly on
    # EVERY rank before any device collective can deadlock.  Pure
    # tracing — nothing compiles or executes; single-process worlds
    # skip it entirely.  Opt out with CHAINERMN_TPU_TRACE_GUARD=0.
    _guard_enabled = [getattr(comm, "process_count", 1) > 1]
    _guard_verified: set = set()

    def _guard_key(params, opt_state, batch):
        # structure AND leaf avals of all three args: a same-structure
        # tree with resized/recast leaves retraces into a program whose
        # collective sequence can differ (the bucket plan is a function
        # of shapes), so it must be re-guarded, not skipped.  Cost: one
        # flatten per arg per step, multi-process worlds only —
        # single-process pays a single bool check.
        def sig(tree):
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            return (treedef, tuple(
                (tuple(getattr(l, "shape", ())),
                 str(getattr(l, "dtype", "")))
                for l in leaves
            ))

        return (sig(params), sig(opt_state), sig(batch))

    def _collective_trace(params, opt_state, batch):
        """The step program's ordered CollectiveTrace (static; does not
        compile or execute).  The batch is placed/shaped first so the
        traced program is the one a real call would dispatch."""
        from .analysis import trace_collectives

        if not _is_placed(batch):
            batch = _place_batch(batch)
        return trace_collectives(
            _get_step(params, opt_state), params, opt_state, batch,
            label="train_step",
        )

    def _verify_collective_trace(params, opt_state, batch, *, _key=None):
        """Force the divergence guard now (any world size): trace, then
        exchange the hash across processes.  Returns the agreed hash.

        Disarm semantics, per program variant: the variant's key is
        marked verified on success and on a MISMATCH (fatal —
        re-checking would replay the same divergent program), but a
        transient exchange failure leaves it UNverified so an
        auto-resumed run re-verifies instead of silently skipping
        straight into the potential deadlock."""
        from .analysis import trace_agreement
        from .resilience.errors import CollectiveTraceMismatchError

        key = _key if _key is not None else _guard_key(
            params, opt_state, batch
        )
        try:
            agreed = trace_agreement(
                comm, _collective_trace(params, opt_state, batch),
                label="train_step",
            )
        except CollectiveTraceMismatchError:
            _guard_verified.add(key)
            raise
        _guard_verified.add(key)
        return agreed

    def _maybe_trace_guard(params, opt_state, batch, key):
        import os as _os

        if _os.environ.get("CHAINERMN_TPU_TRACE_GUARD", "1") == "0":
            _guard_enabled[0] = False
            return
        _verify_collective_trace(params, opt_state, batch, _key=key)

    def _get_step(params, opt_state):
        key = (
            jax.tree_util.tree_structure(params),
            jax.tree_util.tree_structure(opt_state),
        )
        if key not in compiled:
            if use_shard_map:
                state_arg = _state_specs(opt_state, params)
                param_arg = _param_spec_tree(params) if hybrid else None
            else:
                state_arg = _state_shardings(opt_state, params)
                param_arg = (
                    _spec_to_sharding(_param_spec_tree(params))
                    if hybrid
                    else None
                )
            compiled[key] = _build(state_arg, param_arg)
        return compiled[key]

    def checked_step(params, opt_state, batch):
        if not _is_placed(batch):
            batch = _place_batch(batch)
        if _guard_enabled[0]:
            key = _guard_key(params, opt_state, batch)
            if key not in _guard_verified:
                _maybe_trace_guard(params, opt_state, batch, key)
        return _get_step(params, opt_state)(params, opt_state, batch)

    def place(params, opt_state=None, batch=None):
        """Device-put helper: lay out params per their partition specs
        (replicated unless hybrid), optimizer state per its spec (sharded
        for ZeRO / hybrid), shard a batch."""
        pshard = (
            _spec_to_sharding(_param_spec_tree(params)) if hybrid else rep
        )
        out = [jax.device_put(params, pshard)]
        if opt_state is not None:
            out.append(
                jax.device_put(opt_state, _state_shardings(opt_state, params))
            )
        if batch is not None:
            out.append(_place_batch(batch))
        return out[0] if len(out) == 1 else tuple(out)

    place_batch = _place_batch

    checked_step.place = place
    checked_step.place_batch = place_batch
    checked_step.is_placed = _is_placed
    checked_step.batch_sharding = batch_sharding
    checked_step.replicated_sharding = rep
    checked_step.get_jitted = _get_step
    # Exposed so timing harnesses that re-enter with the same buffers
    # (k-steps-in-one-dispatch loops) can refuse a donated step, whose
    # warm call would consume params/opt_state and corrupt later calls.
    checked_step.donate = donate
    # The trainer reads this to apply the host-side half of the policy
    # (raise StepDivergedError on "abort", warn/log on the others).
    checked_step.nonfinite_policy = nonfinite
    # Static-analysis surface (chainermn_tpu.analysis): the step's
    # ordered collective trace, and the explicit form of the divergence
    # guard the first multi-process dispatch runs automatically.
    checked_step.collective_trace = _collective_trace
    checked_step.verify_collective_trace = _verify_collective_trace

    def _memory_estimate(params, opt_state, batch):
        """Per-rank HBM estimate of this step's program (static; does
        not compile or execute) — ``analysis.memory.train_step_memory``
        over the shard_map body, where ZeRO state shards and batch
        shards already carry their per-rank shapes."""
        from .analysis.memory import train_step_memory

        return train_step_memory(
            checked_step, params, opt_state, batch, label="train_step"
        )

    checked_step.memory_estimate = _memory_estimate
    return checked_step

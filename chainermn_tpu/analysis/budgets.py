"""Pinned collective budgets for the repo's compiled paths.

Collective *count* is a performance contract: the flat-wire layer exists
precisely to turn ResNet-50's 267-leaf psum storm into <= 6 bucket
reductions + 1 loss pmean, and regressions re-introduce themselves
silently (one refactor that defeats bucketing costs nothing at trace
time and everything on the wire).  Each entry here is a ceiling, not a
target — enforced by :func:`~chainermn_tpu.analysis.checks.
assert_within_budget` on the walker census in the tier-1 tests, where a
string-grep of HLO used to live.

Ceilings derive from the wire plan: ``DEFAULT_MAX_BUCKETS`` (6) grad
buckets + 1 loss pmean (+1 int8 scale pmax where applicable).  ZeRO
replaces the bucket all-reduces with one reduce-scatter down and one
all-gather up per bucket.  The MoE expert path adds exactly 2 all_to_all
per MoE layer (dispatch + return) and the pipeline path 1 ppermute per
stage edge per direction.
"""

from __future__ import annotations

from .trace import CollectiveTrace
from .checks import assert_within_budget

# The data-parallel all_reduce ceiling is the wire-plan contract:
# comm_wire.DEFAULT_MAX_BUCKETS (6) grad buckets + 1 loss pmean, with
# one ceiling notch of slack (= 8) so a bucket-count change inside the
# promised <= 6 never trips the pin.  Numbers are literal (not imported)
# so a planner default drift FAILS the pin instead of moving it.
#
# Measured-feedback tuning (ISSUE 12) does not get its own pins: these
# ceilings are CONTRACTS a tuned plan must still satisfy, and the tuner
# guarantees it structurally — candidate slot budgets never exceed
# max_buckets, so tuning may only REDUCE collective counts (pinned by
# tests/test_autotune.py enforcing mlp_train_step on a profile-tuned
# compiled step).
BUDGETS = {
    # ISSUE 5 acceptance: the ResNet-50 train step stays <= 8 all-reduce
    # (267 leaves -> 4 default buckets + 1 loss pmean measured; 8 is the
    # contract ceiling the wire layer promised in ISSUE 4).
    "resnet50_train_step": {"all_reduce": 8},
    # transformer LM data-parallel step: same wire plan contract.
    "transformer_train_step": {"all_reduce": 8},
    # MLP/MNIST tier: small trees still bucket (never leaf-storm).
    "mlp_train_step": {"all_reduce": 8},
    # ZeRO-1: one reduce-scatter down + one all-gather up per bucket,
    # loss pmean stays the only all-reduce.
    "zero_train_step": {
        "reduce_scatter": 6,
        "all_gather": 6,
        "all_reduce": 1,
    },
    # Expert-parallel MoE layer: dispatch + return = exactly 2
    # all_to_all per call (``parallel.expert_parallel``).
    "ep_moe_layer": {"all_to_all": 2},
    # Pipeline forward chain: one ppermute edge per stage boundary and
    # one loss-broadcast psum (``parallel.pipeline``).
    "pipeline_forward": {"collective_permute": 1, "all_reduce": 1},
    # ISSUE 6 satellite: the seq2seq pipeline BACKWARD was unguarded —
    # only the forward ppermute was pinned.  Differentiating the gpipe
    # scan yields exactly ONE transposed ppermute (the reverse ring
    # edge, in the backward scan body) and one transposed loss psum:
    # the full train step is fwd + bwd = 2 ppermute + 2 psum, and a
    # schedule regression that unrolls the reverse ring (one permute
    # per microbatch) trips this pin.
    "pipeline_train_step": {"collective_permute": 2, "all_reduce": 2},
    # ISSUE 11: per-schedule collective counts for the multi-hop wire.
    # hier_rs_ag costs exactly 1 reduce_scatter + 1 all_reduce + 1
    # all_gather per bucket (vs flat's 1 all_reduce/bucket): <= 6
    # buckets -> rs <= 6, ag <= 6, ar <= 6 bucket inter-hops + 1 loss
    # pmean = 7.
    "hier_train_step": {
        "all_reduce": 7,
        "reduce_scatter": 6,
        "all_gather": 6,
    },
    # int8 inter hop adds exactly ONE batched scale pmax over the hier
    # buckets (the flat tier's one-extra-collective contract, applied
    # per schedule class): ar ceiling 8.
    "hier_int8_train_step": {
        "all_reduce": 8,
        "reduce_scatter": 6,
        "all_gather": 6,
    },
    # ZeRO's staged blocked path: the single full-mesh rs/ag pair per
    # bucket becomes 2 rs down (intra full-precision + inter on the
    # wire) and 2 ag up; the loss pmean stays the only all_reduce.
    "zero_hier_train_step": {
        "reduce_scatter": 12,
        "all_gather": 12,
        "all_reduce": 1,
    },
    # the eager bcast_tree multicast: exactly 2 masked psums (inter
    # root->leaders, intra leaders->slices) — vs 1 for the flat
    # spelling; a regression to per-stage-per-rank storms trips this.
    "bcast_tree": {"all_reduce": 2},
    # ISSUE 13: the serving tier's tensor-parallel single-token decode
    # step (serving.decode, 2-layer pinned fixture).  Decode is
    # collective-LATENCY-bound ("Understanding and Improving
    # Communication Performance in Multi-node LLM Inference",
    # PAPERS.md), so the count per token IS the latency floor: exactly
    # 2 row-parallel psums per layer (attention out-proj + MLP
    # down-proj) and nothing else — the replicated embedding, paged
    # cache write, and tied head cost zero collectives.  The ceiling
    # is EXACT (no slack notch): any extra collective per token is a
    # regression the latency budget cannot absorb.  The prefill
    # program has the identical census (the pin is enforced on both
    # traces in tests/test_serving.py).
    "decode_step": {"all_reduce": 4},
    # ISSUE 17: the speculative verify program (serving.decode
    # verify_step, same 2-layer fixture) scores k draft tokens per
    # slot in ONE batched step — the s=k program runs the SAME two
    # row-parallel psums per layer as the s=1 decode step, so the k
    # tokens amortize an unchanged collective count.  That amortization
    # is speculative decode's entire value on a latency-bound
    # interconnect, so the ceiling is EXACT like decode_step's: a
    # verify program that added even one collective would scale its
    # cost with k and erase the win.
    "spec_verify_step": {"all_reduce": 4},
    # ISSUE 18: the prefill program under disaggregation (serving.
    # decode prefill phase, same 2-layer fixture).  TP prefill is the
    # same 2-row-parallel-psums-per-layer family as decode_step — the
    # prompt bucket rides the batch/seq dims, never the collective
    # count — so a PREFILL pool's cost per request is bucket-shaped
    # compute over a fixed collective floor.  EXACT like decode_step;
    # the KV handoff path itself (export -> codec pack -> import) is
    # separately pinned to ZERO collectives in tests/test_serving.py.
    "prefill_step": {"all_reduce": 4},
}

# ----------------------------------------------------------------------
# per-rank HBM ceilings (ISSUE 6): bytes a rank may hold at the live-
# range peak of the pinned train-step FIXTURES (the tier-1 test
# configs — tiny models on the 8-way CPU mesh; the estimator scales
# with the real model when you pin your own).  Ceilings carry one
# notch of slack over the measured estimate, and — like the collective
# ceilings — are literal numbers so an estimator or model drift FAILS
# the pin instead of silently moving it.  Enforced by
# :func:`enforce_memory` from ``analysis.memory.train_step_memory``.
MiB = 1024 * 1024
HBM_BUDGETS = {
    # ResNet-50 fixture (b=8 global, 64x64 imgs): 97.7 MiB params
    # resident + ~131 MiB transient (grads + conv activation chain +
    # fresh output params) = 229 MiB measured; 320 is the ceiling.
    "resnet50_train_step": 320 * MiB,
    # tiny transformer LM fixture (d=32, L=2, seq 16): 0.34 MiB
    # measured.
    "transformer_train_step": 1 * MiB,
    # ZeRO fixture (6144 params, adam): 0.10 MiB measured — per-rank
    # opt state is 1/8 of the replicated wrapper's; the pin is what
    # keeps the state_partition_spec annotation honest.
    "zero_train_step": 1 * MiB,
    # MoE transformer fixture (4 experts over the (2,2,2) mesh, top-2,
    # capacity 2x): 1.2 MiB measured.
    "moe_train_step": 4 * MiB,
}


class MemoryBudgetError(AssertionError):
    """A traced program exceeds its pinned per-rank HBM ceiling."""


def budget_for(name: str) -> dict:
    if name not in BUDGETS:
        raise KeyError(
            f"no pinned budget named {name!r}; known: {sorted(BUDGETS)}"
        )
    return dict(BUDGETS[name])


def enforce(name: str, trace: CollectiveTrace) -> dict:
    """Assert ``trace`` stays within the named pin; returns the census."""
    return assert_within_budget(trace, budget_for(name), name=name)


def memory_budget_for(name: str) -> int:
    if name not in HBM_BUDGETS:
        raise KeyError(
            f"no pinned HBM budget named {name!r}; "
            f"known: {sorted(HBM_BUDGETS)}"
        )
    return int(HBM_BUDGETS[name])


def enforce_memory(name: str, estimate) -> int:
    """Assert a :class:`~chainermn_tpu.analysis.memory.MemoryEstimate`'s
    per-rank peak stays under the named ceiling; returns the peak bytes.
    Raises :class:`MemoryBudgetError` with the estimate's breakdown
    otherwise — the memory analogue of :func:`enforce`."""
    ceiling = memory_budget_for(name)
    peak = int(estimate.peak_bytes)
    if peak > ceiling:
        raise MemoryBudgetError(
            f"per-rank HBM budget exceeded for {name}: peak "
            f"{peak / MiB:.1f} MiB > ceiling {ceiling / MiB:.1f} MiB "
            f"({estimate})"
        )
    return peak

"""Pinned collective budgets for the repo's compiled paths.

Collective *count* is a performance contract: the flat-wire layer exists
precisely to turn ResNet-50's 267-leaf psum storm into <= 6 bucket
reductions + 1 loss pmean, and regressions re-introduce themselves
silently (one refactor that defeats bucketing costs nothing at trace
time and everything on the wire).  Each entry here is a ceiling, not a
target — enforced by :func:`~chainermn_tpu.analysis.checks.
assert_within_budget` on the walker census in the tier-1 tests, where a
string-grep of HLO used to live.

Ceilings derive from the wire plan: ``DEFAULT_MAX_BUCKETS`` (6) grad
buckets + 1 loss pmean (+1 int8 scale pmax where applicable).  ZeRO
replaces the bucket all-reduces with one reduce-scatter down and one
all-gather up per bucket.  The MoE expert path adds exactly 2 all_to_all
per MoE layer (dispatch + return) and the pipeline path 1 ppermute per
stage edge per direction.
"""

from __future__ import annotations

from .trace import CollectiveTrace
from .checks import assert_within_budget

# The data-parallel all_reduce ceiling is the wire-plan contract:
# comm_wire.DEFAULT_MAX_BUCKETS (6) grad buckets + 1 loss pmean, with
# one ceiling notch of slack (= 8) so a bucket-count change inside the
# promised <= 6 never trips the pin.  Numbers are literal (not imported)
# so a planner default drift FAILS the pin instead of moving it.
BUDGETS = {
    # ISSUE 5 acceptance: the ResNet-50 train step stays <= 8 all-reduce
    # (267 leaves -> 4 default buckets + 1 loss pmean measured; 8 is the
    # contract ceiling the wire layer promised in ISSUE 4).
    "resnet50_train_step": {"all_reduce": 8},
    # transformer LM data-parallel step: same wire plan contract.
    "transformer_train_step": {"all_reduce": 8},
    # MLP/MNIST tier: small trees still bucket (never leaf-storm).
    "mlp_train_step": {"all_reduce": 8},
    # ZeRO-1: one reduce-scatter down + one all-gather up per bucket,
    # loss pmean stays the only all-reduce.
    "zero_train_step": {
        "reduce_scatter": 6,
        "all_gather": 6,
        "all_reduce": 1,
    },
    # Expert-parallel MoE layer: dispatch + return = exactly 2
    # all_to_all per call (``parallel.expert_parallel``).
    "ep_moe_layer": {"all_to_all": 2},
    # Pipeline forward chain: one ppermute edge per stage boundary and
    # one loss-broadcast psum (``parallel.pipeline``).
    "pipeline_forward": {"collective_permute": 1, "all_reduce": 1},
}


def budget_for(name: str) -> dict:
    if name not in BUDGETS:
        raise KeyError(
            f"no pinned budget named {name!r}; known: {sorted(BUDGETS)}"
        )
    return dict(BUDGETS[name])


def enforce(name: str, trace: CollectiveTrace) -> dict:
    """Assert ``trace`` stays within the named pin; returns the census."""
    return assert_within_budget(trace, budget_for(name), name=name)

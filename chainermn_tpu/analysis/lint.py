"""mnlint — repo-level AST lint for collective discipline.

Run from the repo root (CI / conftest wire it into tier-1)::

    python -m chainermn_tpu.analysis.lint          # lint the repo
    python -m chainermn_tpu.analysis.lint PATH...  # lint specific paths

Exit status 0 = clean, 1 = violations (one ``path:line: [rule] message``
per line).

Rules
-----
``raw-collective``
    ``lax.psum``-family calls (psum / pmean / pmax / pmin / all_gather /
    all_gather_invariant / all_to_all / psum_scatter / ppermute /
    pshuffle / pgather) are forbidden outside the sanctioned
    communication modules — in every spelling: ``lax.psum``,
    ``jax.lax.psum``, module aliases (``import jax.lax as jl`` /
    ``from jax import lax as L`` / ``mylax = jax.lax``), and
    ``from jax.lax import psum`` smuggling.  Everything else must route
    through the audited wrappers (``functions.collectives`` /
    ``functions.point_to_point``) or the communicator API — that is what
    keeps the static analyzer's trace the single source of truth for
    what ships on the wire.  Sanctioned: ``comm_wire/`` (wire codecs),
    ``functions/`` (the audited wrappers themselves), ``parallel/``
    (SP/TP/EP/pipeline layers), ``communicators/`` (the eager tier),
    ``optimizers.py`` (the compiled-tier sync), ``_compat.py`` (shims),
    and ``analysis/`` (this package names primitives to find them).

``untimed-row``
    A benchmark row (dict literal in ``bench.py`` / ``benchmarks/``)
    carrying a timing-shaped key (``*_ms``, ``sec_per_*``, ``*_per_sec``,
    ``tflops*``, ...) must also carry the min-of-N protocol disclosure
    ``n_measurements`` (``spread_max_over_min`` rides along where >= 2
    positive samples exist).  Rows assembled dynamically (``**`` /
    ``.update``) are skipped — the rule targets literal rows that
    silently present one-shot timings as measurements.

``raw-timing``
    ``time.perf_counter()`` / ``time.time()`` calls are forbidden inside
    ``chainermn_tpu/`` outside the two sanctioned timing modules —
    ``observability/`` (the span timeline IS the timing layer) and
    ``utils/benchmarking.py`` (the min-of-N measurement protocol) — in
    every spelling: ``time.time``, module aliases (``import time as
    t``), and ``from time import perf_counter`` smuggling.  Ad-hoc
    timing in the package is how measurements drift from the protocol
    and escape the telemetry stream; route through
    ``observability.span``/``Timeline`` (or ``time.monotonic`` for
    plain interval arithmetic, which the rule deliberately permits —
    it is the clock both sanctioned layers run on).

Host-protocol rules (``--host-protocol`` / ``host_protocol=True``)
------------------------------------------------------------------
Ride-alongs from :mod:`.protolint` (exchange-site catalog rules:
``proto-duplicate-site`` / ``proto-raw-allgather`` / ``proto-magic-tag``
/ ``proto-adhoc-manifest``) plus three SPMD-determinism rules scoped to
``DECISION_MODULES`` — the modules whose values feed cross-rank
decisions (serving placement, fleet rendezvous, elastic resharding,
checkpoint step election, wire planning), where any per-process
nondeterminism becomes a protocol divergence:

``spmd-hash``
    Builtin ``hash()`` is salted per process (``PYTHONHASHSEED``): two
    ranks hashing the same string disagree.  Use ``hashlib`` digests
    for anything that crosses a rank boundary.

``spmd-unsorted-scan``
    Iterating a raw ``os.listdir``/``os.scandir``/``glob.glob``/
    ``glob.iglob`` result (directly, or via a name assigned from one),
    or iterating a ``set``, yields filesystem/hash order — which
    differs across hosts.  Wrap in ``sorted(...)``; generator
    expressions fed straight into an order-insensitive reducer
    (``sorted``/``min``/``max``/``sum``/``len``/``any``/``all``/
    ``set``/``frozenset``) are exempt.

``spmd-random``
    ``random``-module draws (and ``np.random`` global-state draws) are
    seeded per process; a cross-rank decision sampled from them
    diverges silently.  Use ``jax.random`` with an explicitly agreed
    key, or a seeded ``np.random.RandomState``/``default_rng``
    instance (constructors are not draws, so those are untouched).

The SPMD allowlist is **closed and empty** (``SPMD_ALLOWLIST = ()``):
no decision module is exempt; escapes are per-line pragmas only.

Per-line escape hatch (same line or the line above)::

    # mnlint: allow(raw-collective)
    # mnlint: allow(untimed-row)
    # mnlint: allow(raw-timing)
    # mnlint: allow(spmd-hash)
    # mnlint: allow(spmd-unsorted-scan)
    # mnlint: allow(spmd-random)
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass
from typing import List, Optional, Sequence

COLLECTIVE_CALLS = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "psum_scatter", "ppermute", "pshuffle", "pgather",
    "all_gather_invariant",
})

# repo-relative path prefixes (POSIX separators) sanctioned for raw
# lax collective calls — the communication layer itself
SANCTIONED = (
    "chainermn_tpu/comm_wire/",
    "chainermn_tpu/functions/",
    "chainermn_tpu/parallel/",
    "chainermn_tpu/communicators/",
    "chainermn_tpu/analysis/",
    "chainermn_tpu/optimizers.py",
    "chainermn_tpu/_compat.py",
)

SKIP_DIRS = {"__pycache__", ".git", "csrc", "_build", ".claude"}

# raw-timing: the forbidden wall/benchmark clocks, and where raw use of
# them IS the job (the timing layer itself)
TIMING_CALLS = frozenset({"time", "perf_counter"})
TIMING_SANCTIONED = (
    "chainermn_tpu/observability/",
    "chainermn_tpu/utils/benchmarking.py",
)

TIMING_KEY_RE = re.compile(
    r"(^|_)ms($|_)|_ms$"            # iter_ms, step_time_ms, rtt_ms, ms_*
    r"|(^|_)sec(ond)?s?($|_)"       # sec_per_generate, seconds, *_sec
    r"|_per_sec$|_per_s$"           # new_tokens_per_sec
    r"|^tflops|^gflops"             # tflops_per_sec
    r"|_per_step$"
)

PRAGMA_RE = re.compile(r"#\s*mnlint:\s*allow\(([a-z-]+)\)")

# ----------------------------------------------------------------------
# host-protocol (--host-protocol) rule scoping
# ----------------------------------------------------------------------
# Modules whose values feed cross-rank decisions: serving placement and
# scan-driven admission, fleet rendezvous/control, elastic resharding,
# peer-checkpoint healing, checkpoint step election, wire planning.
# Per-process nondeterminism here IS a protocol divergence.
DECISION_MODULES = (
    "chainermn_tpu/serving/",
    "chainermn_tpu/fleet/",
    "chainermn_tpu/resilience/adaptive.py",
    "chainermn_tpu/resilience/elastic.py",
    "chainermn_tpu/resilience/peer_ckpt.py",
    "chainermn_tpu/extensions/checkpoint.py",
    "chainermn_tpu/comm_wire/planner.py",
    "chainermn_tpu/comm_wire/autotune.py",
    "chainermn_tpu/comm_wire/schedules.py",
)

# CLOSED allowlist: no decision module may opt out wholesale.  Escapes
# are per-line pragmas only, so every exemption is visible in the diff
# that introduces it.  (The tuple stays defined so tests can pin that
# serving/ and fleet/ never creep onto it.)
SPMD_ALLOWLIST: tuple = ()

# spmd-unsorted-scan: raw directory/glob scans whose order is
# filesystem-dependent, and the order-insensitive reducers a generator
# over one may feed directly
SCAN_CALLS = frozenset({"listdir", "scandir", "glob", "iglob"})
ORDER_INSENSITIVE = frozenset({
    "sorted", "min", "max", "sum", "len", "any", "all", "set",
    "frozenset",
})

# spmd-random: global-state draw names on random / np.random.
# Constructors (RandomState, default_rng, PRNGKey, Generator) are NOT
# here — a seeded instance is the sanctioned fix.
RANDOM_DRAWS = frozenset({
    "random", "rand", "randn", "randint", "randrange", "shuffle",
    "permutation", "choice", "sample", "uniform", "gauss", "seed",
    "getrandbits", "standard_normal", "bytes",
})


@dataclass(frozen=True)
class Violation:
    path: str       # repo-relative
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _allowed(lines: Sequence[str], lineno: int, rule: str) -> bool:
    """Pragma on the flagged line or the line directly above."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            m = PRAGMA_RE.search(lines[ln - 1])
            if m and m.group(1) == rule:
                return True
    return False


def _is_lax_base(node: ast.expr, aliases=frozenset()) -> bool:
    """True for ``lax`` / ``jax.lax`` / ``...lax`` attribute bases and
    for any name the file has aliased to the lax module."""
    if isinstance(node, ast.Name):
        return node.id in ("lax", "plax") or node.id in aliases
    if isinstance(node, ast.Attribute):
        return node.attr == "lax"
    return False


def _module_aliases(tree: ast.AST, leaf: str,
                    seeds: tuple = ()) -> frozenset:
    """Names the file binds to a module whose dotted path ends in
    ``leaf`` — ``import jax.lax as jl`` / ``from jax import lax as L``
    / ``mylax = jax.lax`` respellings.  ONE walker shared by the
    raw-collective (``lax``) and raw-timing (``time``) rules, so an
    alias-tracking fix cannot land in one and silently miss the
    other.  ``seeds`` are extra bare names already known to denote
    the module (re-assigning them aliases it too)."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname and (
                    a.name == leaf or a.name.endswith("." + leaf)
                ):
                    out.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == leaf and a.asname:
                    out.add(a.asname)
        elif isinstance(node, ast.Assign):
            v = node.value
            if (isinstance(v, ast.Attribute) and v.attr == leaf) or (
                isinstance(v, ast.Name) and (
                    v.id == leaf or v.id in seeds
                )
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return frozenset(out)


def _lax_aliases(tree: ast.AST) -> frozenset:
    """Names the file binds to the lax module — the satellite gap:
    ``import jax.lax as jl`` / ``from jax import lax as L`` /
    ``mylax = jax.lax`` all put raw collectives one attribute access
    away without the ``lax`` spelling the base check keys on."""
    return _module_aliases(tree, "lax", seeds=("plax",))


def _lint_raw_collectives(tree: ast.AST, lines, rel: str) -> List[Violation]:
    out = []
    aliases = _lax_aliases(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            if (node.func.attr in COLLECTIVE_CALLS
                    and _is_lax_base(node.func.value, aliases)):
                if not _allowed(lines, node.lineno, "raw-collective"):
                    out.append(Violation(
                        rel, node.lineno, "raw-collective",
                        f"raw lax.{node.func.attr} outside the sanctioned "
                        "communication modules; use functions.collectives"
                        " / functions.point_to_point or the communicator "
                        "API",
                    ))
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.endswith("lax"):
                bad = [a.name for a in node.names
                       if a.name in COLLECTIVE_CALLS]
                if bad and not _allowed(lines, node.lineno,
                                        "raw-collective"):
                    out.append(Violation(
                        rel, node.lineno, "raw-collective",
                        f"importing {', '.join(bad)} from jax.lax "
                        "smuggles raw collectives past the lint; call "
                        "through functions.collectives",
                    ))
    return out


def _lint_raw_timing(tree: ast.AST, lines, rel: str) -> List[Violation]:
    out = []
    aliases = _module_aliases(tree, "time")
    # names from-imported out of the time module (perf_counter smuggling)
    smuggled = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name in TIMING_CALLS:
                    smuggled.add(a.asname or a.name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        hit = None
        if isinstance(node.func, ast.Attribute):
            base = node.func.value
            if node.func.attr in TIMING_CALLS and isinstance(
                base, ast.Name
            ) and (base.id == "time" or base.id in aliases):
                hit = f"time.{node.func.attr}"
        elif isinstance(node.func, ast.Name) and node.func.id in smuggled:
            hit = node.func.id
        if hit and not _allowed(lines, node.lineno, "raw-timing"):
            out.append(Violation(
                rel, node.lineno, "raw-timing",
                f"raw {hit}() timing outside observability//"
                "utils/benchmarking.py; record through "
                "observability.span / the timeline (time.monotonic is "
                "fine for plain interval arithmetic)",
            ))
    return out


_EMIT_FUNCS = {"dumps", "print", "write"}


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)


def _scope_body_walk(scope: ast.AST):
    """Walk a scope's body WITHOUT descending into nested function
    definitions — each nested function is its own scope, and pooling
    their names would let function A's enriched ``rec`` exempt function
    B's unrelated literal of the same name."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, _SCOPE_NODES[:2]):
            stack.extend(ast.iter_child_nodes(n))


def _dynamic_row_dicts(tree: ast.AST) -> set:
    """Dict literals whose protocol fields may arrive dynamically: args
    of ``.update()`` calls, and ``x = {...}`` literals whose name is
    later handed to a non-emission helper (``_copy_spread(rec, ...)``
    and friends enrich rows in place; ``json.dumps``/``print`` only
    emit, so they don't exempt).  Name tracking is per actual scope."""
    skip: set = set()
    scopes = [n for n in ast.walk(tree) if isinstance(n, _SCOPE_NODES)]
    for scope in scopes:
        assigned: dict = {}   # name -> [dict nodes]
        enriched: set = set()  # names passed to a non-emission call
        for n in _scope_body_walk(scope):
            if isinstance(n, ast.Call):
                fname = None
                if isinstance(n.func, ast.Attribute):
                    fname = n.func.attr
                    if fname == "update":
                        skip.update(
                            a for a in n.args if isinstance(a, ast.Dict)
                        )
                elif isinstance(n.func, ast.Name):
                    fname = n.func.id
                if fname and fname not in _EMIT_FUNCS:
                    for a in list(n.args) + [kw.value for kw in n.keywords]:
                        if isinstance(a, ast.Name):
                            enriched.add(a.id)
            elif isinstance(n, ast.Assign) and isinstance(
                n.value, ast.Dict
            ):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        assigned.setdefault(t.id, []).append(n.value)
        for name in enriched:
            skip.update(assigned.get(name, []))
    return skip


def _lint_untimed_rows(tree: ast.AST, lines, rel: str) -> List[Violation]:
    out = []
    dynamic = _dynamic_row_dicts(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict) or node in dynamic:
            continue
        if any(k is None for k in node.keys):
            continue  # ** expansion: protocol fields may arrive there
        keys = [k.value for k in node.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)]
        timed = [k for k in keys if TIMING_KEY_RE.search(k)]
        if not timed or "n_measurements" in keys:
            continue
        if _allowed(lines, node.lineno, "untimed-row"):
            continue
        out.append(Violation(
            rel, node.lineno, "untimed-row",
            f"timed bench row (key {timed[0]!r}) lacks the "
            "'n_measurements' min-of-N disclosure "
            "(add it, with 'spread_max_over_min' where >= 2 positive "
            "samples exist)",
        ))
    return out


def _lint_spmd_hash(tree: ast.AST, lines, rel: str) -> List[Violation]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Name
        ) and node.func.id == "hash":
            if not _allowed(lines, node.lineno, "spmd-hash"):
                out.append(Violation(
                    rel, node.lineno, "spmd-hash",
                    "builtin hash() is salted per process "
                    "(PYTHONHASHSEED); in a decision module use a "
                    "hashlib digest for anything that crosses a rank "
                    "boundary",
                ))
    return out


def _parent_map(tree: ast.AST) -> dict:
    parents: dict = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _scan_hit(node: ast.expr, scan_mods: frozenset,
              smuggled: frozenset):
    """``"os.listdir"`` when ``node`` is a raw scan call, else None."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in SCAN_CALLS:
        base = f.value
        if isinstance(base, ast.Name) and (
            base.id in ("os", "glob") or base.id in scan_mods
        ):
            return f"{base.id}.{f.attr}"
        # pathlib: p.glob / p.iterdir have no stable base name; keep
        # the rule to os/glob where the repo's scans live
    if isinstance(f, ast.Name) and f.id in smuggled:
        return f.id
    return None


def _set_hit(node: ast.expr):
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "a set"
    if isinstance(node, ast.Call) and isinstance(
        node.func, ast.Name
    ) and node.func.id in ("set", "frozenset"):
        return f"{node.func.id}(...)"
    return None


def _lint_spmd_unsorted_scan(tree: ast.AST, lines,
                             rel: str) -> List[Violation]:
    out = []
    parents = _parent_map(tree)
    scan_mods = _module_aliases(tree, "glob") | _module_aliases(
        tree, "os")
    smuggled = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
            "os", "glob"
        ):
            for a in node.names:
                if a.name in SCAN_CALLS:
                    smuggled.add(a.asname or a.name)
    smuggled = frozenset(smuggled)

    def flag(lineno, what):
        if not _allowed(lines, lineno, "spmd-unsorted-scan"):
            out.append(Violation(
                rel, lineno, "spmd-unsorted-scan",
                f"iterating {what} yields filesystem/hash order, "
                "which differs across hosts; wrap in sorted(...) "
                "before any cross-rank decision depends on it",
            ))

    for scope in (n for n in ast.walk(tree)
                  if isinstance(n, _SCOPE_NODES)):
        # names assigned a raw scan result inside this scope
        tainted = set()
        for n in _scope_body_walk(scope):
            if isinstance(n, ast.Assign) and _scan_hit(
                n.value, scan_mods, smuggled
            ):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)
        for n in _scope_body_walk(scope):
            if isinstance(n, ast.For):
                iters = [(n.iter, n.iter.lineno, None)]
            elif isinstance(n, (ast.ListComp, ast.SetComp,
                                ast.DictComp, ast.GeneratorExp)):
                iters = [(g.iter, g.iter.lineno, n)
                         for g in n.generators]
            else:
                continue
            for it, lineno, comp in iters:
                hit = _scan_hit(it, scan_mods, smuggled)
                if hit is None and isinstance(it, ast.Name) \
                        and it.id in tainted:
                    hit = f"{it.id} (a raw scan result)"
                if hit is None:
                    hit = _set_hit(it)
                if hit is None:
                    continue
                # a comprehension handed straight to an
                # order-insensitive reducer is fine
                if comp is not None:
                    p = parents.get(comp)
                    if isinstance(p, ast.Call) and isinstance(
                        p.func, ast.Name
                    ) and p.func.id in ORDER_INSENSITIVE:
                        continue
                flag(lineno, hit)
    return out


def _lint_spmd_random(tree: ast.AST, lines, rel: str) -> List[Violation]:
    out = []
    aliases = set(_module_aliases(tree, "random"))
    # names bound to jax.random are fine — jax PRNG draws take an
    # explicit key, which is exactly the sanctioned discipline
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname and a.name == "jax.random":
                    aliases.discard(a.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "random":
                        aliases.discard(a.asname or a.name)
        elif isinstance(node, ast.Assign):
            v = node.value
            if isinstance(v, ast.Attribute) and v.attr == "random" \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id == "jax":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        aliases.discard(t.id)
    smuggled = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                node.module.split(".")[-1] == "random" and \
                not node.module.startswith("jax"):
            for a in node.names:
                if a.name in RANDOM_DRAWS:
                    smuggled.add(a.asname or a.name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        hit = None
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in RANDOM_DRAWS:
            base = f.value
            if isinstance(base, ast.Name) and (
                base.id == "random" or base.id in aliases
            ):
                hit = f"{base.id}.{f.attr}"
            elif isinstance(base, ast.Attribute) and \
                    base.attr == "random" and isinstance(
                        base.value, ast.Name
                    ) and base.value.id != "jax":
                hit = f"{base.value.id}.random.{f.attr}"
        elif isinstance(f, ast.Name) and f.id in smuggled:
            hit = f.id
        if hit and not _allowed(lines, node.lineno, "spmd-random"):
            out.append(Violation(
                rel, node.lineno, "spmd-random",
                f"{hit}() draws from per-process global RNG state; "
                "in a decision module use jax.random with an agreed "
                "key or a seeded RandomState/default_rng instance",
            ))
    return out


def _is_bench_file(rel: str) -> bool:
    parts = rel.split("/")
    return "benchmarks" in parts or parts[-1].startswith("bench")


def lint_file(path: str, repo_root: str,
              host_protocol: bool = False) -> List[Violation]:
    rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
    try:
        with open(path, encoding="utf-8") as f:
            src = f.read()
    except (OSError, UnicodeDecodeError):
        return []
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        return [Violation(rel, e.lineno or 0, "syntax",
                          f"file does not parse: {e.msg}")]
    lines = src.splitlines()
    out: List[Violation] = []
    if not any(rel.startswith(p) for p in SANCTIONED):
        out += _lint_raw_collectives(tree, lines, rel)
    if _is_bench_file(rel):
        out += _lint_untimed_rows(tree, lines, rel)
    if rel.startswith("chainermn_tpu/") and not any(
        rel.startswith(p) for p in TIMING_SANCTIONED
    ):
        out += _lint_raw_timing(tree, lines, rel)
    if host_protocol and any(
        rel.startswith(p) for p in DECISION_MODULES
    ) and not any(rel.startswith(p) for p in SPMD_ALLOWLIST):
        out += _lint_spmd_hash(tree, lines, rel)
        out += _lint_spmd_unsorted_scan(tree, lines, rel)
        out += _lint_spmd_random(tree, lines, rel)
    return sorted(out, key=lambda v: (v.path, v.line))


def _iter_py_files(root: str):
    if os.path.isfile(root):
        if root.endswith(".py"):
            yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def repo_root() -> str:
    """The checkout containing this package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def default_targets(root: Optional[str] = None) -> List[str]:
    """What the repo gate lints: the package, the benchmarks, the
    examples, and bench.py.  Tests are deliberately excluded — they
    construct raw collectives on purpose to exercise the analyzer."""
    root = root or repo_root()
    out = []
    for name in ("chainermn_tpu", "benchmarks", "examples", "bench.py"):
        p = os.path.join(root, name)
        if os.path.exists(p):
            out.append(p)
    return out


def run_lint(paths: Optional[Sequence[str]] = None,
             root: Optional[str] = None,
             host_protocol: bool = False) -> List[Violation]:
    root = root or repo_root()
    targets = list(paths) if paths else default_targets(root)
    out: List[Violation] = []
    for t in targets:
        for f in _iter_py_files(t):
            out += lint_file(f, root, host_protocol=host_protocol)
    if host_protocol:
        # lazy: protolint imports this module's helpers
        from . import protolint
        out += protolint.catalog_violations(paths or None, root)
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    host_protocol = "--host-protocol" in argv
    argv = [a for a in argv if a != "--host-protocol"]
    violations = run_lint(argv or None, host_protocol=host_protocol)
    for v in violations:
        print(v)
    if violations:
        print(f"mnlint: {len(violations)} violation(s)", file=sys.stderr)
        return 1
    print("mnlint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""HLO-text collective census — the analyzer's cross-check.

The jaxpr walker (:mod:`.trace`) sees the program *before* XLA; this
module counts collective ops in the *lowered* text (StableHLO or HLO),
so the two censuses verify each other: a walker bug (a missed sub-jaxpr
param) under-counts the trace, a lowering surprise (GSPMD inserting a
reduce behind our back) over-counts the HLO.  ``TestHLOCollectiveCensus``
pins both sides against each other on the ResNet-50 and transformer
train steps.

Counting caveats, so the cross-check is honest about what it can see:

* one multi-operand ``psum`` eqn lowers to ONE variadic ``all_reduce``
  op — both sides count 1 (the walker records one eqn);
* a collective inside ``scan`` appears once in the while-loop body on
  both sides;
* the GSPMD path (``use_shard_map=False``) materializes collectives the
  jaxpr never contained — the cross-check is only meaningful for
  explicitly-partitioned (shard_map) programs, which is what every
  communicator tier builds.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Mapping, Optional

from .trace import CollectiveTrace

# op class -> (stablehlo spelling, classic-HLO spelling)
_PATTERNS = {
    "all_reduce": (r"stablehlo\.all_reduce", r"\ball-reduce(?:-start)?\("),
    "all_gather": (r"stablehlo\.all_gather", r"\ball-gather(?:-start)?\("),
    "reduce_scatter": (
        r"stablehlo\.reduce_scatter",
        r"\breduce-scatter(?:-start)?\(",
    ),
    "collective_permute": (
        r"stablehlo\.collective_permute",
        r"\bcollective-permute(?:-start)?\(",
    ),
    "all_to_all": (r"stablehlo\.all_to_all", r"\ball-to-all(?:-start)?\("),
}

# metadata={op_name="..." source_file="..." source_line=N} on classic-HLO
# ops: XLA stamps every op — including the collectives the SPMD
# partitioner inserts — with the jaxpr equation it came from, which is
# exactly the citation the implicit-collective attribution needs.
_METADATA_RE = re.compile(
    r'metadata=\{[^}]*?op_name="(?P<op>[^"]*)"'
    r'(?:[^}]*?source_file="(?P<file>[^"]*)")?'
    r"(?:[^}]*?source_line=(?P<line>\d+))?"
)


@dataclass(frozen=True)
class HloCollectiveOp:
    """One collective op occurrence in lowered/compiled program text."""

    cls: str                      # HLO op class (all_reduce, ...)
    line_no: int                  # 1-based line in the text
    op_name: Optional[str] = None  # metadata op_name (the jaxpr eqn)
    source: Optional[str] = None   # "file:line" of the issuing eqn

    def citation(self) -> str:
        """Human-readable provenance for findings/errors."""
        parts = [self.cls, f"hlo line {self.line_no}"]
        if self.op_name:
            parts.append(f"eqn {self.op_name!r}")
        if self.source:
            parts.append(f"at {self.source}")
        return " ".join(parts)


def hlo_collective_ops(text: str) -> List[HloCollectiveOp]:
    """Every collective op in lowered (StableHLO) or compiled (classic
    HLO) text, in textual order, each carrying the XLA op metadata when
    the dialect records it (classic HLO does; StableHLO's pretty form
    drops locations).  ``-done`` halves of async pairs are not counted
    (the ``-start`` op is the one occurrence)."""
    dialect = 0 if "stablehlo" in text else 1
    ops: List[HloCollectiveOp] = []
    for i, line in enumerate(text.splitlines(), start=1):
        for cls, pats in _PATTERNS.items():
            if not re.search(pats[dialect], line):
                continue
            op_name = source = None
            m = _METADATA_RE.search(line)
            if m:
                op_name = m.group("op") or None
                if m.group("file") and m.group("line"):
                    source = f"{m.group('file')}:{m.group('line')}"
            ops.append(HloCollectiveOp(
                cls=cls, line_no=i, op_name=op_name, source=source
            ))
    return ops


def hlo_census(text: str) -> dict:
    """``{op_class: count}`` over lowered program text (zero counts
    omitted).  Accepts StableHLO (``lowered.as_text()``) and classic
    HLO (``compiled.as_text()``) spellings."""
    dialect = 0 if "stablehlo" in text else 1
    out = {}
    for cls, pats in _PATTERNS.items():
        n = len(re.findall(pats[dialect], text))
        if n:
            out[cls] = n
    return out


def lowered_census(jitted, *args, **kwargs) -> dict:
    """Census of ``jitted.lower(*args).as_text()`` (compiles nothing)."""
    return hlo_census(jitted.lower(*args, **kwargs).as_text())


def assert_census_agreement(trace: CollectiveTrace, hlo_text: str,
                            classes=("all_reduce",)) -> Mapping[str, int]:
    """Assert the walker census equals the HLO-text census for the
    given op classes; returns the agreed counts.  Default compares only
    ``all_reduce`` — the class whose count is the wire-format contract —
    because XLA may legally rewrite between the gather-ish classes
    (all_gather <-> all_to_all decompositions on some backends)."""
    mine = trace.census()
    theirs = hlo_census(hlo_text)
    agreed = {}
    for cls in classes:
        a, b = mine.get(cls, 0), theirs.get(cls, 0)
        assert a == b, (
            f"census disagreement on {cls}: jaxpr walker counts {a}, "
            f"HLO text counts {b} (walker={mine}, hlo={theirs})"
        )
        agreed[cls] = a
    return agreed

"""HLO-text collective census — the analyzer's cross-check.

The jaxpr walker (:mod:`.trace`) sees the program *before* XLA; this
module counts collective ops in the *lowered* text (StableHLO or HLO),
so the two censuses verify each other: a walker bug (a missed sub-jaxpr
param) under-counts the trace, a lowering surprise (GSPMD inserting a
reduce behind our back) over-counts the HLO.  ``TestHLOCollectiveCensus``
pins both sides against each other on the ResNet-50 and transformer
train steps.

Counting caveats, so the cross-check is honest about what it can see:

* one multi-operand ``psum`` eqn lowers to ONE variadic ``all_reduce``
  op — both sides count 1 (the walker records one eqn);
* a collective inside ``scan`` appears once in the while-loop body on
  both sides;
* the GSPMD path (``use_shard_map=False``) materializes collectives the
  jaxpr never contained — the cross-check is only meaningful for
  explicitly-partitioned (shard_map) programs, which is what every
  communicator tier builds.
"""

from __future__ import annotations

import re
from typing import Mapping

from .trace import CollectiveTrace

# op class -> (stablehlo spelling, classic-HLO spelling)
_PATTERNS = {
    "all_reduce": (r"stablehlo\.all_reduce", r"\ball-reduce(?:-start)?\("),
    "all_gather": (r"stablehlo\.all_gather", r"\ball-gather(?:-start)?\("),
    "reduce_scatter": (r"stablehlo\.reduce_scatter", r"\breduce-scatter\("),
    "collective_permute": (
        r"stablehlo\.collective_permute",
        r"\bcollective-permute(?:-start)?\(",
    ),
    "all_to_all": (r"stablehlo\.all_to_all", r"\ball-to-all\("),
}


def hlo_census(text: str) -> dict:
    """``{op_class: count}`` over lowered program text (zero counts
    omitted).  Accepts StableHLO (``lowered.as_text()``) and classic
    HLO (``compiled.as_text()``) spellings."""
    dialect = 0 if "stablehlo" in text else 1
    out = {}
    for cls, pats in _PATTERNS.items():
        n = len(re.findall(pats[dialect], text))
        if n:
            out[cls] = n
    return out


def lowered_census(jitted, *args, **kwargs) -> dict:
    """Census of ``jitted.lower(*args).as_text()`` (compiles nothing)."""
    return hlo_census(jitted.lower(*args, **kwargs).as_text())


def assert_census_agreement(trace: CollectiveTrace, hlo_text: str,
                            classes=("all_reduce",)) -> Mapping[str, int]:
    """Assert the walker census equals the HLO-text census for the
    given op classes; returns the agreed counts.  Default compares only
    ``all_reduce`` — the class whose count is the wire-format contract —
    because XLA may legally rewrite between the gather-ish classes
    (all_gather <-> all_to_all decompositions on some backends)."""
    mine = trace.census()
    theirs = hlo_census(hlo_text)
    agreed = {}
    for cls in classes:
        a, b = mine.get(cls, 0), theirs.get(cls, 0)
        assert a == b, (
            f"census disagreement on {cls}: jaxpr walker counts {a}, "
            f"HLO text counts {b} (walker={mine}, hlo={theirs})"
        )
        agreed[cls] = a
    return agreed

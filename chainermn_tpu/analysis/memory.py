"""Live-range per-rank HBM estimation from the jaxpr.

Peak device memory is the other silent contract next to collective
count: a refactor that extends an activation's live range (or defeats
remat) costs nothing at trace time and an OOM at scale.  This module
bounds it statically: :func:`estimate_hbm` walks a program's jaxpr with
a classic live-range analysis — a buffer is born at the equation that
defines it and dies after its last use — and reports the peak live
bytes plus the equations where the peak occurs.

Per-rank by construction: the analysis descends into the outermost
``shard_map`` region, where every aval is already the *per-shard* shape
(replicated params full-size, batch shards ``1/n``-size, ZeRO state
blocks ``1/n``-size via their ``state_partition_spec``) — so the walk
measures exactly what one rank holds, with no division heuristics.

Remat-aware for free: ``jax.checkpoint`` changes the *jaxpr* (residuals
are not saved; recompute equations appear in the backward), so the same
live-range walk sees the smaller footprint without special-casing.

Estimator assumptions (documented in docs/static_analysis.md):

* no buffer donation/aliasing — arguments stay resident for the whole
  program and outputs are fresh buffers (matches ``donate=False``
  steps; donating steps peak lower than the estimate);
* no XLA fusion — fused producers never materialize their
  intermediates, so the estimate is an upper bound on the scheduler's
  actual peak (cross-checked against XLA's own
  ``compiled.memory_analysis()`` within a pinned tolerance in tier-1);
* sub-jaxprs (``scan``/``cond``/``while``/``pjit``) contribute their
  own internal peak on top of the live set at their call site — serial
  execution, one body at a time.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import numpy as np


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "dtype"):
        return 0
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n * np.dtype(aval.dtype).itemsize


def _mib(b: int) -> float:
    return round(b / (1024 * 1024), 2)


@dataclass(frozen=True)
class MemoryEstimate:
    """Per-rank HBM estimate of one program."""

    label: str
    inputs_bytes: int       # arguments, resident for the whole program
    outputs_bytes: int      # program results (fresh buffers)
    peak_bytes: int         # live-range peak (inputs + transients)
    n_eqns: int
    # the equation where the peak occurs:
    # (primitive, source, live bytes at that equation)
    top_sites: Tuple[Tuple[str, Optional[str], int], ...] = ()
    # train-step breakdown (0 when not derived from a train step)
    params_bytes: int = 0
    opt_state_bytes: int = 0
    batch_bytes: int = 0

    @property
    def transient_bytes(self) -> int:
        """Peak minus resident arguments — activations, gradients, and
        update buffers at the worst point of the schedule."""
        return max(self.peak_bytes - self.inputs_bytes, 0)

    def __str__(self) -> str:
        parts = [
            f"{self.label}: peak {_mib(self.peak_bytes)} MiB "
            f"(inputs {_mib(self.inputs_bytes)} + transient "
            f"{_mib(self.transient_bytes)})"
        ]
        if self.params_bytes:
            parts.append(
                f"params {_mib(self.params_bytes)} / opt "
                f"{_mib(self.opt_state_bytes)} / batch "
                f"{_mib(self.batch_bytes)} MiB"
            )
        return "; ".join(parts)


def _sub_jaxprs(eqn):
    for val in eqn.params.values():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for sub in vals:
            if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                yield sub


def _inner_peak(jaxpr_like) -> int:
    """Peak bytes of a sub-jaxpr's INTERMEDIATES (its invars/constvars
    are the caller's operands, already counted in the caller's live
    set)."""
    inner = getattr(jaxpr_like, "jaxpr", jaxpr_like)
    peak, _sites = _live_range(inner, count_inputs=False)
    return peak


def _live_range(jaxpr, count_inputs: bool = True):
    """(peak_bytes, sites): linear live-range scan over one jaxpr.

    ``count_inputs``: whether invars/constvars are resident (True at the
    top level; False for sub-jaxprs, whose operands belong to the
    caller's live set).  Resident inputs are PINNED for the whole
    program — the documented no-donation assumption: an argument
    consumed early still occupies HBM at the later activation peak."""
    live: dict = {}
    pinned: set = set()
    if count_inputs:
        for v in list(jaxpr.invars) + list(jaxpr.constvars):
            live[id(v)] = _aval_bytes(v)
            pinned.add(id(v))

    last_use: dict = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not hasattr(v, "val"):
                last_use[id(v)] = i
    for v in jaxpr.outvars:
        if not hasattr(v, "val"):
            last_use[id(v)] = len(jaxpr.eqns)  # results outlive the body

    peak = sum(live.values())
    peak_site = None
    for i, eqn in enumerate(jaxpr.eqns):
        inner = 0
        for sub in _sub_jaxprs(eqn):
            inner = max(inner, _inner_peak(sub))
        for ov in eqn.outvars:
            if type(ov).__name__ == "DropVar":
                continue
            live[id(ov)] = _aval_bytes(ov)
        here = sum(live.values()) + inner
        if here > peak:
            peak = here
            peak_site = (eqn.primitive.name, _src(eqn), here)
        for iv in eqn.invars:
            if (not hasattr(iv, "val") and id(iv) not in pinned
                    and last_use.get(id(iv)) == i):
                live.pop(id(iv), None)
    return peak, ([peak_site] if peak_site else [])


def _src(eqn) -> Optional[str]:
    from .trace import _source_of

    return _source_of(eqn)


def _find_shard_map_body(jaxpr_like, depth: int = 0):
    """The outermost shard_map body (per-shard avals), or None."""
    inner = getattr(jaxpr_like, "jaxpr", jaxpr_like)
    if depth > 4:
        return None
    for eqn in inner.eqns:
        if eqn.primitive.name == "shard_map":
            return eqn.params.get("jaxpr")
        for sub in _sub_jaxprs(eqn):
            found = _find_shard_map_body(sub, depth + 1)
            if found is not None:
                return found
    return None


def estimate_jaxpr_hbm(jaxpr_like, label: str = "program",
                       per_rank: bool = True) -> MemoryEstimate:
    """Estimate HBM for an already-made (closed) jaxpr.

    ``per_rank=True`` descends to the outermost ``shard_map`` body —
    where every aval is the per-shard shape — and analyzes that; when
    the program has no shard_map (plain jit / GSPMD), the top-level
    jaxpr is analyzed as-is (global shapes; divide by the mesh yourself
    if the partitioner shards it).
    """
    target = None
    if per_rank:
        target = _find_shard_map_body(jaxpr_like)
    if target is None:
        target = getattr(jaxpr_like, "jaxpr", jaxpr_like)
    else:
        target = getattr(target, "jaxpr", target)

    inputs = sum(
        _aval_bytes(v)
        for v in list(target.invars) + list(target.constvars)
    )
    outputs = sum(
        _aval_bytes(v) for v in target.outvars if not hasattr(v, "val")
    )
    peak, sites = _live_range(target, count_inputs=True)
    return MemoryEstimate(
        label=label,
        inputs_bytes=inputs,
        outputs_bytes=outputs,
        peak_bytes=peak,
        n_eqns=len(target.eqns),
        top_sites=tuple(sites),
    )


def estimate_hbm(fn, *args, label: Optional[str] = None,
                 per_rank: bool = True, **kwargs) -> MemoryEstimate:
    """Trace ``fn(*args)`` (nothing compiles or executes) and estimate
    its per-rank peak HBM."""
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    return estimate_jaxpr_hbm(
        jaxpr,
        label=label or getattr(fn, "__name__", "program"),
        per_rank=per_rank,
    )


def train_step_memory(step, params, opt_state, batch,
                      label: str = "train_step") -> MemoryEstimate:
    """HBM estimate of a built train step with the params / opt-state /
    batch breakdown attached.

    The breakdown reads the per-rank sizes straight off the shard_map
    body's invars (which arrive in ``(params, opt_state, batch)``
    flatten order), so ZeRO's ``1/n`` state shards and sharded batches
    are counted at their true per-rank size — the sharding annotations
    (``state_partition_spec``, ``batch_sharding``) are what the
    estimator is seeing.
    """
    if hasattr(step, "is_placed") and not step.is_placed(batch):
        batch = step.place_batch(batch)
    fn = step.get_jitted(params, opt_state) if hasattr(
        step, "get_jitted"
    ) else step
    jaxpr = jax.make_jaxpr(fn)(params, opt_state, batch)
    est = estimate_jaxpr_hbm(jaxpr, label=label, per_rank=True)

    body = _find_shard_map_body(jaxpr)
    n_p = len(jax.tree_util.tree_leaves(params))
    n_o = len(jax.tree_util.tree_leaves(opt_state))
    n_b = len(jax.tree_util.tree_leaves(batch))
    p_bytes = o_bytes = b_bytes = 0
    if body is not None:
        inner = getattr(body, "jaxpr", body)
        sizes = [_aval_bytes(v) for v in inner.invars]
        if len(sizes) == n_p + n_o + n_b:
            p_bytes = sum(sizes[:n_p])
            o_bytes = sum(sizes[n_p:n_p + n_o])
            b_bytes = sum(sizes[n_p + n_o:])
    return MemoryEstimate(
        label=est.label,
        inputs_bytes=est.inputs_bytes,
        outputs_bytes=est.outputs_bytes,
        peak_bytes=est.peak_bytes,
        n_eqns=est.n_eqns,
        top_sites=est.top_sites,
        params_bytes=p_bytes,
        opt_state_bytes=o_bytes,
        batch_bytes=b_bytes,
    )

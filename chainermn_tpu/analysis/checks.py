"""Checks over a :class:`~chainermn_tpu.analysis.trace.CollectiveTrace`.

The check catalog (docs/static_analysis.md):

* **divergence guard** — :func:`trace_agreement`: exchange the canonical
  trace hash across processes (like ``comm_wire.plan_agreement``) so
  rank-divergent collective sequences raise
  :class:`~chainermn_tpu.resilience.errors.CollectiveTraceMismatchError`
  loudly on every rank *before* the first collective deadlocks.
* **deadlock lint** — :func:`check_deadlocks`: collectives inside
  data-dependent ``cond`` branches.  Arms with *different* collective
  sequences are errors (a rank-dependent predicate then deadlocks);
  arms with identical sequences are surfaced as warnings (aligned
  today, one edit from divergent).
* **axis audit** — :func:`check_axes`: every collective's axis names
  must exist in the active mesh/topology.
* **wire audit** — :func:`check_wire`: dtype-narrowing casts feeding a
  reduction outside the sanctioned ``comm_wire`` codecs (the compressed
  wire formats carry scale/error-feedback machinery; a bare
  ``psum(g.astype(bf16))`` anywhere else is an unaudited precision
  loss).
* **budget pins** — :func:`assert_within_budget`: per-program
  collective-count ceilings (``analysis.budgets``) enforced from the
  trace census instead of string-grepping HLO.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Mapping, Optional, Sequence

from .trace import CollectiveTrace


@dataclass(frozen=True)
class Finding:
    """One check result.  ``severity``: "error" (will deadlock / is
    wrong) or "warning" (legal but one edit from wrong)."""

    check: str
    severity: str
    message: str
    source: Optional[str] = None

    def __str__(self) -> str:
        where = f" [{self.source}]" if self.source else ""
        return f"{self.check}/{self.severity}: {self.message}{where}"


class CollectiveBudgetError(AssertionError):
    """A traced program exceeds its pinned collective budget."""


# ----------------------------------------------------------------------
# deadlock lint
# ----------------------------------------------------------------------
def check_deadlocks(trace: CollectiveTrace) -> list:
    findings = []
    for rep in trace.cond_reports:
        if not rep.has_collectives:
            continue
        counts = [len(s) for s in rep.branch_signatures]
        if rep.diverges:
            findings.append(Finding(
                check="deadlock",
                severity="error",
                message=(
                    f"{rep.cond_id}: branches trace different collective "
                    f"sequences ({counts} collectives per branch) — a "
                    "rank-dependent predicate deadlocks here"
                ),
                source=rep.source,
            ))
        else:
            findings.append(Finding(
                check="deadlock",
                severity="warning",
                message=(
                    f"{rep.cond_id}: {counts[0]} collective(s) inside a "
                    "data-dependent cond (branches currently agree; keep "
                    "them in lockstep or hoist the collective out)"
                ),
                source=rep.source,
            ))
    return findings


# ----------------------------------------------------------------------
# axis audit
# ----------------------------------------------------------------------
def check_axes(trace: CollectiveTrace, axis_names: Iterable[str]) -> list:
    """``axis_names``: the active mesh/topology axes — pass
    ``comm.axis_names`` or ``mesh.axis_names``."""
    if isinstance(axis_names, str):  # a bare axis name, not its chars
        axis_names = (axis_names,)
    known = set(str(a) for a in axis_names)
    findings = []
    for r in trace.records:
        bad = [a for a in r.axes if a not in known]
        if bad:
            findings.append(Finding(
                check="axis",
                severity="error",
                message=(
                    f"{r.primitive} over unknown axis "
                    f"{'/'.join(bad)} (mesh has {sorted(known)})"
                ),
                source=r.source,
            ))
    return findings


# ----------------------------------------------------------------------
# wire audit
# ----------------------------------------------------------------------
def check_wire(trace: CollectiveTrace,
               exempt_paths: Sequence[str] = ("comm_wire",)) -> list:
    """Flag narrowing casts feeding reductions whose cast site is NOT
    inside one of ``exempt_paths`` (substring match on the cast's source
    file).  The default exempts only the ``comm_wire`` codecs — the one
    audited place where narrowed wires carry scale/error-feedback."""
    findings = []
    for nc in trace.narrowing_casts:
        if nc.cast_source is None:
            # provenance unavailable (source_info API drift): cannot
            # attribute the cast, so don't accuse — the audit
            # under-reports rather than flagging the sanctioned codecs
            continue
        if any(p in nc.cast_source for p in exempt_paths):
            continue
        findings.append(Finding(
            check="wire",
            severity="warning",
            message=(
                f"{nc.src_dtype} -> {nc.dst_dtype} cast feeds "
                f"{nc.collective.primitive} over "
                f"{'/'.join(nc.collective.axes) or '?'} outside "
                "comm_wire codecs (unaudited precision loss on the "
                "wire; route through a wire codec)"
            ),
            source=nc.cast_source,
        ))
    return findings


# ----------------------------------------------------------------------
# budget pins
# ----------------------------------------------------------------------
def assert_within_budget(trace: CollectiveTrace,
                         budget: Mapping[str, int],
                         name: str = "") -> dict:
    """Enforce per-class collective-count ceilings on the trace census.

    ``budget``: ``{hlo_op_class: max_count}`` (see
    ``analysis.budgets.BUDGETS`` for the pinned programs).  Classes not
    named in the budget are unconstrained.  Returns the census on
    success; raises :class:`CollectiveBudgetError` listing every
    exceeded class otherwise.
    """
    census = trace.census()
    over = {
        cls: (census.get(cls, 0), ceiling)
        for cls, ceiling in budget.items()
        if census.get(cls, 0) > ceiling
    }
    if over:
        detail = ", ".join(
            f"{cls}: {got} > {ceiling}"
            for cls, (got, ceiling) in sorted(over.items())
        )
        raise CollectiveBudgetError(
            f"collective budget exceeded for {name or trace.label}: "
            f"{detail} (census={census})"
        )
    return census


# ----------------------------------------------------------------------
# divergence guard
# ----------------------------------------------------------------------
def trace_agreement(comm, trace: CollectiveTrace, *,
                    label: Optional[str] = None,
                    max_attempts: int = 4) -> str:
    """Verify every process traced the same collective sequence.

    Exchanges the canonical trace hash over the communicator's object
    store (host control plane — no device collective runs).  Like
    ``comm_wire.plan_agreement``, the exchange retries transient faults
    AND ``PayloadCorruptionError`` in lockstep (every process observes a
    torn payload, so all retry together).  Returns the agreed hash;
    raises :class:`~chainermn_tpu.resilience.errors.
    CollectiveTraceMismatchError` (non-recoverable — restarting replays
    the same divergent program) when any process disagrees.
    """
    from ..resilience.errors import (
        CollectiveTraceMismatchError,
        PayloadCorruptionError,
    )
    from ..resilience.retry import RetryPolicy, call_with_retry, is_transient

    mine = trace.trace_hash()
    site = f"analysis.trace_agreement({label or trace.label})"

    hashes = call_with_retry(
        lambda: comm.allgather_obj(mine),
        site=site,
        policy=RetryPolicy(max_attempts=max_attempts),
        retryable=lambda e: is_transient(e)
        or isinstance(e, PayloadCorruptionError),
    )
    if any(h != mine for h in hashes):
        raise CollectiveTraceMismatchError(
            f"collective trace hash mismatch across processes: {hashes} "
            f"(mine={mine[:12]}..., {len(trace)} collectives traced) — "
            "the ranks would issue divergent collective sequences and "
            "deadlock; diff the per-rank CollectiveTrace.canonical() "
            "output to find the divergent call",
            site=site,
        )
    return mine


def run_all(trace: CollectiveTrace, *, axis_names=None,
            exempt_paths: Sequence[str] = ("comm_wire",)) -> list:
    """Every local check in one call (the divergence guard needs a
    communicator and budget pins need a ceiling, so neither is here).
    """
    findings = list(check_deadlocks(trace))
    if axis_names is not None:
        findings += check_axes(trace, axis_names)
    findings += check_wire(trace, exempt_paths)
    return findings

"""Checks over a :class:`~chainermn_tpu.analysis.trace.CollectiveTrace`.

The check catalog (docs/static_analysis.md):

* **divergence guard** — :func:`trace_agreement`: exchange the canonical
  trace hash across processes (like ``comm_wire.plan_agreement``) so
  rank-divergent collective sequences raise
  :class:`~chainermn_tpu.resilience.errors.CollectiveTraceMismatchError`
  loudly on every rank *before* the first collective deadlocks.
* **deadlock lint** — :func:`check_deadlocks`: collectives inside
  data-dependent ``cond`` branches.  Arms with *different* collective
  sequences are errors (a rank-dependent predicate then deadlocks);
  arms with identical sequences are surfaced as warnings (aligned
  today, one edit from divergent).
* **axis audit** — :func:`check_axes`: every collective's axis names
  must exist in the active mesh/topology.
* **wire audit** — :func:`check_wire`: dtype-narrowing casts feeding a
  reduction outside the sanctioned ``comm_wire`` codecs (the compressed
  wire formats carry scale/error-feedback machinery; a bare
  ``psum(g.astype(bf16))`` anywhere else is an unaudited precision
  loss).
* **budget pins** — :func:`assert_within_budget`: per-program
  collective-count ceilings (``analysis.budgets``) enforced from the
  trace census instead of string-grepping HLO.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Mapping, Optional, Sequence

from .trace import CollectiveTrace


@dataclass(frozen=True)
class Finding:
    """One check result.  ``severity``: "error" (will deadlock / is
    wrong) or "warning" (legal but one edit from wrong)."""

    check: str
    severity: str
    message: str
    source: Optional[str] = None

    def __str__(self) -> str:
        where = f" [{self.source}]" if self.source else ""
        return f"{self.check}/{self.severity}: {self.message}{where}"


class CollectiveBudgetError(AssertionError):
    """A traced program exceeds its pinned collective budget."""


class ImplicitCollectiveError(AssertionError):
    """The lowered/compiled HLO carries collectives the author never
    wrote — the SPMD partitioner inserted reshards/all-gathers that
    silently eat wire bandwidth."""


# ----------------------------------------------------------------------
# deadlock lint
# ----------------------------------------------------------------------
def check_deadlocks(trace: CollectiveTrace) -> list:
    findings = list(_check_cond_deadlocks(trace))
    findings += _check_while_deadlocks(trace)
    return findings


def _check_cond_deadlocks(trace: CollectiveTrace) -> list:
    findings = []
    for rep in trace.cond_reports:
        if not rep.has_collectives:
            continue
        counts = [len(s) for s in rep.branch_signatures]
        if rep.diverges:
            findings.append(Finding(
                check="deadlock",
                severity="error",
                message=(
                    f"{rep.cond_id}: branches trace different collective "
                    f"sequences ({counts} collectives per branch) — a "
                    "rank-dependent predicate deadlocks here"
                ),
                source=rep.source,
            ))
        else:
            findings.append(Finding(
                check="deadlock",
                severity="warning",
                message=(
                    f"{rep.cond_id}: {counts[0]} collective(s) inside a "
                    "data-dependent cond (branches currently agree; keep "
                    "them in lockstep or hoist the collective out)"
                ),
                source=rep.source,
            ))
    return findings


def _check_while_deadlocks(trace: CollectiveTrace) -> list:
    """The while half of the lint (ISSUE 6 satellite; PR 4 compared
    only ``cond`` arms): a collective inside a ``while`` executes once
    per iteration, so a rank-divergent trip count issues rank-divergent
    collective sequences — the loop analogue of divergent cond arms.
    Statically safe shapes (counter-only predicates, predicates
    computed through a cross-rank reduction) warn instead of erroring,
    exactly as lockstep cond arms do."""
    findings = []
    for rep in trace.while_reports:
        if not rep.has_collectives:
            continue
        n = len(rep.cond_signatures) + len(rep.body_signatures)
        if not rep.trip_count_agreed:
            findings.append(Finding(
                check="deadlock",
                severity="error",
                message=(
                    f"{rep.while_id}: {n} collective(s) inside a "
                    "data-dependent while — the exit predicate is "
                    "neither counter-only nor cross-rank reduced, so "
                    "rank-divergent trip counts issue divergent "
                    "collective sequences and deadlock"
                ),
                source=rep.source,
            ))
        else:
            how = (
                "counter-only predicate"
                if rep.counter_only_predicate
                else "predicate agreed through a cross-rank reduction"
            )
            findings.append(Finding(
                check="deadlock",
                severity="warning",
                message=(
                    f"{rep.while_id}: {n} collective(s) inside a while "
                    f"with a {how} (trip counts currently agree; keep "
                    "the predicate rank-invariant)"
                ),
                source=rep.source,
            ))
    return findings


# ----------------------------------------------------------------------
# axis audit
# ----------------------------------------------------------------------
def check_axes(trace: CollectiveTrace, axis_names: Iterable[str]) -> list:
    """``axis_names``: the active mesh/topology axes — pass
    ``comm.axis_names`` or ``mesh.axis_names``."""
    if isinstance(axis_names, str):  # a bare axis name, not its chars
        axis_names = (axis_names,)
    known = set(str(a) for a in axis_names)
    findings = []
    for r in trace.records:
        bad = [a for a in r.axes if a not in known]
        if bad:
            findings.append(Finding(
                check="axis",
                severity="error",
                message=(
                    f"{r.primitive} over unknown axis "
                    f"{'/'.join(bad)} (mesh has {sorted(known)})"
                ),
                source=r.source,
            ))
    return findings


# ----------------------------------------------------------------------
# wire audit
# ----------------------------------------------------------------------
def check_wire(trace: CollectiveTrace,
               exempt_paths: Sequence[str] = ("comm_wire",)) -> list:
    """Flag narrowing casts feeding reductions whose cast site is NOT
    inside one of ``exempt_paths`` (substring match on the cast's source
    file).  The default exempts only the ``comm_wire`` codecs — the one
    audited place where narrowed wires carry scale/error-feedback."""
    findings = []
    for nc in trace.narrowing_casts:
        if nc.cast_source is None:
            # provenance unavailable (source_info API drift): cannot
            # attribute the cast, so don't accuse — the audit
            # under-reports rather than flagging the sanctioned codecs
            continue
        if any(p in nc.cast_source for p in exempt_paths):
            continue
        findings.append(Finding(
            check="wire",
            severity="warning",
            message=(
                f"{nc.src_dtype} -> {nc.dst_dtype} cast feeds "
                f"{nc.collective.primitive} over "
                f"{'/'.join(nc.collective.axes) or '?'} outside "
                "comm_wire codecs (unaudited precision loss on the "
                "wire; route through a wire codec)"
            ),
            source=nc.cast_source,
        ))
    return findings


# ----------------------------------------------------------------------
# budget pins
# ----------------------------------------------------------------------
def assert_within_budget(trace: CollectiveTrace,
                         budget: Mapping[str, int],
                         name: str = "") -> dict:
    """Enforce per-class collective-count ceilings on the trace census.

    ``budget``: ``{hlo_op_class: max_count}`` (see
    ``analysis.budgets.BUDGETS`` for the pinned programs).  Classes not
    named in the budget are unconstrained.  Returns the census on
    success; raises :class:`CollectiveBudgetError` listing every
    exceeded class otherwise.
    """
    census = trace.census()
    over = {
        cls: (census.get(cls, 0), ceiling)
        for cls, ceiling in budget.items()
        if census.get(cls, 0) > ceiling
    }
    if over:
        detail = ", ".join(
            f"{cls}: {got} > {ceiling}"
            for cls, (got, ceiling) in sorted(over.items())
        )
        raise CollectiveBudgetError(
            f"collective budget exceeded for {name or trace.label}: "
            f"{detail} (census={census})"
        )
    return census


# ----------------------------------------------------------------------
# implicit-collective attribution (ISSUE 6 tentpole)
# ----------------------------------------------------------------------
# XLA may legally rewrite WITHIN the gather family (all_gather <->
# all_to_all decompositions on some backends), so attribution pools
# those two classes; the reduction and permute classes must match
# exactly — they are the wire-format contract.
_ATTRIBUTION_GROUPS = (
    ("all_reduce",),
    ("reduce_scatter",),
    ("collective_permute",),
    ("all_gather", "all_to_all"),
)


def attribute_collectives(trace: CollectiveTrace, hlo_text: str,
                          flow=None) -> dict:
    """Match every collective in the lowered/compiled HLO text to an
    authored trace record.

    Returns ``{group_label: {"authored": n, "lowered": n, "implicit":
    [citation, ...]}}`` where ``implicit`` lists the surplus ops the
    partitioner inserted, each cited with the responsible equation: the
    XLA op metadata (compiled text carries ``op_name``/``source_file``
    per op) joined with the sharding-flow pass's reshard sites
    (``flow``: a :class:`~chainermn_tpu.analysis.shardflow.
    ShardFlowReport`).  Pass the *compiled* text
    (``jitted.lower(...).compile().as_text()``) — the SPMD partitioner
    runs at compile time, so the StableHLO lowering cannot contain its
    insertions.
    """
    from .hlo import hlo_collective_ops

    ops = hlo_collective_ops(hlo_text)
    census = trace.census()
    report: dict = {}
    for group in _ATTRIBUTION_GROUPS:
        label = "/".join(group)
        authored = sum(census.get(c, 0) for c in group)
        group_ops = [o for o in ops if o.cls in group]
        surplus = max(len(group_ops) - authored, 0)
        # cite the RIGHT surplus ops: an op whose source matches an
        # authored record's call site is the author's own collective —
        # prefer citing the ops no authored record issued (an inserted
        # reshard can appear textually BEFORE the authored ops, so
        # plain tail-slicing would name the wrong equation)
        authored_sources = {
            r.source for r in trace.records
            if r.cls in group and r.source
        }
        unmatched = [
            o for o in group_ops
            if not (o.source and o.source in authored_sources)
        ]
        pool = unmatched if len(unmatched) >= surplus else group_ops
        sites = (
            [s for s in flow.reshard_sites if s.cls in group]
            if flow is not None else []
        )
        implicit = []
        for i, op in enumerate(pool[len(pool) - surplus:]):
            cites = [op.citation()]
            # pair op to flow site 1:1 (both in program order) when the
            # counts line up; otherwise the pairing is ambiguous — cite
            # every candidate site once, on the first surplus op only
            if len(sites) == surplus:
                cites.append(sites[i].citation())
            elif sites and i == 0:
                cites += [s.citation() for s in sites]
            implicit.append("; ".join(cites))
        report[label] = {
            "authored": authored,
            "lowered": len(group_ops),
            "implicit": implicit,
        }
    return report


def check_implicit_collectives(trace: CollectiveTrace, hlo_text: str,
                               flow=None) -> list:
    """Findings for every partitioner-inserted collective (error — it
    ships bytes the author never audited) and for authored collectives
    the lowering dropped (warning — usually a walker/lowering mismatch
    worth a look, not a deadlock)."""
    findings = []
    for label, rep in attribute_collectives(
        trace, hlo_text, flow
    ).items():
        for citation in rep["implicit"]:
            findings.append(Finding(
                check="implicit_collectives",
                severity="error",
                message=(
                    f"{label}: {rep['lowered']} in HLO vs "
                    f"{rep['authored']} authored — partitioner-inserted "
                    f"collective: {citation}"
                ),
            ))
        if rep["lowered"] < rep["authored"]:
            findings.append(Finding(
                check="implicit_collectives",
                severity="warning",
                message=(
                    f"{label}: only {rep['lowered']} in HLO vs "
                    f"{rep['authored']} authored — the lowering "
                    "elided/rewrote authored collectives"
                ),
            ))
    return findings


def assert_attributed(trace: CollectiveTrace, hlo_text: str, *,
                      flow=None, name: str = "") -> dict:
    """Assert zero partitioner-inserted collectives; returns the
    attribution report.  Raises :class:`ImplicitCollectiveError` citing
    every responsible equation otherwise."""
    report = attribute_collectives(trace, hlo_text, flow)
    bad = [
        f"{label}: {c}"
        for label, rep in report.items()
        for c in rep["implicit"]
    ]
    if bad:
        raise ImplicitCollectiveError(
            f"unattributed collectives in {name or trace.label}: "
            + "; ".join(bad)
        )
    return report


def implicit_agreement(comm, trace: CollectiveTrace, hlo_text: str, *,
                       flow=None, label: Optional[str] = None) -> dict:
    """Cross-process form of :func:`assert_attributed`: every process
    checks its own program, then the per-rank implicit-collective
    counts are exchanged over the host control plane — if ANY rank's
    program carries a partitioner-inserted collective, EVERY rank
    raises :class:`ImplicitCollectiveError` before dispatch (a one-rank
    reshard is a divergent collective sequence: dispatching it would
    deadlock, not just waste bandwidth)."""
    from ..resilience.retry import lockstep_allgather

    report = attribute_collectives(trace, hlo_text, flow)
    mine = [
        f"{label_}: {c}"
        for label_, rep in report.items()
        for c in rep["implicit"]
    ]
    site = f"analysis.implicit_agreement({label or trace.label})"
    # same lockstep retry as trace_agreement/plan_agreement: a torn
    # payload is observed by every process, so all retry together
    everyone = lockstep_allgather(comm, mine, site=site)
    if any(everyone):
        detail = "; ".join(
            f"rank {r}: {'; '.join(v)}"
            for r, v in enumerate(everyone) if v
        )
        raise ImplicitCollectiveError(
            f"partitioner-inserted collectives detected at {site} — "
            f"{detail}"
        )
    return report


# ----------------------------------------------------------------------
# divergence guard
# ----------------------------------------------------------------------
def trace_agreement(comm, trace: CollectiveTrace, *,
                    label: Optional[str] = None,
                    max_attempts: int = 4) -> str:
    """Verify every process traced the same collective sequence.

    Exchanges the canonical trace hash over the communicator's object
    store (host control plane — no device collective runs).  Like
    ``comm_wire.plan_agreement``, the exchange retries transient faults
    AND ``PayloadCorruptionError`` in lockstep (every process observes a
    torn payload, so all retry together).  Returns the agreed hash;
    raises :class:`~chainermn_tpu.resilience.errors.
    CollectiveTraceMismatchError` (non-recoverable — restarting replays
    the same divergent program) when any process disagrees.
    """
    from ..resilience.errors import CollectiveTraceMismatchError
    from ..resilience.retry import lockstep_allgather

    mine = trace.trace_hash()
    site = f"analysis.trace_agreement({label or trace.label})"

    hashes = lockstep_allgather(comm, mine, site=site,
                                max_attempts=max_attempts)
    if any(h != mine for h in hashes):
        raise CollectiveTraceMismatchError(
            f"collective trace hash mismatch across processes: {hashes} "
            f"(mine={mine[:12]}..., {len(trace)} collectives traced) — "
            "the ranks would issue divergent collective sequences and "
            "deadlock; diff the per-rank CollectiveTrace.canonical() "
            "output to find the divergent call",
            site=site,
        )
    return mine


def protocol_agreement(comm, recorder=None, *,
                       label: Optional[str] = None,
                       max_attempts: int = 4) -> str:
    """Verify every process issued the same ordered HOST-side exchange
    sequence — the control-plane twin of :func:`trace_agreement`.

    ``recorder`` is a :class:`~chainermn_tpu.resilience.protocol.
    ProtocolRecorder` (default: the installed one); its window
    signature — the ordered ``(site|tag)`` tokens since the last agreed
    point, with by-design-asymmetric ops excluded — is hashed and
    exchanged through the lockstep retry.  Any mismatch raises
    :class:`~chainermn_tpu.resilience.errors.ProtocolDivergenceError`
    on EVERY rank (non-recoverable: replaying the same divergent host
    code re-diverges) *before* the mismatched protocol wedges a later
    exchange into a deadlock.  On agreement the recorder's cursor
    advances (``mark_agreed``), so successive calls check successive
    windows.  Returns the agreed signature hash.

    The guard's own exchange rides ``lockstep_allgather`` — a torn
    payload on the agreement itself retries on all ranks together —
    and is recorded under its ``analysis.protocol_agreement(...)``
    site AFTER the signature is taken, so it never perturbs the window
    it is checking.
    """
    from ..resilience import protocol as _proto
    from ..resilience.errors import ProtocolDivergenceError
    from ..resilience.retry import lockstep_allgather

    rec = recorder if recorder is not None else _proto.active()
    if rec is None:
        raise RuntimeError(
            "protocol_agreement: no ProtocolRecorder installed — set "
            f"{_proto.ENV_RECORD}=1 (or protocol.install(...)) before "
            "constructing the communicator"
        )
    sig = rec.window_signature()
    mine = {
        "hash": _proto.signature_hash(sig),
        "n": len(sig),
        "tail": sig[-8:],
        # full signature when small enough to name the divergent index
        "sig": sig if len(sig) <= 256 else None,
    }
    site = (f"analysis.protocol_agreement({label})" if label
            else "analysis.protocol_agreement")
    everyone = lockstep_allgather(comm, mine, site=site,
                                  max_attempts=max_attempts)
    if any(e["hash"] != mine["hash"] for e in everyone):
        per_rank = "; ".join(
            f"rank {r}: n={e['n']} hash={e['hash'][:12]} "
            f"tail={e['tail']}"
            for r, e in enumerate(everyone)
        )
        where = ""
        sigs = [e["sig"] for e in everyone]
        if all(s is not None for s in sigs):
            upto = max(len(s) for s in sigs)
            for i in range(upto):
                toks = {s[i] if i < len(s) else None for s in sigs}
                if len(toks) > 1:
                    where = (f"; first divergent exchange at index {i}: "
                             + ", ".join(
                                 f"rank {r}={s[i] if i < len(s) else None!r}"
                                 for r, s in enumerate(sigs)))
                    break
        raise ProtocolDivergenceError(
            f"host-protocol divergence at {site}: processes issued "
            f"different obj-store exchange sequences ({per_rank}"
            f"{where}) — the control plane would deadlock on the next "
            "mismatched exchange; diff the per-rank protocol jsonl "
            "(FleetReport.protocol_divergence pinpoints the token)",
            site=site,
        )
    rec.mark_agreed()
    return mine["hash"]


# ----------------------------------------------------------------------
# ordering-aware overlap check (ISSUE 8)
# ----------------------------------------------------------------------
def check_overlap(jaxpr_like, plan) -> list:
    """Ordering-aware check for the bucket-overlap program shape: every
    wire bucket psum must be *issued* at its dependency frontier —
    dispatched the moment its bucket's leaves are produced, before the
    remaining backward segments complete — rather than queued at the
    program tail the way the synchronous wire lowers.

    Unlike the census pins (which are ordering-blind by design — the
    overlap engine's contract is that the census does NOT move), this
    check reads equation *positions*, so it takes a jaxpr (e.g.
    ``step.get_jitted(p, o).scheduled_jaxpr(p, o, batch)``) and the
    wire's :class:`~chainermn_tpu.comm_wire.BucketPlan` (or a
    schedule-carrying :class:`~chainermn_tpu.comm_wire.WirePlan`, whose
    ``hier_rs_ag`` buckets are checked as ONE readiness unit headed by
    the intra reduce-scatter, with the rs→ar→ag triple's completeness
    verified alongside), and returns
    :class:`Finding`\\ s — one ``error`` per late-issued bucket psum
    (``delay`` = foreign equations between operand readiness and
    dispatch), plus an ``error`` when the program carries fewer bucket
    psums than the plan has buckets.  A multi-bucket synchronous step
    always fails; an overlap-scheduled one returns ``[]``.
    """
    from ..comm_wire.overlap import order_violations

    # ONE source of truth: comm_wire.overlap.order_violations computes
    # the contract; this spelling only wraps each violation as a
    # Finding (the assert-style spelling is assert_overlap_order).
    return [
        Finding(check="overlap", severity="error", message=msg)
        for msg in order_violations(jaxpr_like, plan)
    ]


def run_all(trace: CollectiveTrace, *, axis_names=None,
            exempt_paths: Sequence[str] = ("comm_wire",)) -> list:
    """Every local check in one call (the divergence guard needs a
    communicator and budget pins need a ceiling, so neither is here).
    """
    findings = list(check_deadlocks(trace))
    if axis_names is not None:
        findings += check_axes(trace, axis_names)
    findings += check_wire(trace, exempt_paths)
    return findings

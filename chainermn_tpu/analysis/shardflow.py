"""Sharding-flow pass: propagate PartitionSpecs through a closed jaxpr.

The jaxpr walker (:mod:`.trace`) sees every collective the *author*
wrote; the XLA SPMD partitioner can still insert all-gathers and
reshards behind our backs whenever the shardings flowing into an
equation don't line up (a sharded operand feeding a replicated-output
dot, two operands sharded along different axes, a declared output
sharding the natural result layout doesn't match).  Those inserted
collectives never appear in the jaxpr, so the trace census under-counts
the wire — silently, which is how an accidental resharding all-gather
eats bandwidth for months.

This module closes the gap statically: :func:`shardflow` seeds the
jaxpr's invars with the program's input PartitionSpecs and propagates
them equation by equation, descending — like the trace walker — into
``pjit`` calls and into ``scan``/``cond``/``while`` bodies (consts and
carries pass through, stacked scan inputs lose their leading dim,
loop-carried layouts must be iteration-stable to stay known;
``shard_map`` regions are manual — their collectives are authored and
already traced, so the flow takes their declared ``out_names`` and
moves on).  Wherever propagation
finds a layout the partitioner cannot reconcile without communication,
it records a :class:`ReshardSite` — the equation index, primitive, and
``file:line`` of the responsible call, plus the collective class the
partitioner will insert.  ``checks.check_implicit_collectives`` then
joins three artifacts:

* the authored census (trace records),
* the lowered/compiled HLO census (:mod:`.hlo` — the compiled text is
  the authoritative one: GSPMD partitions at compile time),
* this pass's reshard sites,

so every surplus collective in the HLO is either attributed to a cited
equation or flagged as unattributed.

Propagation is deliberately conservative: unknown primitives produce
*unknown* specs, and unknown specs accuse nobody — the pass
under-reports rather than mis-reports, the same contract as the
narrowing-cast audit.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence, Tuple

import jax

from .trace import _source_of

# A dim spec is a tuple of mesh axis names sharding that dimension (()
# = unsharded); an array spec is a tuple of dim specs; None = unknown.
DimSpec = Tuple[str, ...]
ArraySpec = Optional[Tuple[DimSpec, ...]]


def canon_spec(spec, ndim: int) -> ArraySpec:
    """A ``PartitionSpec`` (or already-canonical tuple) as a canonical
    per-dimension tuple of axis-name tuples, padded to ``ndim``."""
    if spec is None:
        return None
    parts = tuple(spec)
    out = []
    for i in range(ndim):
        p = parts[i] if i < len(parts) else None
        if p is None:
            out.append(())
        elif isinstance(p, (tuple, list)):
            out.append(tuple(str(a) for a in p))
        else:
            out.append((str(p),))
    return tuple(out)


def _replicated(ndim: int) -> ArraySpec:
    return ((),) * ndim


def _is_sharded(spec: ArraySpec) -> bool:
    return spec is not None and any(spec)


def spec_str(spec: ArraySpec) -> str:
    if spec is None:
        return "?"
    return "P(" + ", ".join(
        "+".join(d) if d else "None" for d in spec
    ) + ")"


@dataclass(frozen=True)
class ReshardSite:
    """One equation where the partitioner must insert communication."""

    # 1-based equation counter in WALK order (top-level and descended
    # sub-jaxpr equations interleaved) — a stable label for findings,
    # not an index into any one eqn list; ``source`` is the
    # authoritative pointer to the responsible call.
    eqn_index: int
    primitive: str
    cls: str                # collective class the partitioner inserts
    note: str               # why (human-readable layout mismatch)
    source: Optional[str]   # file:line of the responsible call

    def citation(self) -> str:
        where = f" [{self.source}]" if self.source else ""
        return (
            f"walk-eqn#{self.eqn_index} {self.primitive}: {self.note} "
            f"(partitioner inserts {self.cls}){where}"
        )


@dataclass(frozen=True)
class ShardFlowReport:
    """Propagated output specs + every reshard site the flow found."""

    label: str
    out_specs: Tuple[ArraySpec, ...]
    reshard_sites: Tuple[ReshardSite, ...]
    n_eqns: int

    def sites_of_class(self, cls: str) -> Tuple[ReshardSite, ...]:
        return tuple(s for s in self.reshard_sites if s.cls == cls)


# primitives whose output follows the (single known) operand layout —
# a closed allowlist of genuinely elementwise ops.  Deliberately NOT a
# shapes-all-equal fallback: a same-shape scan/sort/cumsum is not
# layout-preserving, and fabricating a spec for it would let downstream
# equations be accused of (or excused from) reshards they don't cause —
# unknown primitives must produce unknown specs.
_ELEMENTWISE_HINTS = (
    "add", "add_any", "sub", "mul", "div", "max", "min", "pow", "rem",
    "and", "or", "xor", "not", "neg", "sign", "floor", "ceil", "round",
    "exp", "expm1", "log", "log1p", "tanh", "tan", "sinh", "cosh",
    "asin", "acos", "atan", "asinh", "acosh", "atanh", "logistic",
    "sqrt", "rsqrt", "cbrt", "abs", "cos", "sin", "erf", "erfc",
    "erf_inv", "convert_element_type", "integer_pow", "select_n", "ne",
    "eq", "ge", "gt", "le", "lt", "stop_gradient", "copy", "clamp",
    "is_finite", "nextafter", "real", "imag", "square",
)


class _Flow:
    def __init__(self, label: str):
        self.label = label
        self.sites: list = []
        self._eqn_index = 0  # running index across the whole walk

    # -- env helpers ---------------------------------------------------
    @staticmethod
    def _get(env, v) -> ArraySpec:
        if hasattr(v, "val"):  # Literal: replicated by construction
            return _replicated(getattr(v.val, "ndim", 0))
        return env.get(id(v))

    @staticmethod
    def _set(env, v, spec: ArraySpec) -> None:
        if spec is not None:
            env[id(v)] = spec

    def _site(self, eqn, cls: str, note: str) -> None:
        self.sites.append(ReshardSite(
            eqn_index=self._eqn_index,
            primitive=eqn.primitive.name,
            cls=cls,
            note=note,
            source=_source_of(eqn),
        ))

    # -- the walk ------------------------------------------------------
    def walk(self, jaxpr_like, env: dict) -> dict:
        """Propagate through one (closed) jaxpr; ``env`` maps var ids to
        specs and is updated in place.  Returns the env."""
        inner = getattr(jaxpr_like, "jaxpr", jaxpr_like)
        for cv in inner.constvars:
            env.setdefault(
                id(cv), _replicated(len(getattr(cv.aval, "shape", ())))
            )
        for eqn in inner.eqns:
            self._eqn_index += 1
            self._propagate(eqn, env)
        return env

    def _propagate(self, eqn, env) -> None:
        name = eqn.primitive.name
        in_specs = [self._get(env, v) for v in eqn.invars]

        if name in ("pjit", "xla_call", "remat", "remat2", "checkpoint",
                    "custom_jvp_call", "custom_vjp_call",
                    "custom_vjp_call_jaxpr", "closed_call", "core_call"):
            self._descend(eqn, env, in_specs)
            return
        if name == "shard_map":
            self._shard_map_out(eqn, env)
            return
        if name == "scan":
            self._scan(eqn, env, in_specs)
            return
        if name == "cond" and "branches" in eqn.params:
            self._cond(eqn, env, in_specs)
            return
        if name == "while":
            self._while(eqn, env, in_specs)
            return

        out_spec: ArraySpec = None
        known = [s for s in in_specs if s is not None]

        if name == "transpose":
            perm = eqn.params.get("permutation")
            if in_specs and in_specs[0] is not None and perm is not None:
                out_spec = tuple(in_specs[0][p] for p in perm)
        elif name == "broadcast_in_dim":
            dims = eqn.params.get("broadcast_dimensions", ())
            src = in_specs[0] if in_specs else None
            nd = len(getattr(eqn.outvars[0].aval, "shape", ()))
            if src is not None:
                out = [()] * nd
                for i, d in enumerate(dims):
                    if i < len(src):
                        out[d] = src[i]
                out_spec = tuple(out)
        elif name == "reshape":
            src = in_specs[0] if in_specs else None
            in_shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
            out_shape = tuple(getattr(eqn.outvars[0].aval, "shape", ()))
            if src is not None and not _is_sharded(src):
                out_spec = _replicated(len(out_shape))
            elif src is not None and in_shape == out_shape:
                out_spec = src
        elif name in ("reduce_sum", "reduce_max", "reduce_min",
                      "reduce_prod", "reduce_and", "reduce_or",
                      "argmax", "argmin"):
            src = in_specs[0] if in_specs else None
            axes = tuple(eqn.params.get("axes", ()))
            if src is not None:
                if any(i < len(src) and src[i] for i in axes):
                    self._site(
                        eqn, "all_reduce",
                        "reduction over a sharded dimension — partial "
                        "results must be combined across shards",
                    )
                out_spec = tuple(
                    d for i, d in enumerate(src) if i not in axes
                )
        elif name == "dot_general":
            out_spec = self._dot_general(eqn, env, in_specs)
        elif name in _ELEMENTWISE_HINTS:
            shaped = [
                (s, v) for s, v in zip(in_specs, eqn.invars)
                if s is not None
                and len(getattr(getattr(v, "aval", None), "shape", ()))
                == len(getattr(eqn.outvars[0].aval, "shape", ()))
            ]
            sharded = [(s, v) for s, v in shaped if _is_sharded(s)]
            distinct = {s for s, _ in sharded}
            if len(distinct) > 1:
                a, b = sorted(distinct)[:2]
                self._site(
                    eqn, "all_gather",
                    f"operands carry incompatible shardings "
                    f"{spec_str(a)} vs {spec_str(b)} — one side must be "
                    "resharded",
                )
            if sharded:
                out_spec = sharded[0][0]
            elif shaped:
                out_spec = shaped[0][0]

        for ov in eqn.outvars:
            if type(ov).__name__ == "DropVar":
                continue
            nd = len(getattr(getattr(ov, "aval", None), "shape", ()))
            if out_spec is not None and len(out_spec) == nd:
                self._set(env, ov, out_spec)

    def _descend(self, eqn, env, in_specs) -> None:
        """pjit-style call: positional invar alignment in, outvar
        alignment out (the same exact mapping the trace walker uses)."""
        for val in eqn.params.values():
            subs = val if isinstance(val, (tuple, list)) else (val,)
            for sub in subs:
                if not (hasattr(sub, "eqns") or hasattr(sub, "jaxpr")):
                    continue
                inner = getattr(sub, "jaxpr", sub)
                sub_env: dict = {}
                if len(inner.invars) == len(eqn.invars):
                    for iv, s in zip(inner.invars, in_specs):
                        self._set(sub_env, iv, s)
                self.walk(sub, sub_env)
                if len(inner.outvars) == len(eqn.outvars):
                    for sv, ov in zip(inner.outvars, eqn.outvars):
                        self._set(env, ov, self._get(sub_env, sv))
                return  # one callable sub-jaxpr per call eqn

    @staticmethod
    def _join(a: ArraySpec, b: ArraySpec) -> ArraySpec:
        """Specs agree -> the spec; any disagreement or unknown ->
        unknown (conservative: accuse nobody)."""
        return a if a == b else None

    def _walk_sub(self, sub, invar_specs) -> list:
        """Walk one sub-jaxpr with the given invar specs; returns the
        propagated outvar specs."""
        inner = getattr(sub, "jaxpr", sub)
        sub_env: dict = {}
        for iv, s in zip(inner.invars, invar_specs):
            self._set(sub_env, iv, s)
        self.walk(sub, sub_env)
        return [self._get(sub_env, ov) for ov in inner.outvars]

    def _scan(self, eqn, env, in_specs) -> None:
        """scan invars = consts + carry + xs (stacked, leading time
        dim); body sees consts/carry as-is and xs with the leading dim
        sliced off.  Outputs: carry (joined with the incoming carry
        spec — a layout that changes per iteration is unknown, not
        trusted) and ys re-stacked behind an unsharded leading dim."""
        n_consts = int(eqn.params.get("num_consts", 0))
        n_carry = int(eqn.params.get("num_carry", 0))
        body = eqn.params.get("jaxpr")
        if body is None:
            return
        body_in = list(in_specs[:n_consts + n_carry]) + [
            (s[1:] if s else s) if s is not None else None
            for s in in_specs[n_consts + n_carry:]
        ]
        outs = self._walk_sub(body, body_in)
        carry_in = in_specs[n_consts:n_consts + n_carry]
        for i, ov in enumerate(eqn.outvars):
            if i < n_carry:
                spec = self._join(
                    carry_in[i] if i < len(carry_in) else None,
                    outs[i] if i < len(outs) else None,
                )
            else:
                y = outs[i] if i < len(outs) else None
                spec = ((),) + y if y is not None else None
            if spec is not None:
                self._set(env, ov, spec)

    def _cond(self, eqn, env, in_specs) -> None:
        """Both branches walked with the operand specs (predicate
        skipped); outputs must agree across branches to be known."""
        branch_outs = [
            self._walk_sub(b, in_specs[1:])
            for b in eqn.params["branches"]
        ]
        for i, ov in enumerate(eqn.outvars):
            specs = [
                outs[i] if i < len(outs) else None
                for outs in branch_outs
            ]
            spec = specs[0]
            for s in specs[1:]:
                spec = self._join(spec, s)
            if spec is not None:
                self._set(env, ov, spec)

    def _while(self, eqn, env, in_specs) -> None:
        """invars = cond_consts + body_consts + carry; each sub-jaxpr
        walked once with its consts + the carry; outputs (the carry)
        must be loop-stable (join of carry-in and body-out) to be
        known."""
        cn = int(eqn.params.get("cond_nconsts", 0))
        bn = int(eqn.params.get("body_nconsts", 0))
        carry = in_specs[cn + bn:]
        if "cond_jaxpr" in eqn.params:
            self._walk_sub(
                eqn.params["cond_jaxpr"], list(in_specs[:cn]) + carry
            )
        outs: list = []
        if "body_jaxpr" in eqn.params:
            outs = self._walk_sub(
                eqn.params["body_jaxpr"],
                list(in_specs[cn:cn + bn]) + carry,
            )
        for i, ov in enumerate(eqn.outvars):
            spec = self._join(
                carry[i] if i < len(carry) else None,
                outs[i] if i < len(outs) else None,
            )
            if spec is not None:
                self._set(env, ov, spec)

    def _shard_map_out(self, eqn, env) -> None:
        """A manual region: outputs carry the declared out_names (its
        internal collectives are authored — the trace walker owns them).
        """
        out_names = eqn.params.get("out_names", ())
        for ov, names in zip(eqn.outvars, out_names):
            nd = len(getattr(getattr(ov, "aval", None), "shape", ()))
            spec = [()] * nd
            try:
                for dim, axes in dict(names).items():
                    if dim < nd:
                        spec[dim] = tuple(str(a) for a in axes)
            except Exception:
                continue
            self._set(env, ov, tuple(spec))

    def _dot_general(self, eqn, env, in_specs) -> ArraySpec:
        """Megatron arithmetic: sharded contracting dims force a
        cross-shard combine; free dims carry their operand's sharding —
        and one mesh axis appearing on two output dims is impossible, so
        the partitioner gathers one side."""
        dnums = eqn.params.get("dimension_numbers")
        if dnums is None:
            return None
        (lc, rc), (lb, rb) = dnums
        lhs, rhs = (in_specs + [None, None])[:2]

        contracted_shard = []
        for side, spec, dims in (("lhs", lhs, lc), ("rhs", rhs, rc)):
            if spec is None:
                continue
            for d in dims:
                if d < len(spec) and spec[d]:
                    contracted_shard.append((side, d, spec[d]))
        if contracted_shard:
            both = {s for s, _, _ in contracted_shard} == {"lhs", "rhs"}
            self._site(
                eqn,
                "all_reduce" if both else "all_gather",
                "contracting dimension is sharded "
                + (
                    "on both operands — partial products must be "
                    "all-reduced"
                    if both
                    else f"on {contracted_shard[0][0]} only — the "
                    "partitioner gathers it"
                ),
            )

        def free_dims(spec, contract, batch):
            if spec is None:
                return None
            return [
                spec[d] for d in range(len(spec))
                if d not in contract and d not in batch
            ]

        lfree = free_dims(lhs, lc, lb)
        rfree = free_dims(rhs, rc, rb)
        if lfree is None or rfree is None:
            return None
        batch = [
            (lhs[d] if lhs is not None and d < len(lhs) else ())
            for d in lb
        ]
        out = tuple(batch + lfree + rfree)
        used: set = set()
        for d in out:
            for a in d:
                if a in used:
                    self._site(
                        eqn, "all_gather",
                        f"mesh axis {a!r} would shard two output "
                        "dimensions — the partitioner gathers one "
                        "operand",
                    )
                    return None
                used.add(a)
        return out


def shardflow_jaxpr(jaxpr_like, in_specs: Sequence[Any],
                    label: str = "flow",
                    declared_out_specs: Optional[Sequence[Any]] = None,
                    ) -> ShardFlowReport:
    """Run the flow over an already-made (closed) jaxpr.

    ``in_specs``: one ``PartitionSpec`` (or None = unknown) per jaxpr
    invar.  ``declared_out_specs``: the program's declared output
    shardings — a propagated output MORE sharded than its declaration
    is a reshard the partitioner resolves with an all-gather, and is
    recorded as a site against the whole program.
    """
    inner = getattr(jaxpr_like, "jaxpr", jaxpr_like)
    flow = _Flow(label)
    env: dict = {}
    invars = list(inner.invars)
    specs = list(in_specs) + [None] * (len(invars) - len(in_specs))
    for v, s in zip(invars, specs):
        nd = len(getattr(getattr(v, "aval", None), "shape", ()))
        flow._set(env, v, canon_spec(s, nd))
    flow.walk(jaxpr_like, env)

    outs = tuple(flow._get(env, v) for v in inner.outvars)
    if declared_out_specs is not None:
        for i, (got, want) in enumerate(zip(outs, declared_out_specs)):
            if got is None:
                continue
            nd = len(got)
            want_c = canon_spec(want, nd)
            if want_c is None:
                continue
            for d in range(nd):
                extra = [a for a in got[d] if a not in want_c[d]]
                if extra:
                    flow.sites.append(ReshardSite(
                        eqn_index=-1,
                        primitive="<output>",
                        cls="all_gather",
                        note=(
                            f"output {i} propagates as "
                            f"{spec_str(got)} but is declared "
                            f"{spec_str(want_c)} — the partitioner "
                            "gathers it to match"
                        ),
                        source=None,
                    ))
                    break
    return ShardFlowReport(
        label=label,
        out_specs=outs,
        reshard_sites=tuple(flow.sites),
        n_eqns=flow._eqn_index,
    )


def shardflow(fn, *args, in_specs: Sequence[Any],
              out_specs: Optional[Sequence[Any]] = None,
              label: Optional[str] = None, **kwargs) -> ShardFlowReport:
    """Trace ``fn(*args, **kwargs)`` and run the sharding-flow pass.

    ``in_specs``: PartitionSpecs aligned with the *flattened* positional
    args (one spec per array leaf, tree-flatten order — matching how
    the jaxpr receives them).  Nothing is compiled or executed.
    """
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    # one spec per flattened arg leaf; None (= unknown) is itself a leaf
    flat_specs = jax.tree_util.tree_leaves(
        tuple(in_specs), is_leaf=lambda x: x is None
    )
    return shardflow_jaxpr(
        jaxpr, flat_specs,
        label=label or getattr(fn, "__name__", "flow"),
        declared_out_specs=out_specs,
    )

"""Collective trace extraction from jaxprs.

The SPMD contract under every communicator tier is that all ranks
execute the *same ordered sequence of collectives*; one divergent psum
deadlocks the job or silently mixes wire layouts.  This module makes
that sequence a first-class object: :func:`trace_collectives` traces any
jittable function (a compiled train step, an eager communicator method,
a bare shard_map body) to a :class:`CollectiveTrace` — the ordered list
of collective primitives with axis names, dtypes, shapes, and the
enclosing control-flow context — by walking the closed jaxpr recursively
through ``pjit`` / ``scan`` / ``cond`` / ``while`` / ``shard_map``
sub-jaxprs (including the ``_compat`` shard_map shim on old jax, which
binds the same primitive).

The walk is static: nothing is compiled or executed, so tracing even a
ResNet-50 train step costs milliseconds.  Counting is per jaxpr
*occurrence* — a collective inside ``scan`` appears once, exactly as it
appears once in the lowered HLO while-loop body — which is what lets the
trace census cross-check against the HLO text census
(:mod:`chainermn_tpu.analysis.hlo`) instead of replacing one grep with
another.

Two audits are gathered during the same walk (they need dataflow and
branch structure that the flat record list no longer has):

* narrowing casts feeding a reduction (the wire audit's raw material) —
  ``convert_element_type`` eqns that shrink the element and whose result
  is consumed by a psum-family reduction, annotated with the cast's
  source file so :func:`~chainermn_tpu.analysis.checks.check_wire` can
  exempt the sanctioned ``comm_wire`` codecs;
* per-branch collective signatures of every ``cond`` (the deadlock
  lint's raw material) — a data-dependent branch whose arms trace
  different collective sequences is the canonical SPMD deadlock.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass
from typing import Callable, Optional, Tuple

import jax

# strips the walk-global cond counter out of branch-relative signatures
# (see _Walker._walk_cond)
_COND_ID_RE = re.compile(r"cond#\d+")

# Communication primitives and the HLO op class each lowers to.  pmean
# has no primitive of its own (psum + divide), pgather/all_gather_invariant
# are folded into the gather class.  axis_index / axis_size are *not*
# communication and are deliberately absent.
COLLECTIVE_CLASS = {
    "psum": "all_reduce",
    "pmax": "all_reduce",
    "pmin": "all_reduce",
    "all_gather": "all_gather",
    "all_gather_invariant": "all_gather",
    "pgather": "all_gather",
    "reduce_scatter": "reduce_scatter",
    "psum_scatter": "reduce_scatter",
    "ppermute": "collective_permute",
    "pshuffle": "collective_permute",
    "all_to_all": "all_to_all",
}

# classes whose semantics are a cross-rank *reduction* (the wire audit
# only cares about narrowed inputs to these — a narrowed ppermute
# payload loses precision locally, it does not corrupt a sum)
REDUCTION_CLASSES = ("all_reduce", "reduce_scatter")

# eqn params that distinguish two otherwise-identical collectives (a
# ppermute with a different perm is a different program)
_DETAIL_PARAMS = (
    "axis_index_groups",
    "all_gather_dimension",
    "scatter_dimension",
    "split_axis",
    "concat_axis",
    "axis_size",
    "tiled",
    "perm",
)


def _axes_of(params) -> Tuple[str, ...]:
    axes = params.get("axes", params.get("axis_name", ()))
    if axes is None:
        return ()
    if isinstance(axes, (str, int)):
        return (str(axes),)
    return tuple(str(a) for a in axes)


def _source_of(eqn) -> Optional[str]:
    """``file:line`` of the user frame that issued this eqn, if known."""
    try:
        from jax._src import source_info_util as siu

        fr = siu.user_frame(eqn.source_info)
        if fr is None:
            return None
        return f"{fr.file_name}:{fr.start_line}"
    except Exception:
        return None


@dataclass(frozen=True)
class CollectiveRecord:
    """One collective primitive occurrence in program order."""

    primitive: str          # jaxpr primitive name (psum, all_gather, ...)
    cls: str                # HLO op class (all_reduce, all_to_all, ...)
    axes: Tuple[str, ...]   # mesh axis names reduced/permuted over
    dtypes: Tuple[str, ...]  # operand dtypes, in operand order
    shapes: Tuple[Tuple[int, ...], ...]  # operand shapes
    context: Tuple[str, ...]  # enclosing sub-jaxpr path, outermost first
    detail: str = ""        # canonicalized distinguishing params
    source: Optional[str] = None  # file:line of the issuing call

    def signature(self, context_from: int = 0) -> str:
        """Canonical string for hashing/comparison.  Excludes ``source``
        (formatting-only edits must not change the trace hash) and keeps
        everything that changes the compiled program.  ``context_from``
        drops that many leading context elements — the cond deadlock
        lint compares branch bodies *relative to the branch*, so two
        arms with identical collectives compare equal even though their
        absolute contexts carry different branch labels."""
        return "|".join(
            (
                self.primitive,
                ",".join(self.axes),
                ",".join(self.dtypes),
                ";".join("x".join(map(str, s)) for s in self.shapes),
                "/".join(self.context[context_from:]),
                self.detail,
            )
        )

    def in_cond(self) -> bool:
        return any(c.startswith("cond#") for c in self.context)


@dataclass(frozen=True)
class NarrowingCast:
    """A dtype-narrowing ``convert_element_type`` feeding a reduction."""

    collective: CollectiveRecord
    src_dtype: str
    dst_dtype: str
    cast_source: Optional[str]  # file:line of the cast


@dataclass(frozen=True)
class CondBranchReport:
    """Per-branch collective signatures of one ``cond`` eqn."""

    cond_id: str                 # "cond#<k>" — unique within the trace
    context: Tuple[str, ...]     # context of the cond eqn itself
    branch_signatures: Tuple[Tuple[str, ...], ...]
    source: Optional[str] = None

    @property
    def has_collectives(self) -> bool:
        return any(self.branch_signatures)

    @property
    def diverges(self) -> bool:
        """True when the arms trace different collective sequences —
        rank-dependent predicates then deadlock or mis-pair wires."""
        sigs = self.branch_signatures
        return any(s != sigs[0] for s in sigs[1:])


@dataclass(frozen=True)
class CollectiveTrace:
    """Ordered collective records of one traced program + walk-time
    audit material.  Immutable; all checks live in ``analysis.checks``.
    """

    records: Tuple[CollectiveRecord, ...]
    narrowing_casts: Tuple[NarrowingCast, ...] = ()
    cond_reports: Tuple[CondBranchReport, ...] = ()
    label: str = "trace"

    def __len__(self) -> int:
        return len(self.records)

    def __iter__(self):
        return iter(self.records)

    def census(self) -> dict:
        """``{hlo_op_class: count}`` over all records (zero counts
        omitted) — the analyzer-side half of the HLO cross-check."""
        out: dict = {}
        for r in self.records:
            out[r.cls] = out.get(r.cls, 0) + 1
        return out

    def count(self, cls: str) -> int:
        return self.census().get(cls, 0)

    def axis_names(self) -> Tuple[str, ...]:
        seen: list = []
        for r in self.records:
            for a in r.axes:
                if a not in seen:
                    seen.append(a)
        return tuple(seen)

    def canonical(self) -> str:
        """Canonical multi-line serialization (one signature per record,
        program order) — the thing the divergence guard hashes.  Pure
        function of the traced program: values, device placement, and
        source locations do not enter."""
        return "\n".join(r.signature() for r in self.records)

    def trace_hash(self) -> str:
        """sha256 of :meth:`canonical` — the cross-process agreement
        token (salted ``hash()`` would differ per interpreter)."""
        return hashlib.sha256(self.canonical().encode()).hexdigest()


# ----------------------------------------------------------------------
# jaxpr walking
# ----------------------------------------------------------------------
def _eqns(jaxpr_like):
    """Eqn list of a Jaxpr or ClosedJaxpr (shard_map carries an open
    Jaxpr; pjit/scan/cond carry ClosedJaxprs)."""
    inner = getattr(jaxpr_like, "jaxpr", jaxpr_like)
    return inner.eqns, inner


def _avals(eqn):
    dtypes, shapes = [], []
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if aval is None or not hasattr(aval, "dtype"):
            continue
        dtypes.append(str(aval.dtype))
        shapes.append(tuple(int(d) for d in aval.shape))
    return tuple(dtypes), tuple(shapes)


def _detail_of(params) -> str:
    parts = []
    for k in _DETAIL_PARAMS:
        if k in params and params[k] is not None:
            parts.append(f"{k}={params[k]}")
    return ";".join(parts)


_CTX_LABELS = {
    "pjit": "pjit",
    "xla_call": "pjit",
    "scan": "scan",
    "shard_map": "shard_map",
    "remat": "remat",
    "remat2": "remat",
    "checkpoint": "remat",
    "custom_jvp_call": "custom_jvp",
    "custom_vjp_call": "custom_vjp",
    "custom_vjp_call_jaxpr": "custom_vjp",
}


def _is_jaxpr(x) -> bool:
    return hasattr(x, "eqns") or hasattr(x, "jaxpr")


class _Walker:
    def __init__(self):
        self.records: list = []
        self.narrowing: list = []
        self.cond_reports: list = []
        self._cond_counter = 0

    def walk(self, jaxpr_like, context: Tuple[str, ...] = (),
             narrow_in: Optional[dict] = None) -> None:
        """``narrow_in``: vars of this scope known (from the caller's
        scope) to carry a narrowing-cast result, mapped to their
        (src_dtype, dst_dtype, source) provenance."""
        eqns, jaxpr = _eqns(jaxpr_like)
        narrow: dict = dict(narrow_in or {})
        for eqn in eqns:
            name = eqn.primitive.name
            params = eqn.params

            if name == "convert_element_type":
                self._note_cast(eqn, narrow)
            elif name in COLLECTIVE_CLASS:
                rec = self._record(eqn, context)
                self.records.append(rec)
                if rec.cls in REDUCTION_CLASSES:
                    for v in eqn.invars:
                        if id(v) in narrow:
                            src, dst, where = narrow[id(v)]
                            self.narrowing.append(
                                NarrowingCast(rec, src, dst, where)
                            )

            if name == "cond" and "branches" in params:
                self._walk_cond(eqn, context, narrow)
            elif name == "while":
                for key, lbl in (("cond_jaxpr", "while/cond"),
                                 ("body_jaxpr", "while/body")):
                    if key in params:
                        self.walk(params[key], context + (lbl,))
            else:
                self._walk_generic_subs(eqn, context, narrow)

    # -- helpers -------------------------------------------------------
    def _record(self, eqn, context) -> CollectiveRecord:
        dtypes, shapes = _avals(eqn)
        return CollectiveRecord(
            primitive=eqn.primitive.name,
            cls=COLLECTIVE_CLASS[eqn.primitive.name],
            axes=_axes_of(eqn.params),
            dtypes=dtypes,
            shapes=shapes,
            context=context,
            detail=_detail_of(eqn.params),
            source=_source_of(eqn),
        )

    def _note_cast(self, eqn, narrow) -> None:
        inv = eqn.invars[0]
        outv = eqn.outvars[0]
        src = getattr(getattr(inv, "aval", None), "dtype", None)
        dst = getattr(getattr(outv, "aval", None), "dtype", None)
        if src is None or dst is None:
            return
        import numpy as np

        if np.dtype(dst).itemsize < np.dtype(src).itemsize:
            narrow[id(outv)] = (str(src), str(dst), _source_of(eqn))
        elif id(inv) in narrow:
            # widening a previously-narrowed value does not undo the
            # precision loss (int8 -> int32 before an integer psum is
            # still an int8 wire): provenance follows the value
            narrow[id(outv)] = narrow[id(inv)]

    def _walk_cond(self, eqn, context, narrow) -> None:
        self._cond_counter += 1
        cond_id = f"cond#{self._cond_counter}"
        sigs = []
        for i, branch in enumerate(eqn.params["branches"]):
            label = f"{cond_id}[{i}]"
            start = len(self.records)
            sub_narrow = self._map_into(eqn, branch, narrow,
                                        skip_leading=1)  # predicate
            self.walk(branch, context + (label,), sub_narrow)
            # branch-RELATIVE signatures: arms with identical
            # collective bodies must compare equal despite carrying
            # different branch labels in their absolute contexts — and
            # despite NESTED conds drawing different ids from the
            # global counter (arm 0's inner cond is cond#2, arm 1's
            # identical one cond#3), so the ids are stripped here; the
            # trace hash keeps them (the counter sequence is a
            # deterministic function of the program, so equal programs
            # still hash equal)
            sigs.append(tuple(
                _COND_ID_RE.sub("cond", r.signature(
                    context_from=len(context) + 1
                ))
                for r in self.records[start:]
            ))
        self.cond_reports.append(CondBranchReport(
            cond_id=cond_id,
            context=context,
            branch_signatures=tuple(sigs),
            source=_source_of(eqn),
        ))

    def _walk_generic_subs(self, eqn, context, narrow) -> None:
        label_base = _CTX_LABELS.get(
            eqn.primitive.name, eqn.primitive.name
        )
        for key, val in eqn.params.items():
            vals = val if isinstance(val, (tuple, list)) else (val,)
            for i, sub in enumerate(vals):
                if not _is_jaxpr(sub):
                    continue
                label = (
                    label_base
                    if len(vals) == 1
                    else f"{label_base}:{key}[{i}]"
                )
                self.walk(
                    sub,
                    context + (label,),
                    self._map_into(eqn, sub, narrow),
                )

    @staticmethod
    def _map_into(eqn, sub, narrow, skip_leading: int = 0) -> dict:
        """Translate narrowing provenance across a sub-jaxpr boundary by
        positional invar alignment (exact for pjit / shard_map / cond
        branches; scan's const/carry/xs packing is skipped rather than
        guessed — a missed propagation under-reports, never
        mis-reports)."""
        if not narrow:
            return {}
        inner = getattr(sub, "jaxpr", sub)
        outer = list(eqn.invars)[skip_leading:]
        inner_vars = list(inner.invars)
        if len(outer) != len(inner_vars):
            return {}
        out = {}
        for o, s in zip(outer, inner_vars):
            if id(o) in narrow:
                out[id(s)] = narrow[id(o)]
        return out


def trace_jaxpr(jaxpr_like, label: str = "trace") -> CollectiveTrace:
    """Walk an already-made (closed) jaxpr into a
    :class:`CollectiveTrace`."""
    w = _Walker()
    w.walk(jaxpr_like)
    return CollectiveTrace(
        records=tuple(w.records),
        narrowing_casts=tuple(w.narrowing),
        cond_reports=tuple(w.cond_reports),
        label=label,
    )


def trace_collectives(fn: Callable, *args, label: Optional[str] = None,
                      **kwargs) -> CollectiveTrace:
    """Trace ``fn(*args, **kwargs)`` to its ordered collective sequence.

    ``fn`` is anything jax can trace: a plain function, a jitted train
    step, a ``shard_map``-wrapped body, or an eager communicator method
    whose dispatch is built from cached jit programs (the jaxpr then
    contains ``pjit`` eqns that the walker descends into).  Args may be
    arrays or ``jax.ShapeDtypeStruct``\\ s — only shapes/dtypes matter.

    Nothing is compiled or executed; no collective runs.
    """
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    return trace_jaxpr(
        jaxpr, label=label or getattr(fn, "__name__", "trace")
    )

"""Collective trace extraction from jaxprs.

The SPMD contract under every communicator tier is that all ranks
execute the *same ordered sequence of collectives*; one divergent psum
deadlocks the job or silently mixes wire layouts.  This module makes
that sequence a first-class object: :func:`trace_collectives` traces any
jittable function (a compiled train step, an eager communicator method,
a bare shard_map body) to a :class:`CollectiveTrace` — the ordered list
of collective primitives with axis names, dtypes, shapes, and the
enclosing control-flow context — by walking the closed jaxpr recursively
through ``pjit`` / ``scan`` / ``cond`` / ``while`` / ``shard_map``
sub-jaxprs (including the ``_compat`` shard_map shim on old jax, which
binds the same primitive).

The walk is static: nothing is compiled or executed, so tracing even a
ResNet-50 train step costs milliseconds.  Counting is per jaxpr
*occurrence* — a collective inside ``scan`` appears once, exactly as it
appears once in the lowered HLO while-loop body — which is what lets the
trace census cross-check against the HLO text census
(:mod:`chainermn_tpu.analysis.hlo`) instead of replacing one grep with
another.

Two audits are gathered during the same walk (they need dataflow and
branch structure that the flat record list no longer has):

* narrowing casts feeding a reduction (the wire audit's raw material) —
  ``convert_element_type`` eqns that shrink the element and whose result
  is consumed by a psum-family reduction, annotated with the cast's
  source file so :func:`~chainermn_tpu.analysis.checks.check_wire` can
  exempt the sanctioned ``comm_wire`` codecs;
* per-branch collective signatures of every ``cond`` (the deadlock
  lint's raw material) — a data-dependent branch whose arms trace
  different collective sequences is the canonical SPMD deadlock.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass
from typing import Callable, Optional, Tuple

import jax
import numpy as np

# strips the walk-global cond counter out of branch-/loop-relative
# signatures (see _Walker._walk_cond / _Walker._walk_while — while
# bodies label their contexts "while/cond"/"while/body" with no
# counter, so nested cond ids are the only ids to strip in both)
_COND_ID_RE = re.compile(r"cond#\d+")

# Communication primitives and the HLO op class each lowers to.  pmean
# has no primitive of its own (psum + divide), pgather/all_gather_invariant
# are folded into the gather class.  axis_index / axis_size are *not*
# communication and are deliberately absent.
COLLECTIVE_CLASS = {
    "psum": "all_reduce",
    "pmax": "all_reduce",
    "pmin": "all_reduce",
    "all_gather": "all_gather",
    "all_gather_invariant": "all_gather",
    "pgather": "all_gather",
    "reduce_scatter": "reduce_scatter",
    "psum_scatter": "reduce_scatter",
    "ppermute": "collective_permute",
    "pshuffle": "collective_permute",
    "all_to_all": "all_to_all",
}

# classes whose semantics are a cross-rank *reduction* (the wire audit
# only cares about narrowed inputs to these — a narrowed ppermute
# payload loses precision locally, it does not corrupt a sum)
REDUCTION_CLASSES = ("all_reduce", "reduce_scatter")

# eqn params that distinguish two otherwise-identical collectives (a
# ppermute with a different perm is a different program)
_DETAIL_PARAMS = (
    "axis_index_groups",
    "all_gather_dimension",
    "scatter_dimension",
    "split_axis",
    "concat_axis",
    "axis_size",
    "tiled",
    "perm",
)


def _axes_of(params) -> Tuple[str, ...]:
    axes = params.get("axes", params.get("axis_name", ()))
    if axes is None:
        return ()
    if isinstance(axes, (str, int)):
        return (str(axes),)
    return tuple(str(a) for a in axes)


# ----------------------------------------------------------------------
# per-collective cost model (ISSUE 6): bytes-on-wire + hop class
# ----------------------------------------------------------------------
# Hop classification follows the hierarchical communicator's axis naming
# (``communicators/_topology.py`` derives the ('mn_inter', 'mn_intra')
# pair): an axis whose name carries "inter" crosses node/slice
# boundaries (DCN-class links), "intra" stays on one ICI island, and a
# topology-agnostic axis ("mn") is "flat" — a single axis spanning the
# whole communicator, intra-slice on one-slice worlds.  The comm_wire
# planner consumes this to size buckets per link class (DynamiQ-style
# byte/latency accounting, PAPERS.md).
def hop_class(axes) -> str:
    """"inter" / "intra" / "mixed" / "flat" / "local" for a collective's
    mesh axis tuple."""
    if not axes:
        return "local"
    kinds = set()
    for a in axes:
        a = str(a)
        if "inter" in a:
            kinds.add("inter")
        elif "intra" in a:
            kinds.add("intra")
        else:
            kinds.add("flat")
    if kinds == {"flat"}:
        return "flat"
    if len(kinds) > 1:
        return "mixed"
    return kinds.pop()


def _world_of(axis_sizes: Tuple[int, ...]) -> Optional[int]:
    """Total ranks spanned by a collective's axis tuple; None when any
    size is unknown (0).  The ONE definition behind both
    ``CollectiveRecord.world`` and the walker's wire pricing."""
    if not axis_sizes or any(s <= 0 for s in axis_sizes):
        return None
    n = 1
    for s in axis_sizes:
        n *= s
    return n


def wire_bytes(cls: str, payload_bytes: int,
               world: Optional[int]) -> Optional[int]:
    """Per-rank bytes shipped for one collective under the standard ring
    algorithms; ``None`` when the axis size (``world``) is unknown.

    ``payload_bytes`` is the operand bytes as the record carries them
    (per-shard input for all_reduce/all_gather/ppermute, the full block
    being scattered for reduce_scatter).  Formulas: ring all-reduce
    moves ``2p(n-1)/n`` per rank (reduce-scatter + all-gather halves),
    reduce-scatter/all-to-all ``p(n-1)/n``, all-gather receives the
    other ``n-1`` shards (``p(n-1)``), collective-permute is one hop
    (``p``).
    """
    if world is None or world <= 0:
        return None
    n = world
    if cls == "all_reduce":
        return int(2 * payload_bytes * (n - 1) / n)
    if cls in ("reduce_scatter", "all_to_all"):
        return int(payload_bytes * (n - 1) / n)
    if cls == "all_gather":
        return int(payload_bytes * (n - 1))
    if cls == "collective_permute":
        return int(payload_bytes)
    return int(payload_bytes)


def _source_of(eqn) -> Optional[str]:
    """``file:line`` of the user frame that issued this eqn, if known."""
    try:
        from jax._src import source_info_util as siu

        fr = siu.user_frame(eqn.source_info)
        if fr is None:
            return None
        return f"{fr.file_name}:{fr.start_line}"
    except Exception:
        return None


@dataclass(frozen=True)
class CollectiveRecord:
    """One collective primitive occurrence in program order."""

    primitive: str          # jaxpr primitive name (psum, all_gather, ...)
    cls: str                # HLO op class (all_reduce, all_to_all, ...)
    axes: Tuple[str, ...]   # mesh axis names reduced/permuted over
    dtypes: Tuple[str, ...]  # operand dtypes, in operand order
    shapes: Tuple[Tuple[int, ...], ...]  # operand shapes
    context: Tuple[str, ...]  # enclosing sub-jaxpr path, outermost first
    detail: str = ""        # canonicalized distinguishing params
    source: Optional[str] = None  # file:line of the issuing call
    # -- cost model (derived; excluded from signature()/hash) ----------
    axis_sizes: Tuple[int, ...] = ()  # size per axis in `axes` (0 unknown)
    payload_bytes: int = 0  # operand bytes entering the collective
    bytes_on_wire: Optional[int] = None  # per-rank wire bytes (ring)
    hop: str = "local"      # "inter"/"intra"/"mixed"/"flat"/"local"

    @property
    def world(self) -> Optional[int]:
        """Total ranks this collective spans (None if any axis size is
        unknown at trace time)."""
        return _world_of(self.axis_sizes)

    def signature(self, context_from: int = 0) -> str:
        """Canonical string for hashing/comparison.  Excludes ``source``
        (formatting-only edits must not change the trace hash) and keeps
        everything that changes the compiled program.  ``context_from``
        drops that many leading context elements — the cond deadlock
        lint compares branch bodies *relative to the branch*, so two
        arms with identical collectives compare equal even though their
        absolute contexts carry different branch labels."""
        return "|".join(
            (
                self.primitive,
                ",".join(self.axes),
                ",".join(self.dtypes),
                ";".join("x".join(map(str, s)) for s in self.shapes),
                "/".join(self.context[context_from:]),
                self.detail,
            )
        )

    def in_cond(self) -> bool:
        return any(c.startswith("cond#") for c in self.context)


@dataclass(frozen=True)
class NarrowingCast:
    """A dtype-narrowing ``convert_element_type`` feeding a reduction."""

    collective: CollectiveRecord
    src_dtype: str
    dst_dtype: str
    cast_source: Optional[str]  # file:line of the cast


@dataclass(frozen=True)
class CondBranchReport:
    """Per-branch collective signatures of one ``cond`` eqn."""

    cond_id: str                 # "cond#<k>" — unique within the trace
    context: Tuple[str, ...]     # context of the cond eqn itself
    branch_signatures: Tuple[Tuple[str, ...], ...]
    source: Optional[str] = None

    @property
    def has_collectives(self) -> bool:
        return any(self.branch_signatures)

    @property
    def diverges(self) -> bool:
        """True when the arms trace different collective sequences —
        rank-dependent predicates then deadlock or mis-pair wires."""
        sigs = self.branch_signatures
        return any(s != sigs[0] for s in sigs[1:])


@dataclass(frozen=True)
class WhileReport:
    """Collective signatures of one ``while`` eqn's cond/body jaxprs —
    the deadlock lint's raw material for data-dependent loops.

    A collective inside a ``while`` body executes once per iteration:
    rank-divergent trip counts issue rank-divergent collective sequences
    (the while analogue of divergent ``cond`` arms).  Two statically
    checkable mitigations are recorded:

    * ``counter_only_predicate`` — the exit predicate reads only carry
      slots that the body advances by a constant (the ``fori_loop``
      shape), so the trip count is a pure function of loop-invariant
      inputs (assumed rank-uniform, as for ``cond`` predicates);
    * ``cond_has_reduction`` — the predicate itself is computed through
      a cross-rank reduction (the convergence-loop shape: every rank
      agrees on the continue/exit decision by construction).
    """

    while_id: str                 # "while#<k>" — unique within the trace
    context: Tuple[str, ...]      # context of the while eqn itself
    cond_signatures: Tuple[str, ...]
    body_signatures: Tuple[str, ...]
    counter_only_predicate: bool
    cond_has_reduction: bool
    source: Optional[str] = None

    @property
    def has_collectives(self) -> bool:
        return bool(self.cond_signatures or self.body_signatures)

    @property
    def trip_count_agreed(self) -> bool:
        """True when the trip count is statically rank-uniform (counter
        predicate) or rank-agreed (reduction inside the predicate)."""
        return self.counter_only_predicate or self.cond_has_reduction


@dataclass(frozen=True)
class CollectiveTrace:
    """Ordered collective records of one traced program + walk-time
    audit material.  Immutable; all checks live in ``analysis.checks``.
    """

    records: Tuple[CollectiveRecord, ...]
    narrowing_casts: Tuple[NarrowingCast, ...] = ()
    cond_reports: Tuple[CondBranchReport, ...] = ()
    label: str = "trace"
    while_reports: Tuple[WhileReport, ...] = ()

    def __len__(self) -> int:
        return len(self.records)

    def __iter__(self):
        return iter(self.records)

    def census(self) -> dict:
        """``{hlo_op_class: count}`` over all records (zero counts
        omitted) — the analyzer-side half of the HLO cross-check."""
        out: dict = {}
        for r in self.records:
            out[r.cls] = out.get(r.cls, 0) + 1
        return out

    def count(self, cls: str) -> int:
        return self.census().get(cls, 0)

    def wire_census(self, by_class: bool = False) -> dict:
        """``{hop_class: total bytes_on_wire}`` over records whose axis
        sizes were known at trace time (zero totals omitted) — the
        aggregate the comm_wire planner's hop-aware bucket sizing
        consumes.

        ``by_class=True`` keys the totals ``"{hop}/{op_class}"`` (e.g.
        ``"intra/reduce_scatter"``, ``"inter/all_reduce"``) — the
        per-hop attribution of a multi-hop schedule's rs→ar→ag triple,
        which is how a hier-scheduled step SHOWS its inter-hop byte
        saving: the flat step's bytes sit under ``mixed/all_reduce``,
        the staged step's under intra rs/ag plus a small
        ``inter/all_reduce``."""
        out: dict = {}
        for r in self.records:
            if r.bytes_on_wire:
                key = f"{r.hop}/{r.cls}" if by_class else r.hop
                out[key] = out.get(key, 0) + r.bytes_on_wire
        return out

    def axis_names(self) -> Tuple[str, ...]:
        seen: list = []
        for r in self.records:
            for a in r.axes:
                if a not in seen:
                    seen.append(a)
        return tuple(seen)

    def canonical(self) -> str:
        """Canonical multi-line serialization (one signature per record,
        program order) — the thing the divergence guard hashes.  Pure
        function of the traced program: values, device placement, and
        source locations do not enter."""
        return "\n".join(r.signature() for r in self.records)

    def trace_hash(self) -> str:
        """sha256 of :meth:`canonical` — the cross-process agreement
        token (salted ``hash()`` would differ per interpreter)."""
        return hashlib.sha256(self.canonical().encode()).hexdigest()


# ----------------------------------------------------------------------
# jaxpr walking
# ----------------------------------------------------------------------
def _eqns(jaxpr_like):
    """Eqn list of a Jaxpr or ClosedJaxpr (shard_map carries an open
    Jaxpr; pjit/scan/cond carry ClosedJaxprs)."""
    inner = getattr(jaxpr_like, "jaxpr", jaxpr_like)
    return inner.eqns, inner


def _avals(eqn):
    dtypes, shapes = [], []
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if aval is None or not hasattr(aval, "dtype"):
            continue
        dtypes.append(str(aval.dtype))
        shapes.append(tuple(int(d) for d in aval.shape))
    return tuple(dtypes), tuple(shapes)


def _detail_of(params) -> str:
    parts = []
    for k in _DETAIL_PARAMS:
        if k in params and params[k] is not None:
            parts.append(f"{k}={params[k]}")
    return ";".join(parts)


_CTX_LABELS = {
    "pjit": "pjit",
    "xla_call": "pjit",
    "scan": "scan",
    "shard_map": "shard_map",
    "remat": "remat",
    "remat2": "remat",
    "checkpoint": "remat",
    "custom_jvp_call": "custom_jvp",
    "custom_vjp_call": "custom_vjp",
    "custom_vjp_call_jaxpr": "custom_vjp",
}


def _is_jaxpr(x) -> bool:
    return hasattr(x, "eqns") or hasattr(x, "jaxpr")


class _Walker:
    def __init__(self, axis_sizes=None):
        self.records: list = []
        self.narrowing: list = []
        self.cond_reports: list = []
        self.while_reports: list = []
        self._cond_counter = 0
        self._while_counter = 0
        # mesh axis name -> size, for the cost model.  Seeded by the
        # caller (eager paths whose mesh is not in the jaxpr) and
        # updated authoritatively from every shard_map eqn's mesh param.
        self._axis_env: dict = dict(axis_sizes or {})

    def walk(self, jaxpr_like, context: Tuple[str, ...] = (),
             narrow_in: Optional[dict] = None) -> None:
        """``narrow_in``: vars of this scope known (from the caller's
        scope) to carry a narrowing-cast result, mapped to their
        (src_dtype, dst_dtype, source) provenance."""
        eqns, jaxpr = _eqns(jaxpr_like)
        narrow: dict = dict(narrow_in or {})
        for eqn in eqns:
            name = eqn.primitive.name
            params = eqn.params

            if name == "convert_element_type":
                self._note_cast(eqn, narrow)
            elif name in COLLECTIVE_CLASS:
                rec = self._record(eqn, context)
                self.records.append(rec)
                if rec.cls in REDUCTION_CLASSES:
                    for v in eqn.invars:
                        if id(v) in narrow:
                            src, dst, where = narrow[id(v)]
                            self.narrowing.append(
                                NarrowingCast(rec, src, dst, where)
                            )

            if name == "cond" and "branches" in params:
                self._walk_cond(eqn, context, narrow)
            elif name == "while":
                self._walk_while(eqn, context)
            else:
                self._walk_generic_subs(eqn, context, narrow)

    # -- helpers -------------------------------------------------------
    def _record(self, eqn, context) -> CollectiveRecord:
        dtypes, shapes = _avals(eqn)
        axes = _axes_of(eqn.params)
        cls = COLLECTIVE_CLASS[eqn.primitive.name]
        sizes = tuple(int(self._axis_env.get(a, 0)) for a in axes)
        payload = 0
        for dt, sh in zip(dtypes, shapes):
            n = 1
            for d in sh:
                n *= int(d)
            payload += n * np.dtype(dt).itemsize
        world = _world_of(sizes)
        return CollectiveRecord(
            primitive=eqn.primitive.name,
            cls=cls,
            axes=axes,
            dtypes=dtypes,
            shapes=shapes,
            context=context,
            detail=_detail_of(eqn.params),
            source=_source_of(eqn),
            axis_sizes=sizes,
            payload_bytes=payload,
            bytes_on_wire=wire_bytes(cls, payload, world),
            hop=hop_class(axes),
        )

    def _note_cast(self, eqn, narrow) -> None:
        inv = eqn.invars[0]
        outv = eqn.outvars[0]
        src = getattr(getattr(inv, "aval", None), "dtype", None)
        dst = getattr(getattr(outv, "aval", None), "dtype", None)
        if src is None or dst is None:
            return
        if np.dtype(dst).itemsize < np.dtype(src).itemsize:
            narrow[id(outv)] = (str(src), str(dst), _source_of(eqn))
        elif id(inv) in narrow:
            # widening a previously-narrowed value does not undo the
            # precision loss (int8 -> int32 before an integer psum is
            # still an int8 wire): provenance follows the value
            narrow[id(outv)] = narrow[id(inv)]

    def _walk_cond(self, eqn, context, narrow) -> None:
        self._cond_counter += 1
        cond_id = f"cond#{self._cond_counter}"
        sigs = []
        for i, branch in enumerate(eqn.params["branches"]):
            label = f"{cond_id}[{i}]"
            start = len(self.records)
            sub_narrow = self._map_into(eqn, branch, narrow,
                                        skip_leading=1)  # predicate
            self.walk(branch, context + (label,), sub_narrow)
            # branch-RELATIVE signatures: arms with identical
            # collective bodies must compare equal despite carrying
            # different branch labels in their absolute contexts — and
            # despite NESTED conds drawing different ids from the
            # global counter (arm 0's inner cond is cond#2, arm 1's
            # identical one cond#3), so the ids are stripped here; the
            # trace hash keeps them (the counter sequence is a
            # deterministic function of the program, so equal programs
            # still hash equal)
            sigs.append(tuple(
                _COND_ID_RE.sub("cond", r.signature(
                    context_from=len(context) + 1
                ))
                for r in self.records[start:]
            ))
        self.cond_reports.append(CondBranchReport(
            cond_id=cond_id,
            context=context,
            branch_signatures=tuple(sigs),
            source=_source_of(eqn),
        ))

    def _walk_while(self, eqn, context) -> None:
        """Trace a ``while`` eqn's cond/body and file a
        :class:`WhileReport` (the while half of the deadlock lint —
        PR 4 only analyzed ``cond`` arms)."""
        self._while_counter += 1
        wid = f"while#{self._while_counter}"
        params = eqn.params
        sigs, recs = {}, {}
        for key, lbl in (("cond_jaxpr", "while/cond"),
                         ("body_jaxpr", "while/body")):
            start = len(self.records)
            if key in params:
                self.walk(params[key], context + (lbl,))
            recs[key] = self.records[start:]
            # loop-relative signatures, nested-cond ids stripped (same
            # treatment as cond arms): informational, stable across
            # unrelated edits
            sigs[key] = tuple(
                _COND_ID_RE.sub("cond", r.signature(
                    context_from=len(context) + 1
                ))
                for r in recs[key]
            )
        cond_recs_reduce = any(
            r.cls == "all_reduce" for r in recs["cond_jaxpr"]
        )
        self.while_reports.append(WhileReport(
            while_id=wid,
            context=context,
            cond_signatures=sigs.get("cond_jaxpr", ()),
            body_signatures=sigs.get("body_jaxpr", ()),
            counter_only_predicate=_predicate_is_counter_only(params),
            cond_has_reduction=cond_recs_reduce,
            source=_source_of(eqn),
        ))

    def _walk_generic_subs(self, eqn, context, narrow) -> None:
        if eqn.primitive.name == "shard_map":
            mesh = eqn.params.get("mesh")
            shape = getattr(mesh, "shape", None)
            if shape:
                try:
                    self._axis_env.update(
                        {str(k): int(v) for k, v in dict(shape).items()}
                    )
                except Exception:
                    pass
        label_base = _CTX_LABELS.get(
            eqn.primitive.name, eqn.primitive.name
        )
        for key, val in eqn.params.items():
            vals = val if isinstance(val, (tuple, list)) else (val,)
            for i, sub in enumerate(vals):
                if not _is_jaxpr(sub):
                    continue
                label = (
                    label_base
                    if len(vals) == 1
                    else f"{label_base}:{key}[{i}]"
                )
                self.walk(
                    sub,
                    context + (label,),
                    self._map_into(eqn, sub, narrow),
                )

    @staticmethod
    def _map_into(eqn, sub, narrow, skip_leading: int = 0) -> dict:
        """Translate narrowing provenance across a sub-jaxpr boundary by
        positional invar alignment (exact for pjit / shard_map / cond
        branches; scan's const/carry/xs packing is skipped rather than
        guessed — a missed propagation under-reports, never
        mis-reports)."""
        if not narrow:
            return {}
        inner = getattr(sub, "jaxpr", sub)
        outer = list(eqn.invars)[skip_leading:]
        inner_vars = list(inner.invars)
        if len(outer) != len(inner_vars):
            return {}
        out = {}
        for o, s in zip(outer, inner_vars):
            if id(o) in narrow:
                out[id(s)] = narrow[id(o)]
        return out


def _predicate_is_counter_only(while_params) -> bool:
    """True when the ``while`` exit predicate reads ONLY carry slots the
    body advances by a constant (the ``fori_loop`` shape) — the trip
    count is then a pure function of loop-invariant inputs, which the
    lint assumes rank-uniform (the same assumption it makes for ``cond``
    predicates built from replicated values).

    Conservative in the safe direction: any slot the analysis cannot
    prove counter-like makes the predicate data-dependent.
    """
    try:
        cond_jaxpr = while_params["cond_jaxpr"].jaxpr
        body_jaxpr = while_params["body_jaxpr"].jaxpr
        cond_nconsts = int(while_params.get("cond_nconsts", 0))
        body_nconsts = int(while_params.get("body_nconsts", 0))
    except (KeyError, AttributeError):
        return False

    # vars the predicate transitively depends on, within the cond jaxpr
    needed = {id(v) for v in cond_jaxpr.outvars if not hasattr(v, "val")}
    for eqn in reversed(cond_jaxpr.eqns):
        if any(id(ov) in needed for ov in eqn.outvars):
            needed.update(
                id(iv) for iv in eqn.invars if not hasattr(iv, "val")
            )
    carry_in = list(cond_jaxpr.invars)[cond_nconsts:]
    read_slots = [i for i, v in enumerate(carry_in) if id(v) in needed]

    body_carry_in = list(body_jaxpr.invars)[body_nconsts:]
    body_consts = {id(v) for v in body_jaxpr.constvars}
    producers = {}
    for eqn in body_jaxpr.eqns:
        for ov in eqn.outvars:
            producers[id(ov)] = eqn

    def counter_like(slot: int) -> bool:
        if slot >= len(body_jaxpr.outvars) or slot >= len(body_carry_in):
            return False
        out = body_jaxpr.outvars[slot]
        src = body_carry_in[slot]
        if out is src:  # unchanged slot: loop-invariant value
            return True
        eqn = producers.get(id(out))
        if eqn is None or eqn.primitive.name not in ("add", "sub"):
            return False
        ids = [iv for iv in eqn.invars]
        has_self = any(iv is src for iv in ids)
        others_const = all(
            iv is src or hasattr(iv, "val") or id(iv) in body_consts
            for iv in ids
        )
        return has_self and others_const

    return all(counter_like(i) for i in read_slots)


def trace_jaxpr(jaxpr_like, label: str = "trace",
                axis_sizes=None) -> CollectiveTrace:
    """Walk an already-made (closed) jaxpr into a
    :class:`CollectiveTrace`.  ``axis_sizes`` seeds the cost model's
    mesh-axis sizes for programs whose jaxpr carries no shard_map mesh
    (every shard_map eqn's own mesh overrides the seed)."""
    w = _Walker(axis_sizes=axis_sizes)
    w.walk(jaxpr_like)
    return CollectiveTrace(
        records=tuple(w.records),
        narrowing_casts=tuple(w.narrowing),
        cond_reports=tuple(w.cond_reports),
        label=label,
        while_reports=tuple(w.while_reports),
    )


def trace_collectives(fn: Callable, *args, label: Optional[str] = None,
                      axis_sizes=None, **kwargs) -> CollectiveTrace:
    """Trace ``fn(*args, **kwargs)`` to its ordered collective sequence.

    ``fn`` is anything jax can trace: a plain function, a jitted train
    step, a ``shard_map``-wrapped body, or an eager communicator method
    whose dispatch is built from cached jit programs (the jaxpr then
    contains ``pjit`` eqns that the walker descends into).  Args may be
    arrays or ``jax.ShapeDtypeStruct``\\ s — only shapes/dtypes matter.

    Nothing is compiled or executed; no collective runs.
    """
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    return trace_jaxpr(
        jaxpr, label=label or getattr(fn, "__name__", "trace"),
        axis_sizes=axis_sizes,
    )

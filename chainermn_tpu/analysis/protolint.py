"""protolint — the host-protocol analyzer: exchange-site catalog.

mnlint (:mod:`.lint`) guards the *compiled* collective surface; this
module gives the HOST protocol — the obj-store exchanges, hand-assigned
tags, and shared-FS atomic writes the serving/elastic/peer-ckpt tiers
coordinate through — the same three-layer treatment:

1. **This AST pass**: walk ``chainermn_tpu/`` and extract every
   host-side exchange into a :class:`ProtocolCatalog` —
   ``lockstep_allgather(site=...)`` agreement sites, raw
   ``send_obj``/``recv_obj`` calls with their tags,
   ``bcast_obj``/``gather_obj``/``allgather_obj`` collectives, and
   tmp+rename JSON manifest writers — then enforce the catalog rules
   below.
2. **SPMD-determinism lint**: :mod:`.lint`'s ``--host-protocol`` rules
   (``spmd-hash`` / ``spmd-unsorted-scan`` / ``spmd-random``) over the
   modules that feed cross-rank decisions.
3. **Runtime guard**: :mod:`chainermn_tpu.resilience.protocol` +
   :func:`~chainermn_tpu.analysis.checks.protocol_agreement`.

Catalog rules (rule ids; pragma escape ``# mnlint: allow(<rule>)``)
-------------------------------------------------------------------
``proto-duplicate-site``
    Agreement site names must be globally unique across the package:
    two ``lockstep_allgather`` call sites sharing one literal ``site=``
    make retries, recorded protocols, and error messages ambiguous
    about WHICH exchange tore.  F-string sites count as dynamic
    prefixes (``prefix*``) and are exempt from uniqueness (they embed
    a discriminator by construction).

``proto-raw-allgather``
    Every agreement-shaped allgather rides ``lockstep_allgather``: a
    raw ``allgather_obj`` call outside ``resilience/retry.py`` (the
    wrapper itself) / ``communicators/_obj_store.py`` (the transport)
    is an error — it would skip the lockstep retry AND the protocol
    recorder's site naming.

``proto-magic-tag``
    Every ``send_obj``/``recv_obj`` tag must be the default (0) or
    resolve to the central registry (``resilience/tags.py`` — a name
    imported from it, or a call to one of its helpers).  Tag literals
    and arithmetic (the old ``PEER_TAG + 1 + o``) are errors, as are
    module-level ``*_TAG = <int>`` constants outside the registry:
    reserved ranges must be DECLARED where overlap is checked.

``proto-adhoc-manifest``
    A function that both ``json.dump``\\ s and ``os.rename``/
    ``os.replace``\\ s is an ad-hoc atomic manifest writer; outside
    ``resilience/elastic.py`` (``write_manifest`` — the sanctioned
    one) it is an error, so the tmp-suffix/fsync/commit semantics
    cannot fork per call site.

Run it (also folded into ``python -m chainermn_tpu.analysis.lint
--host-protocol`` and the tier-1 repo gate)::

    python -m chainermn_tpu.analysis.protolint
"""

from __future__ import annotations

import ast
import os
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .lint import (
    Violation,
    _allowed,
    _iter_py_files,
    _module_aliases,
    repo_root,
)

# files sanctioned for raw allgather_obj: the lockstep wrapper itself
# and the transport layer beneath it
RAW_ALLGATHER_SANCTIONED = (
    "chainermn_tpu/resilience/retry.py",
    "chainermn_tpu/communicators/_obj_store.py",
    "chainermn_tpu/communicators/communicator_base.py",
)

# the one sanctioned atomic-JSON-manifest writer
MANIFEST_SANCTIONED = ("chainermn_tpu/resilience/elastic.py",)

# the registry itself may declare integer tag constants
TAGS_MODULE = "chainermn_tpu/resilience/tags.py"

# call names the catalog keys on (the fleet worker's _lockstep_allgather
# wrapper forwards to the real one, so its call sites carry the literal
# site strings the catalog must see)
LOCKSTEP_CALLS = frozenset({"lockstep_allgather", "_lockstep_allgather"})
P2P_CALLS = frozenset({"send_obj", "recv_obj"})
COLLECTIVE_OBJ_CALLS = frozenset({"bcast_obj", "gather_obj",
                                  "allgather_obj", "exchange_obj"})


@dataclass(frozen=True)
class ExchangeSite:
    """One cataloged host-side exchange."""

    path: str               # repo-relative
    line: int
    kind: str               # lockstep | send | recv | exchange |
    #                         atomic_write | tag_constant
    site: Optional[str] = None   # resolved site name; "prefix*" for
    #                              f-strings; None when unresolvable
    dynamic: bool = False        # site not a compile-time literal
    tag: Optional[str] = None        # rendered tag expression
    tag_source: Optional[str] = None  # default | registry | literal | expr

    def __str__(self) -> str:
        bits = [self.kind]
        if self.site is not None:
            bits.append(f"site={self.site}")
        if self.tag is not None:
            bits.append(f"tag={self.tag}({self.tag_source})")
        return f"{self.path}:{self.line}: " + " ".join(bits)


@dataclass
class ProtocolCatalog:
    """Every host-side exchange the AST pass found."""

    sites: List[ExchangeSite]

    def by_kind(self, kind: str) -> List[ExchangeSite]:
        return [s for s in self.sites if s.kind == kind]

    def lockstep_sites(self) -> List[ExchangeSite]:
        return self.by_kind("lockstep")

    def site_names(self) -> List[str]:
        """Resolved (non-dynamic) agreement site names, sorted."""
        return sorted(s.site for s in self.lockstep_sites()
                      if not s.dynamic and s.site is not None)

    def __len__(self) -> int:
        return len(self.sites)

    def render(self) -> str:
        lines = [f"ProtocolCatalog: {len(self.sites)} exchange site(s)"]
        for s in sorted(self.sites, key=lambda s: (s.path, s.line)):
            lines.append("  " + str(s))
        return "\n".join(lines)


# ----------------------------------------------------------------------
# per-file extraction
# ----------------------------------------------------------------------
def _module_str_constants(tree: ast.AST) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` bindings — how most agreement
    sites are spelled (``REPLICATE_SITE = "peer_ckpt.replicate"``)."""
    out: Dict[str, str] = {}
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Constant
        ) and isinstance(node.value.value, str):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.value.value
    return out


def _tags_bindings(tree: ast.AST) -> Tuple[frozenset, frozenset]:
    """(names imported FROM resilience.tags, names bound to the tags
    MODULE) — what a registry-resolved tag expression may reference."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.split(".")[-1] == "tags":
                for a in node.names:
                    names.add(a.asname or a.name)
    mods = _module_aliases(tree, "tags")
    return frozenset(names), frozenset(mods)


def _classify_site(node: Optional[ast.expr],
                   consts: Dict[str, str]) -> Tuple[Optional[str], bool]:
    """Resolve a ``site=`` expression: (name, dynamic)."""
    if node is None:
        return None, True
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, False
    if isinstance(node, ast.Name) and node.id in consts:
        return consts[node.id], False
    if isinstance(node, ast.JoinedStr):
        prefix = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                prefix.append(v.value)
            else:
                break
        return "".join(prefix) + "*", True
    return None, True


def _classify_tag(node: Optional[ast.expr], tag_names: frozenset,
                  tag_mods: frozenset) -> Tuple[str, Optional[str]]:
    """Resolve a ``tag=`` expression: (source, rendered).

    ``source``: ``default`` (absent / literal 0), ``registry`` (a name
    imported from resilience.tags, an attribute of the tags module, or
    a call to either), ``literal`` (any other int constant), ``expr``
    (arithmetic / anything else)."""
    if node is None:
        return "default", None
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        if node.value == 0:
            return "default", "0"
        return "literal", repr(node.value)
    target = node.func if isinstance(node, ast.Call) else node
    if isinstance(target, ast.Name) and target.id in tag_names:
        return "registry", ast.unparse(node)
    if isinstance(target, ast.Attribute) and isinstance(
        target.value, ast.Name
    ) and target.value.id in tag_mods:
        return "registry", ast.unparse(node)
    return "expr", ast.unparse(node)


def _kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _call_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _tag_constant_assigns(tree: ast.AST):
    """Module-level ``X_TAG = <int>`` / ``TAG_X = <int>`` assigns — a
    hand-reserved tag outside the registry."""
    for node in ast.iter_child_nodes(tree):
        if not (isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Constant
        ) and isinstance(node.value.value, int)
                and not isinstance(node.value.value, bool)):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and (
                t.id.endswith("_TAG") or t.id.startswith("TAG_")
            ):
                yield node.lineno, t.id, node.value.value


def _atomic_write_functions(tree: ast.AST):
    """Functions containing BOTH a ``json.dump`` call and an
    ``os.rename``/``os.replace`` call — ad-hoc atomic JSON writers.
    Keyed on the ``json`` module specifically (alias-tracked):
    ``pickle.dump`` + rename is a binary payload commit, not a
    manifest, and stays out of this rule."""
    json_names = _module_aliases(tree, "json") | frozenset({"json"})
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        dump_line = None
        renames = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name == "dump" and isinstance(
                node.func, ast.Attribute
            ) and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in json_names:
                dump_line = dump_line or node.lineno
            elif name in ("rename", "replace") and isinstance(
                node.func, ast.Attribute
            ):
                renames = True
        if dump_line is not None and renames:
            yield dump_line, fn.name


def scan_file(path: str, root: str
              ) -> Tuple[List[ExchangeSite], List[Violation]]:
    """Extract one file's exchange sites and its per-file violations
    (everything except cross-file site uniqueness)."""
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    try:
        with open(path, encoding="utf-8") as f:
            src = f.read()
    except (OSError, UnicodeDecodeError):
        return [], []
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        return [], [Violation(rel, e.lineno or 0, "syntax",
                              f"file does not parse: {e.msg}")]
    lines = src.splitlines()
    consts = _module_str_constants(tree)
    tag_names, tag_mods = _tags_bindings(tree)
    sites: List[ExchangeSite] = []
    out: List[Violation] = []

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name in LOCKSTEP_CALLS:
                site, dynamic = _classify_site(_kwarg(node, "site"),
                                               consts)
                sites.append(ExchangeSite(rel, node.lineno, "lockstep",
                                          site=site, dynamic=dynamic))
            elif name in P2P_CALLS:
                kind = "send" if name == "send_obj" else "recv"
                # tag may also arrive positionally: send_obj(obj, dest,
                # tag) / recv_obj(source, tag)
                tag_node = _kwarg(node, "tag")
                if tag_node is None:
                    pos = 2 if name == "send_obj" else 1
                    if len(node.args) > pos:
                        tag_node = node.args[pos]
                source, rendered = _classify_tag(tag_node, tag_names,
                                                 tag_mods)
                sites.append(ExchangeSite(rel, node.lineno, kind,
                                          tag=rendered,
                                          tag_source=source))
                if source in ("literal", "expr") and not _allowed(
                    lines, node.lineno, "proto-magic-tag"
                ):
                    out.append(Violation(
                        rel, node.lineno, "proto-magic-tag",
                        f"{name} tag {rendered!r} does not resolve to "
                        "the central registry; declare a reserved "
                        "range in resilience/tags.py and import it",
                    ))
            elif name in COLLECTIVE_OBJ_CALLS:
                sites.append(ExchangeSite(rel, node.lineno, "exchange",
                                          site=name))
                if name == "allgather_obj" and rel not in \
                        RAW_ALLGATHER_SANCTIONED and not _allowed(
                            lines, node.lineno, "proto-raw-allgather"):
                    out.append(Violation(
                        rel, node.lineno, "proto-raw-allgather",
                        "raw allgather_obj outside the lockstep "
                        "wrapper/transport: agreement-shaped "
                        "exchanges must ride resilience.retry."
                        "lockstep_allgather(site=...) so torn "
                        "payloads retry on all ranks together",
                    ))

    if rel != TAGS_MODULE:
        for lineno, cname, value in _tag_constant_assigns(tree):
            sites.append(ExchangeSite(rel, lineno, "tag_constant",
                                      tag=f"{cname}={value}",
                                      tag_source="literal"))
            if not _allowed(lines, lineno, "proto-magic-tag"):
                out.append(Violation(
                    rel, lineno, "proto-magic-tag",
                    f"hand-reserved tag constant {cname} = {value} "
                    "outside resilience/tags.py; register the range "
                    "there so overlap is checked at import",
                ))

    for lineno, fname in _atomic_write_functions(tree):
        sites.append(ExchangeSite(rel, lineno, "atomic_write",
                                  site=fname))
        if rel not in MANIFEST_SANCTIONED and not _allowed(
            lines, lineno, "proto-adhoc-manifest"
        ):
            out.append(Violation(
                rel, lineno, "proto-adhoc-manifest",
                f"{fname}() hand-rolls an atomic JSON write "
                "(json.dump + rename); route through "
                "resilience.elastic.write_manifest so the commit "
                "semantics cannot fork per call site",
            ))
    return sites, out


# ----------------------------------------------------------------------
# cross-file rules + drivers
# ----------------------------------------------------------------------
def _lines_of(path: str) -> List[str]:
    try:
        with open(path, encoding="utf-8") as f:
            return f.read().splitlines()
    except (OSError, UnicodeDecodeError):
        return []


def default_targets(root: Optional[str] = None) -> List[str]:
    """The package only: tests construct divergent protocols on
    purpose, and benchmarks/examples exchange through the package's
    audited call sites."""
    root = root or repo_root()
    return [os.path.join(root, "chainermn_tpu")]


def run_protolint(paths: Optional[Sequence[str]] = None,
                  root: Optional[str] = None
                  ) -> Tuple[ProtocolCatalog, List[Violation]]:
    """Build the catalog over ``paths`` (default: the package) and
    return it with every catalog-rule violation."""
    root = root or repo_root()
    targets = list(paths) if paths else default_targets(root)
    sites: List[ExchangeSite] = []
    out: List[Violation] = []
    for t in targets:
        for f in _iter_py_files(t):
            s, v = scan_file(f, root)
            sites.extend(s)
            out.extend(v)
    # global site-name uniqueness (literal/resolved sites only; dynamic
    # f-string prefixes discriminate by construction)
    by_name: Dict[str, List[ExchangeSite]] = {}
    for s in sites:
        if s.kind == "lockstep" and not s.dynamic and s.site:
            by_name.setdefault(s.site, []).append(s)
    for name, dupes in sorted(by_name.items()):
        if len(dupes) <= 1:
            continue
        spots = ", ".join(f"{d.path}:{d.line}" for d in dupes)
        for d in dupes:
            if _allowed(_lines_of(os.path.join(root, d.path)),
                        d.line, "proto-duplicate-site"):
                continue
            out.append(Violation(
                d.path, d.line, "proto-duplicate-site",
                f"agreement site {name!r} is declared at multiple "
                f"call sites ({spots}); site names must be globally "
                "unique so retries and recorded protocols are "
                "unambiguous",
            ))
    return ProtocolCatalog(sites), sorted(
        set(out), key=lambda v: (v.path, v.line, v.rule)
    )


def build_catalog(paths: Optional[Sequence[str]] = None,
                  root: Optional[str] = None) -> ProtocolCatalog:
    return run_protolint(paths, root)[0]


def catalog_violations(paths: Optional[Sequence[str]] = None,
                       root: Optional[str] = None) -> List[Violation]:
    """What ``analysis.lint --host-protocol`` folds into the gate."""
    return run_protolint(paths, root)[1]


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    catalog, violations = run_protolint(argv or None)
    print(catalog.render())
    for v in violations:
        print(v)
    if violations:
        print(f"protolint: {len(violations)} violation(s)",
              file=sys.stderr)
        return 1
    print("protolint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Static analysis of the collective surface.

SPMD correctness hinges on every rank tracing the same ordered sequence
of collectives; collective count/dtype/ordering are also the
communication-performance levers (PAPERS.md: DynamiQ, multi-node
inference comm studies).  This package makes both first-class:

* :mod:`.trace` — walk any jittable function's closed jaxpr (through
  ``pjit``/``scan``/``cond``/``while``/``shard_map``) into an ordered
  :class:`CollectiveTrace`;
* :mod:`.checks` — the check catalog: cross-process divergence guard
  (:func:`trace_agreement`), deadlock lint on data-dependent ``cond``
  branches, mesh-axis audit, narrowing-cast wire audit, budget
  enforcement, and the ordering-aware overlap check
  (:func:`check_overlap` — every wire bucket psum issued at its
  dependency frontier, the ``comm_wire.overlap`` contract);
* :mod:`.hlo` — the lowered-text census the trace cross-checks against,
  plus per-op extraction with XLA metadata (the attribution citations);
* :mod:`.shardflow` — the sharding-flow pass: propagate PartitionSpecs
  through the jaxpr, record every :class:`ReshardSite` where the SPMD
  partitioner must insert communication — joined with the HLO census
  into the ``implicit_collectives`` check (every lowered collective is
  matched to an authored record or flagged with an equation citation);
* :mod:`.memory` — live-range per-rank HBM estimation (params + grads +
  opt state + activation peak, remat-aware) with ceilings pinned in
  :mod:`.budgets` (``enforce_memory``);
* :mod:`.budgets` — pinned per-program collective AND per-rank HBM
  ceilings;
* :mod:`.lint` — the repo AST gate
  (``python -m chainermn_tpu.analysis.lint``; ``--host-protocol`` adds
  the SPMD-determinism rules and the protolint catalog rules);
* :mod:`.protolint` — the HOST-protocol analyzer: catalog every
  obj-store exchange site/tag/atomic-write (``ProtocolCatalog``) and
  enforce site uniqueness, lockstep-wrapped allgathers, registry-
  resolved tags, and the single sanctioned manifest writer.  Its
  runtime twin is :func:`checks.protocol_agreement` over
  :mod:`chainermn_tpu.resilience.protocol`'s recorder, raising
  ``ProtocolDivergenceError`` on every rank before a divergent host
  protocol can deadlock.

Every :class:`CollectiveRecord` additionally carries the cost model the
comm_wire planner consumes: ``bytes_on_wire`` (ring-algorithm per-rank
wire bytes) and ``hop`` (inter/intra/flat link class from the
hierarchical ``mn_inter``/``mn_intra`` axis naming).

The divergence guard is production-wired: ``build_train_step``'s first
dispatch in a multi-process world exchanges the trace hash and raises
``CollectiveTraceMismatchError`` before any collective runs (see
docs/static_analysis.md).
"""

from .trace import (  # noqa: F401
    COLLECTIVE_CLASS,
    CollectiveRecord,
    CollectiveTrace,
    CondBranchReport,
    NarrowingCast,
    WhileReport,
    hop_class,
    trace_collectives,
    trace_jaxpr,
    wire_bytes,
)
from .checks import (  # noqa: F401
    CollectiveBudgetError,
    Finding,
    ImplicitCollectiveError,
    assert_attributed,
    assert_within_budget,
    attribute_collectives,
    check_axes,
    check_deadlocks,
    check_implicit_collectives,
    check_overlap,
    check_wire,
    implicit_agreement,
    protocol_agreement,
    run_all,
    trace_agreement,
)
from .hlo import (  # noqa: F401
    HloCollectiveOp,
    assert_census_agreement,
    hlo_census,
    hlo_collective_ops,
    lowered_census,
)
from .budgets import (  # noqa: F401
    BUDGETS,
    HBM_BUDGETS,
    MemoryBudgetError,
    budget_for,
    enforce,
    enforce_memory,
    memory_budget_for,
)
from .shardflow import (  # noqa: F401
    ReshardSite,
    ShardFlowReport,
    shardflow,
    shardflow_jaxpr,
)
from .memory import (  # noqa: F401
    MemoryEstimate,
    estimate_hbm,
    estimate_jaxpr_hbm,
    train_step_memory,
)

# re-exported so `except analysis.CollectiveTraceMismatchError` works at
# the place the guard is documented (ProtocolDivergenceError likewise,
# for the host-protocol guard)
from ..resilience.errors import (  # noqa: F401
    CollectiveTraceMismatchError,
    ProtocolDivergenceError,
)

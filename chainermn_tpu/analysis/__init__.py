"""Static analysis of the collective surface.

SPMD correctness hinges on every rank tracing the same ordered sequence
of collectives; collective count/dtype/ordering are also the
communication-performance levers (PAPERS.md: DynamiQ, multi-node
inference comm studies).  This package makes both first-class:

* :mod:`.trace` — walk any jittable function's closed jaxpr (through
  ``pjit``/``scan``/``cond``/``while``/``shard_map``) into an ordered
  :class:`CollectiveTrace`;
* :mod:`.checks` — the check catalog: cross-process divergence guard
  (:func:`trace_agreement`), deadlock lint on data-dependent ``cond``
  branches, mesh-axis audit, narrowing-cast wire audit, and budget
  enforcement;
* :mod:`.hlo` — the lowered-text census the trace cross-checks against;
* :mod:`.budgets` — pinned per-program collective ceilings;
* :mod:`.lint` — the repo AST gate
  (``python -m chainermn_tpu.analysis.lint``).

The divergence guard is production-wired: ``build_train_step``'s first
dispatch in a multi-process world exchanges the trace hash and raises
``CollectiveTraceMismatchError`` before any collective runs (see
docs/static_analysis.md).
"""

from .trace import (  # noqa: F401
    COLLECTIVE_CLASS,
    CollectiveRecord,
    CollectiveTrace,
    CondBranchReport,
    NarrowingCast,
    trace_collectives,
    trace_jaxpr,
)
from .checks import (  # noqa: F401
    CollectiveBudgetError,
    Finding,
    assert_within_budget,
    check_axes,
    check_deadlocks,
    check_wire,
    run_all,
    trace_agreement,
)
from .hlo import (  # noqa: F401
    assert_census_agreement,
    hlo_census,
    lowered_census,
)
from .budgets import BUDGETS, budget_for, enforce  # noqa: F401

# re-exported so `except analysis.CollectiveTraceMismatchError` works at
# the place the guard is documented
from ..resilience.errors import CollectiveTraceMismatchError  # noqa: F401

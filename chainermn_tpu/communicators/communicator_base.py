"""Abstract communicator API.

Reference parity: ``chainermn/communicators/communicator_base.py``
(``CommunicatorBase`` — properties ``rank``/``size``/``intra_rank``/
``intra_size``/``inter_rank``/``inter_size``; collectives ``bcast``,
``allreduce``, ``send``, ``recv``, ``gather``, ``allgather``, ``alltoall``,
``split``; pickled ``*_obj`` variants; model-level ``bcast_data`` and
``allreduce_grad``).

TPU-native redesign
-------------------
ChainerMN is MPI-SPMD: every *rank* is a separate process holding its own
array, and a collective is a blocking call into mpi4py/NCCL.  JAX on TPU is
single-controller SPMD: one Python process drives many chips, arrays are
*global* (sharded across a ``jax.sharding.Mesh``), and collectives are XLA
ops (``psum``/``all_gather``/``ppermute``/``all_to_all``) compiled into a
program that runs on every chip over ICI.

The eager API therefore works on **stacked arrays**: an array whose leading
axis is the rank axis, sharded one-slice-per-chip over the communicator's
mesh.  ``x[r]`` is "rank r's value".  ``allreduce(x)`` returns a stacked
array in which every slice holds the reduction — exactly the post-state of
``MPI_Allreduce`` across ranks.  This keeps ChainerMN's per-rank semantics
testable in one process while the hot path (see ``optimizers.py``) stays
fully compiled.

Two tiers (SURVEY.md section 7):

* *Compiled tier*: training steps are jitted; gradient sync is ``psum`` over
  ``comm.axis_names`` inside the program.  This is the performance path.
* *Eager tier* (this API): each collective is a tiny cached-jit program over
  the same mesh — the ChainerMN-shaped escape hatch and test surface.
"""

from __future__ import annotations

import pickle
from typing import Any, Callable, Mapping, Optional, Sequence

import jax
import numpy as np

# Reductions supported by `allreduce`.  ChainerMN's MPI backend exposes sum
# (and mean via allreduce_grad's divide); we add the other XLA reductions.
REDUCE_OPS = ("sum", "mean", "max", "min", "prod")


class CommunicatorBase:
    """Abstract base class of all communicators.

    Concrete subclasses implement the array collectives; object (pickle)
    transport and model-level helpers are implemented here on top of them.
    """

    def __init__(self, topology):
        self._topology = topology
        self._obj_store = None  # set by subclasses / factory

    # ------------------------------------------------------------------
    # Rank model (parity: CommunicatorBase properties)
    # ------------------------------------------------------------------
    @property
    def topology(self):
        return self._topology

    @property
    def devices(self) -> tuple:
        return self._topology.devices

    @property
    def size(self) -> int:
        """Number of chips in this communicator (ChainerMN: #processes)."""
        return self._topology.size

    @property
    def platform(self) -> str:
        """Backend platform of this communicator's devices.  Always passed
        explicitly to process queries so creating a communicator over CPU
        devices never initializes (or blocks on) an accelerator backend."""
        return self.devices[0].platform if self.devices else "cpu"

    @property
    def process_index(self) -> int:
        return jax.process_index(backend=self.platform)

    @property
    def process_count(self) -> int:
        return jax.process_count(backend=self.platform)

    @property
    def rank(self) -> int:
        """Rank of *this controller process's first device*.

        In single-controller SPMD one process owns every rank, so "my rank"
        is not unique the way it is under MPI.  For data-loading decisions
        (the main use of ``comm.rank`` in ChainerMN scripts) the meaningful
        quantity is the process index; ``local_ranks`` gives the full set.
        """
        pid = self.process_index
        for i, d in enumerate(self.devices):
            if d.process_index == pid:
                return i
        return 0

    @property
    def local_ranks(self) -> tuple:
        """Ranks whose devices are addressable from this process."""
        pid = self.process_index
        return tuple(
            i for i, d in enumerate(self.devices) if d.process_index == pid
        )

    @property
    def intra_rank(self) -> int:
        return self._topology.intra_ranks[self.rank]

    @property
    def intra_size(self) -> int:
        return self._topology.intra_sizes[self.rank] if self.size else 0

    @property
    def inter_rank(self) -> int:
        return self._topology.inter_ranks[self.rank]

    @property
    def inter_size(self) -> int:
        return self._topology.inter_size

    def world_descriptor(self) -> dict:
        """JSON-able description of this communicator's world, written
        into checkpoint manifests (the elastic-restart contract,
        ``resilience.elastic``: a resumed world whose descriptor differs
        from the manifest routes the restore through the resharder).
        ``world_size`` is the chip count the collectives span — what
        ZeRO state blocks shard over; ``mesh_axes`` records the axis
        factorization (the hierarchical ``mn_inter``/``mn_intra`` pair
        re-derives from the surviving topology on a resize)."""
        try:
            axes = {
                str(k): int(v) for k, v in dict(self.mesh.shape).items()
            }
        except Exception:
            axes = {}
        return {
            "world_size": int(self.size),
            "process_count": int(self.process_count),
            "mesh_axes": axes,
        }

    # ------------------------------------------------------------------
    # Array collectives (abstract; stacked-array semantics)
    # ------------------------------------------------------------------
    def allreduce(self, x, op: str = "sum"):
        """Stacked (size, ...) -> stacked (size, ...), every slice = reduce."""
        raise NotImplementedError

    def bcast(self, x, root: int = 0):
        """Stacked (size, ...) -> stacked; every slice = x[root]."""
        raise NotImplementedError

    def gather(self, x, root: int = 0):
        """Stacked (size, ...) -> (size, ...) materialized on root's device."""
        raise NotImplementedError

    def allgather(self, x):
        """Stacked (size, ...) -> (size, ...) replicated on every device."""
        raise NotImplementedError

    def scatter(self, x, root: int = 0):
        """(size, ...) on root -> stacked (size, ...), one slice per rank."""
        raise NotImplementedError

    def alltoall(self, x):
        """Stacked (size, size, ...); out[j, i] = in[i, j]."""
        raise NotImplementedError

    def send(self, x, dest: int, source: int):
        """Move slice ``source`` of a stacked array to rank ``dest``.

        Unlike MPI there is no ambient "my rank", so the source is explicit.
        Returns a stacked array whose ``dest`` slice holds the payload.
        """
        raise NotImplementedError

    def recv(self, x, source: int, dest: int):
        """Transpose view of :meth:`send`; provided for API parity."""
        return self.send(x, dest=dest, source=source)

    def reduce_scatter(self, x, op: str = "sum"):
        """Stacked (size, n) -> stacked; slice r = reduce of column-block r."""
        raise NotImplementedError

    def barrier(self) -> None:
        """Synchronize all processes (no-op within one controller).

        Resilience: an injected or transient pre-barrier fault is
        absorbed by the bounded retry schedule (the late rank simply
        joins the rendezvous on its retry); exhaustion raises a
        recoverable ``TransientCommError`` instead of wedging forever.
        """
        from chainermn_tpu.resilience.retry import resilient_call

        if self.process_count > 1:
            from jax.experimental import multihost_utils

            resilient_call(
                "barrier",
                lambda: multihost_utils.sync_global_devices(
                    "chainermn_tpu_barrier"
                ),
            )
        else:
            resilient_call("barrier", lambda: None)

    # ------------------------------------------------------------------
    # split (parity: CommunicatorBase.split via mpi_comm.Split)
    # ------------------------------------------------------------------
    def split(self, colors: Sequence[int], keys: Optional[Sequence[int]] = None
              ) -> Mapping[int, "CommunicatorBase"]:
        """Partition into sub-communicators.

        ChainerMN's ``split(color, key)`` is called with per-process scalars;
        under a single controller the caller holds *all* ranks, so colors is
        a length-``size`` sequence and the result is ``{color: sub_comm}``
        covering every group (each sub-communicator is fully usable since all
        devices are addressable).
        """
        raise NotImplementedError

    # ------------------------------------------------------------------
    # Object (control-plane) transport — parity: send_obj/recv_obj/
    # bcast_obj/gather_obj/allreduce_obj (pickled, chunked MPI messages).
    # On TPU these ride the host control plane, never ICI.
    # ------------------------------------------------------------------
    def send_obj(self, obj: Any, dest: int, tag: int = 0) -> None:
        self._obj_store.send(obj, dest=dest, tag=tag)

    def recv_obj(self, source: int, tag: int = 0, dest: Optional[int] = None
                 ) -> Any:
        """Receive a pickled message.

        ``dest`` names the receiving rank.  Under MPI that is implicitly the
        calling process; under a single controller every rank lives here, so
        it is an explicit argument (default: rank 0 / this process).
        """
        if dest is None:
            dest = 0 if self.process_count == 1 else None
        kw = {} if dest is None else {"dest": dest}
        return self._obj_store.recv(source=source, tag=tag, **kw)

    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        return self._obj_store.bcast(obj, root=root)

    def gather_obj(self, obj: Any, root: int = 0) -> list:
        return self._obj_store.gather(obj, root=root)

    def allgather_obj(self, obj: Any) -> list:
        return self._obj_store.allgather(obj)

    def allreduce_obj(self, obj: Any, op: Callable = None) -> Any:
        objs = self._obj_store.allgather(obj)
        if op is None:
            out = objs[0]
            for o in objs[1:]:
                out = out + o
            return out
        return op(objs)

    # ------------------------------------------------------------------
    @property
    def sync_seed(self) -> int:
        """A seed every rank/process of this communicator agrees on.

        Parity: the seed-broadcast of the synchronized iterator
        (chainermn/iterators/_synchronized_iterator.py).  Agreed once per
        communicator (process 0's draw wins under multi-process); anything
        built from the same communicator shares the same stream.
        """
        if getattr(self, "_sync_seed", None) is None:
            import numpy as _np

            seed = int(_np.random.randint(0, 2**31 - 1))
            self._sync_seed = int(self.bcast_obj(seed, root=0))
        return self._sync_seed

    # ------------------------------------------------------------------
    # Model-level helpers (parity: bcast_data / allreduce_grad)
    # ------------------------------------------------------------------
    def bcast_data(self, tree):
        """Replicate a parameter pytree across every device of this
        communicator (parity: ``bcast_data(model)`` — initial weight sync).

        Under multi-process, additionally broadcasts process 0's values so
        all controllers agree bit-for-bit.
        """
        from jax.sharding import NamedSharding, PartitionSpec

        if self.process_count > 1:
            from jax.experimental import multihost_utils

            tree = multihost_utils.broadcast_one_to_all(tree)
        sharding = NamedSharding(self.mesh, PartitionSpec())
        return jax.device_put(tree, sharding)

    def allreduce_grad(self, grads, *, mean: bool = True):
        """Average a pytree of *stacked* gradients over the rank axis.

        Parity: ``CommunicatorBase.allreduce_grad(model)`` — the data-parallel
        gradient sync.  The compiled path does this inside the jitted train
        step (see ``optimizers.py``); this eager form exists for
        ChainerMN-shaped scripts and tests.
        """
        op = "mean" if mean else "sum"
        return jax.tree_util.tree_map(lambda g: self.allreduce(g, op=op), grads)

    # `mesh` is provided by concrete XLA-backed subclasses; declared here so
    # helpers above can rely on it.
    @property
    def mesh(self):
        raise NotImplementedError

    @property
    def axis_names(self) -> tuple:
        """Mesh axis names to ``psum`` over for a full-communicator reduce."""
        raise NotImplementedError

    # ------------------------------------------------------------------
    def finalize(self) -> None:
        """Release resources (parity: MPI communicator teardown)."""

    def __repr__(self):
        return (
            f"<{type(self).__name__} size={self.size} "
            f"inter={self.inter_size}x{self.intra_size if self.size else 0}>"
        )


def dumps(obj: Any) -> bytes:
    """Pickle helper shared by object-transport backends (parity:
    chunked-pickle protocol of ``mpi_communicator_base.py``)."""
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def loads(data: bytes) -> Any:
    return pickle.loads(data)

"""Concrete communicator built on XLA collectives over a device mesh.

Reference parity: ``chainermn/communicators/mpi_communicator_base.py``
(``MpiCommunicatorBase`` — the shared implementation under all production
communicators).  Where MpiCommunicatorBase dispatched into mpi4py/NCCL, this
class lowers every collective to an XLA op (``psum`` / ``all_gather`` /
``all_to_all`` / ``ppermute``) via ``jax.shard_map`` over a
``jax.sharding.Mesh`` — so the "backend" is the XLA compiler and the wires
are ICI/DCN, with no MPI anywhere.

Subclass differences (flat / hierarchical / two-dimensional / tpu) are pure
*mesh factorizations*: the same collectives over differently shaped meshes,
which is exactly how XLA maps a multi-axis reduction onto the physical
torus.  That collapses the reference's five hand-written allreduce
algorithms (hierarchical reduce->MPI->bcast etc.) into mesh geometry the
compiler schedules.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .communicator_base import CommunicatorBase
from ._obj_store import create_obj_store
from ._topology import Topology
from ..observability import timeline as _obs
from ..resilience.retry import resilient_call

_REDUCERS = {
    "sum": lax.psum,
    "mean": lambda x, ax: lax.pmean(x, ax),
    "max": lax.pmax,
    "min": lax.pmin,
}


def _linear_rank(axis_names: tuple, mesh_shape: dict):
    """Flattened rank of the executing shard across ``axis_names``."""
    r = jnp.int32(0)
    for name in axis_names:
        r = r * mesh_shape[name] + lax.axis_index(name)
    return r


class XlaCommunicatorBase(CommunicatorBase):
    """Eager-tier collectives on stacked arrays over an XLA mesh.

    ``allreduce_grad_dtype`` mirrors PureNcclCommunicator's reduced-precision
    gradient reduction (pure_nccl_communicator.py: pack -> cast fp16 ->
    ncclAllReduce -> scale + cast back): here the cast/reduce/scale is one
    fused XLA program — no hand-written CUDA kernels needed.
    """

    # mesh axis names, outermost first; subclasses override factorization
    def __init__(
        self,
        devices: Optional[Sequence] = None,
        allreduce_grad_dtype=None,
        *,
        wire_schedule: str = "auto",
        _topology: Optional[Topology] = None,
    ):
        if _topology is None:
            if devices is None:
                devices = jax.devices()
            _topology = Topology.create(devices)
        super().__init__(_topology)
        self._allreduce_grad_dtype = (
            jnp.dtype(allreduce_grad_dtype)
            if allreduce_grad_dtype is not None
            else None
        )
        # eager-tier schedule knob (the analogue of the compiled wire's
        # WireConfig.schedule): "auto" lets the cost model stage
        # qualifying allreduce_grad buckets onto the multi-hop program
        # on hierarchical meshes, "flat" pins the single-psum baseline
        # (bit-compat with pre-schedule releases — the staged reduction
        # reassociates the summation tree), "hier_rs_ag" forces staging
        # wherever the mesh supports it.
        from ..comm_wire.schedules import GRAD_SCHEDULES

        if wire_schedule not in ("auto",) + GRAD_SCHEDULES:
            raise ValueError(
                f"unknown wire_schedule {wire_schedule!r}; one of "
                f"{('auto',) + GRAD_SCHEDULES}"
            )
        self._wire_schedule = wire_schedule
        self._mesh = self._build_mesh()
        self._obj_store = create_obj_store(
            self.size, self.process_count,
            rank_to_process=tuple(d.process_index for d in self.devices),
        )
        self._stack_spec = P(self.axis_names)
        self._stack_sharding = NamedSharding(self._mesh, self._stack_spec)

    # -- mesh construction --------------------------------------------
    def _build_mesh(self) -> Mesh:
        """Default: one flat axis over all chips (subclasses refactorize)."""
        return Mesh(np.array(self.devices, dtype=object), ("mn",))

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def axis_names(self) -> tuple:
        return self._mesh.axis_names

    @property
    def stack_sharding(self) -> NamedSharding:
        """Sharding of a stacked (rank-leading) array on this communicator."""
        return self._stack_sharding

    @property
    def allreduce_grad_dtype(self):
        return self._allreduce_grad_dtype

    # -- helpers -------------------------------------------------------
    def _shard(self, f, n_stacked_args: int = 1, out_replicated: bool = False):
        spec = self._stack_spec
        in_specs = tuple([spec] * n_stacked_args)
        out_specs = P() if out_replicated else spec
        return jax.jit(
            jax.shard_map(
                f, mesh=self._mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False,
            )
        )

    def _put(self, x):
        x = jnp.asarray(x)
        if x.ndim == 0 or x.shape[0] != self.size:
            raise ValueError(
                f"stacked array must have leading axis == size ({self.size}); "
                f"got shape {x.shape}"
            )
        return jax.device_put(x, self._stack_sharding)

    @functools.cached_property
    def _allreduce_fns(self):
        axes = self.axis_names
        fns = {}
        for op, red in _REDUCERS.items():
            fns[op] = self._shard(functools.partial(lambda r, x: r(x, axes), red))
        return fns

    # -- collectives ---------------------------------------------------
    # Every public eager collective is an instrumented resilience site
    # ("collective.<name>"): with no injector active the wrapper is one
    # ``is None`` check (the BENCH_* hot path is unchanged); with one
    # active, injected pre-dispatch faults are deterministic,
    # call-count-addressed, and absorbed by the retry schedule.
    def allreduce(self, x, op: str = "sum"):
        if op == "prod":
            # XLA has no pprod; exp/sum/log would lose sign — use allgather.
            g = self.allgather(x)
            return self._put(jnp.broadcast_to(jnp.prod(g, axis=0), jnp.shape(x)))
        # telemetry span with per-rank payload bytes (the stacked array
        # carries every rank's row; one row is what each rank reduces);
        # measured mode forces completion so the span is a latency, not
        # an async dispatch — disabled path dispatches exactly as before
        nbytes = getattr(x, "nbytes", None)
        with _obs.span(
            "collective.allreduce", op=op,
            bytes=(int(nbytes) // self.size) if nbytes else None,
        ):
            out = resilient_call(
                "collective.allreduce",
                lambda: self._allreduce_fns[op](self._put(x)),
            )
            if _obs.active() is not None:
                jax.block_until_ready(out)
        return out

    @functools.cached_property
    def _hier_split(self):
        """The mesh's (inter, intra) axis split, or None on flat /
        degenerate meshes — the input to every eager schedule choice
        (``comm_wire.schedules``)."""
        from ..comm_wire import axis_split, mesh_axis_sizes

        axes = self.axis_names
        return axis_split(axes, mesh_axis_sizes(self._mesh, axes))

    @functools.cached_property
    def _bcast_fn(self):
        # bcast_tree schedule (ISSUE 11): on a hierarchical mesh the
        # single flat masked psum becomes a two-stage multicast tree —
        # masked psum over mn_inter (root -> one leader per slice: the
        # payload crosses the DCN-class links once per slice), then
        # masked psum over mn_intra (leader -> slice, ICI).  The staged
        # sum only adds zeros to the payload, so the result is
        # bit-identical to the flat spelling; flat meshes (and the
        # width-1-inter ragged fallback) keep the one-stage form.
        from ..comm_wire import bcast_tree_stages, mesh_axis_sizes

        axes, shape = self.axis_names, dict(self._mesh.shape)
        stages = bcast_tree_stages(axes, mesh_axis_sizes(self._mesh, axes))

        def f(x, root):
            me = _linear_rank(axes, shape)
            masked = jnp.where(me == root, x, jnp.zeros_like(x))
            for stage_axes in stages:
                masked = lax.psum(masked, stage_axes)
            return masked

        spec = self._stack_spec
        return jax.jit(
            jax.shard_map(
                f, mesh=self._mesh, in_specs=(spec, P()), out_specs=spec,
                check_vma=False,
            )
        )

    def bcast(self, x, root: int = 0):
        return resilient_call(
            "collective.bcast",
            lambda: self._bcast_fn(self._put(x), jnp.int32(root)),
        )

    @functools.cached_property
    def _allgather_fn(self):
        axes = self.axis_names

        def f(x):
            g = x
            for ax in reversed(axes):  # innermost first => rank-ordered
                g = lax.all_gather(g, ax, axis=0, tiled=True)
            return g

        return self._shard(f, out_replicated=True)

    def allgather(self, x):
        return resilient_call(
            "collective.allgather",
            lambda: self._allgather_fn(self._put(x)),
        )

    def gather(self, x, root: int = 0):
        g = self.allgather(x)
        return jax.device_put(g, self.devices[root])

    def scatter(self, x, root: int = 0):
        del root  # stacked representation: scatter = reshard one-per-rank
        return resilient_call(
            "collective.scatter", lambda: self._put(jnp.asarray(x))
        )

    @functools.cached_property
    def _alltoall_fn(self):
        axes = self.axis_names
        sizes = [dict(self._mesh.shape)[a] for a in axes]

        def f(x):  # per-shard (1, size, ...)
            y = x
            # Successive per-axis all_to_alls over the flattened rank axis:
            # split my row (axis 1) across the axis, concat on axis 0.
            # Processing axes outermost-first keeps each split contiguous
            # w.r.t. the linear-rank column layout.
            for ax in axes:
                y = lax.all_to_all(y, ax, split_axis=1, concat_axis=0,
                                   tiled=True)
            # Received blocks stack with the earliest-processed axis digit
            # varying fastest: axis0 index = sum_i d_i * prod_{j<i} n_j.
            # Unscramble to linear rank order (d_0 outermost).
            if len(axes) > 1:
                k = len(sizes)
                y = y.reshape(tuple(reversed(sizes)) + y.shape[1:])
                perm = tuple(reversed(range(k))) + tuple(
                    range(k, y.ndim)
                )
                y = y.transpose(perm).reshape((-1,) + y.shape[k:])
            return y  # (size, 1, ...): y[i, 0] = what rank i sent to me

        spec = self._stack_spec
        return jax.jit(
            jax.shard_map(
                f, mesh=self._mesh,
                in_specs=(spec,),
                out_specs=P(None, self.axis_names),
                check_vma=False,
            )
        )

    def alltoall(self, x):
        x = jnp.asarray(x)
        if x.ndim < 2 or x.shape[0] != self.size or x.shape[1] != self.size:
            raise ValueError(
                f"alltoall expects (size, size, ...); got {x.shape}"
            )
        out = resilient_call(
            "collective.alltoall",
            lambda: self._alltoall_fn(
                jax.device_put(x, self._stack_sharding)
            ),
        )
        # out[j, i] currently equals in[i, j] with (recv_rank, sender) layout
        # transposed into (sender, recv_rank); swap back to stacked-by-rank.
        return jnp.swapaxes(out, 0, 1)

    @functools.cached_property
    def _ppermute_fn(self):
        axes, shape = self.axis_names, dict(self._mesh.shape)

        def f(x, src, dst):
            # Keep only the source slice, broadcast it (masked psum — a
            # bcast-rooted-at-src), then mask down to the destination.  A
            # true neighbor ppermute p2p lives in functions/point_to_point
            # (single-axis rings); the eager stacked form must be correct
            # for *any* mesh factorization, which mask+psum is.
            me = _linear_rank(axes, shape)
            keep = jnp.where(me == src, x, jnp.zeros_like(x))
            everywhere = lax.psum(keep, axes)
            return jnp.where(me == dst, everywhere, jnp.zeros_like(x))

        spec = self._stack_spec
        return jax.jit(
            jax.shard_map(
                f, mesh=self._mesh, in_specs=(spec, P(), P()),
                out_specs=spec, check_vma=False,
            )
        )

    def send(self, x, dest: int, source: int):
        """out[dest] = x[source]; other slices zero."""
        return resilient_call(
            "collective.send",
            lambda: self._ppermute_fn(
                self._put(x), jnp.int32(source), jnp.int32(dest)
            ),
            peer=dest,
        )

    @functools.cached_property
    def _reduce_scatter_fns(self):
        axes = self.axis_names
        fns = {}
        for op in ("sum", "mean"):
            def f(x, _op=op):  # per-shard (1, n)
                y = lax.psum_scatter(
                    jnp.squeeze(x, 0), axes[-1] if len(axes) == 1 else axes,
                    scatter_dimension=0, tiled=True,
                )
                if _op == "mean":
                    y = y / len(self.devices)
                return y[None]
            fns[op] = self._shard(f)
        return fns

    def reduce_scatter(self, x, op: str = "sum"):
        x = jnp.asarray(x)
        if x.ndim != 2 or x.shape[1] % self.size:
            raise ValueError(
                f"reduce_scatter expects (size, k*size); got {x.shape}"
            )
        return resilient_call(
            "collective.reduce_scatter",
            lambda: self._reduce_scatter_fns[op](self._put(x)),
        )

    # -- split ---------------------------------------------------------
    def split(self, colors, keys=None):
        colors = list(colors)
        if len(colors) != self.size:
            raise ValueError(
                f"split needs one color per rank ({self.size}); got "
                f"{len(colors)}"
            )
        if keys is None:
            keys = list(range(self.size))
        groups: dict = {}
        for rank, color in enumerate(colors):
            if color is None or color < 0:  # MPI_UNDEFINED analogue
                continue
            groups.setdefault(color, []).append((keys[rank], rank))
        out = {}
        for color, members in groups.items():
            members.sort()
            devs = [self.devices[r] for _, r in members]
            out[color] = _SplitCommunicator(
                devices=devs, allreduce_grad_dtype=self._allreduce_grad_dtype
            )
        return out

    # -- reduced-precision gradient reduction --------------------------
    @functools.cached_property
    def _allreduce_grad_cast_fns(self):
        axes = self.axis_names
        comm_dtype = self._allreduce_grad_dtype
        fns = {}
        for op in ("sum", "mean"):
            def f(g, _op=op):
                # cast -> reduce -> cast back -> mean-scale, one fused
                # program (parity: pure_nccl_communicator.py fp16
                # pack/scale kernels).  The divide runs AFTER the cast
                # back: the psum result is already off the wire, so
                # dividing in comm_dtype would only add a second
                # low-precision rounding.
                orig = g.dtype
                r = lax.psum(g.astype(comm_dtype), axes).astype(orig)
                return r / len(self.devices) if _op == "mean" else r

            fns[op] = self._shard(f)
        return fns

    @functools.cached_property
    def _allreduce_grad_hier_fns(self):
        """Eager multi-hop bucket reduction (``hier_rs_ag``,
        comm_wire.schedules): full-precision ``psum_scatter`` over the
        intra (ICI) axis, the ``allreduce_grad_dtype`` cast applied to
        the inter (DCN-class) hop only, intra ``all_gather`` — the
        eager analogue of the compiled wire's staged schedule.  Only
        built on meshes with a genuine (inter, intra) split."""
        split = self._hier_split
        dt = self._allreduce_grad_dtype
        size = self.size
        fns = {}
        for op in ("sum", "mean"):
            def f(x, _op=op):  # per-shard (1, cols)
                row = jnp.squeeze(x, 0)
                cols = row.shape[0]
                pad = (-cols) % split.intra_size
                rp = jnp.pad(row, (0, pad)) if pad else row
                local = lax.psum_scatter(
                    rp, split.intra, scatter_dimension=0, tiled=True
                )
                w = local if dt is None else local.astype(dt)
                summed = lax.psum(w, (split.inter,))
                r = summed.astype(row.dtype)
                if _op == "mean":
                    r = r / size
                out = lax.all_gather(
                    r, split.intra, axis=0, tiled=True
                )
                return out[:cols][None]

            fns[op] = self._shard(f)
        return fns

    def allreduce_grad(self, grads, *, mean: bool = True):
        """Bucketed eager gradient allreduce on stacked arrays.

        The leaves are packed (per rank) into the deterministic wire
        bucket plan and each bucket ships through ONE compiled
        collective program — the eager tier's analogue of the compiled
        path's flat wire (one launch per bucket instead of per leaf,
        and a bounded number of cached jit programs).  On a
        hierarchical mesh, buckets the cost model stages (ISSUE 11 —
        ``schedule_for_bucket``) ride the multi-hop rs→ar→ag program
        instead of the flat psum; the per-rank arithmetic is the same
        mean with the wire cast moved to the inter hop only.
        """
        from .. import comm_wire as _cw

        leaves, treedef = jax.tree_util.tree_flatten(grads)
        if not leaves:
            return grads
        op = "mean" if mean else "sum"
        fn = (
            self._allreduce_fns[op]
            if self._allreduce_grad_dtype is None
            else self._allreduce_grad_cast_fns[op]
        )
        # plan on the PER-RANK portion of each stacked leaf (the wire
        # payload each rank contributes)
        per_rank = [l[0] if hasattr(l, "shape") and np.ndim(l) else l
                    for l in leaves]
        plan = _cw.make_plan(per_rank)
        split = self._hier_split

        def bucket_fn(b):
            """``(compiled program, schedule)`` for one bucket — flat
            psum, or the staged hier program when the communicator's
            ``wire_schedule`` knob (default "auto": the cost model)
            schedules it — a pure function of bucket bytes + mesh +
            knob, so every process picks the same program.  The
            schedule rides the telemetry span so ``attribute()`` can
            pair a staged bucket's span with its rs→ar→ag record
            TRIPLE instead of mis-pairing it as one all_reduce."""
            if split is None or self._wire_schedule == "flat":
                return fn, "flat"
            payload = int(b.size) * np.dtype(b.dtype).itemsize
            if _cw.schedule_for_bucket(
                payload, self._mesh, axes=self.axis_names,
                requested=self._wire_schedule,
            ) == "hier_rs_ag":
                return self._allreduce_grad_hier_fns[op], "hier_rs_ag"
            return fn, "flat"

        def run():
            # telemetry: per-bucket wire.ship / collective.psum spans
            # with per-rank bucket bytes — the measured half of
            # ``observability.attribute``'s join against the static
            # trace's bucket psum records.  Observer effect, disclosed:
            # with telemetry active each bucket's reduction is forced
            # to completion inside its span (a latency, not an async
            # dispatch), serializing what the unobserved run pipelines;
            # the DISABLED path below is byte-identical to before.
            tel = _obs.active()
            if tel is None:
                packed = _cw.pack_stacked(plan, leaves, self.size)
                # pipelined bucket round-trips (ISSUE 8 satellite):
                # stage EVERY bucket's device placement before
                # dispatching the first reduction, so bucket k+1's send
                # is in flight while bucket k reduces (jax dispatch is
                # async — interleaving put/reduce per bucket serialized
                # the transfers behind each reduction's dispatch).
                # Reduction order and arithmetic are unchanged:
                # bit-identical to the serial schedule.
                staged = [self._put(cat) for cat in packed]
                red = [
                    bucket_fn(plan.buckets[k])[0](s)
                    for k, s in enumerate(staged)
                ]
            else:
                with _obs.span("collective.allreduce_grad",
                               buckets=plan.n_buckets):
                    with _obs.span("wire.pack", buckets=plan.n_buckets):
                        packed = _cw.pack_stacked(plan, leaves, self.size)
                    staged = []
                    for k, cat in enumerate(packed):
                        with _obs.span("wire.ship", bucket=k):
                            staged.append(self._put(cat))
                    red = []
                    for k, s in enumerate(staged):
                        b = plan.buckets[k]
                        f, sched = bucket_fn(b)
                        args = dict(
                            bucket=k,
                            bytes=b.size * np.dtype(b.dtype).itemsize,
                        )
                        if sched == "hier_rs_ag":
                            # the span covers the WHOLE staged triple:
                            # disclose the schedule + each leg's EXACT
                            # operand bytes as the hier program issues
                            # them — rs on the intra-padded native
                            # bucket, ar on the wire-dtype-cast shard,
                            # ag on the native shard — so attribute()
                            # pairs the span with the bucket's
                            # rs->ar->ag records byte-exactly instead
                            # of mis-pricing it as one psum
                            native = np.dtype(b.dtype).itemsize
                            wire_i = (
                                native
                                if self._allreduce_grad_dtype is None
                                else np.dtype(
                                    self._allreduce_grad_dtype
                                ).itemsize
                            )
                            shard = -(-int(b.size) // split.intra_size)
                            padded = shard * split.intra_size
                            args["schedule"] = sched
                            args["rs_bytes"] = padded * native
                            args["ar_bytes"] = shard * wire_i
                            args["ag_bytes"] = shard * native
                        with _obs.span("collective.psum", **args):
                            r = f(s)
                            jax.block_until_ready(r)
                        red.append(r)
            out = _cw.unpack_stacked(
                plan, red, [jnp.shape(l) for l in leaves]
            )
            return jax.tree_util.tree_unflatten(treedef, out)

        return resilient_call("collective.allreduce_grad", run)


class _SplitCommunicator(XlaCommunicatorBase):
    """Sub-communicator produced by :meth:`XlaCommunicatorBase.split`."""

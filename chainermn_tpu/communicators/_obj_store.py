"""Host-side object (control-plane) transport.

Reference parity: the ``*_obj`` methods of
``chainermn/communicators/mpi_communicator_base.py`` (pickle + chunked MPI
send with a ~256 MB cap per message).

TPU-native redesign: object traffic is *control plane*, not ICI traffic.

* Single controller (``jax.process_count() == 1``): every rank lives in this
  process, so transport is an in-memory mailbox.  ``send_obj``/``recv_obj``
  still round-trip through pickle so that anything a multi-process run would
  reject (unpicklable payloads) fails identically in tests.
* Multi-process: rides ``jax.experimental.multihost_utils`` (which uses the
  jax.distributed KV store / host collectives underneath).  Rank-addressed
  send/recv between processes maps onto the distributed KV store.
"""

from __future__ import annotations

import collections
from typing import Any

import jax
import numpy as np

from .communicator_base import dumps, loads
from ..observability import timeline as _obs
from ..resilience import fault_injection as _fi
from ..resilience import protocol as _proto
from ..resilience import tags as _tags
from ..resilience.errors import PayloadCorruptionError
from ..resilience.retry import RetryPolicy, call_with_retry

# Chunk cap mirroring the reference's max message length for pickled sends
# (mpi_communicator_base.py, ~256 MB).  Applies to the KV-store path.
MAX_OBJ_CHUNK_BYTES = 256 * 1024 * 1024


def _recv_timeout_ms() -> int:
    """TOTAL blocking-recv budget for the KV-store path, split across the
    retry policy's attempts.  A peer that died never publishes its key; a
    bounded wait turns that into a ``TransientCommError`` (naming the
    peer, attempts, and elapsed time) the global except hook can contain
    instead of a 10-minute hang."""
    import os

    return int(os.environ.get("CHAINERMN_TPU_OBJ_TIMEOUT_MS", 600_000))


def _obj_policy() -> RetryPolicy:
    """Retry policy for host-side exchanges (bounded attempts, jitter-free
    exponential backoff — deterministic for tests)."""
    import os

    return RetryPolicy(
        max_attempts=int(
            os.environ.get("CHAINERMN_TPU_OBJ_MAX_ATTEMPTS", 4)
        )
    )


def _maybe_fault(site: str, peer=None, payload: Any = None) -> Any:
    """Injection point with retry: with no injector active this is one
    ``is None`` check; with one active, injected transient timeouts are
    absorbed by the (deterministic) retry schedule and the possibly
    mutated payload (truncation faults) is returned."""
    if _fi.active() is None:
        return payload
    return call_with_retry(
        lambda: _fi.fire(site, peer=peer, payload=payload),
        site=site, peer=peer, policy=_obj_policy(),
    )


def _loads_checked(data: bytes, site: str, peer=None) -> Any:
    """Unpickle with taxonomy: a truncated / torn payload surfaces as a
    recoverable :class:`PayloadCorruptionError`, not a bare pickle error."""
    try:
        return loads(data)
    except Exception as e:
        raise PayloadCorruptionError(
            f"{site}: payload failed to unpickle "
            f"({type(e).__name__}: {e})",
            site=site, peer=peer,
        ) from e


def _check_rank(value: int, size: int, name: str) -> None:
    if not 0 <= value < size:
        raise ValueError(f"{name} {value} out of range for size {size}")


class LocalObjStore:
    """In-process mailbox — all ranks share one controller."""

    def __init__(self, size: int):
        self._size = size
        self._mail: dict = collections.defaultdict(collections.deque)

    def send(self, obj: Any, dest: int, tag: int = _tags.DEFAULT) -> None:
        _check_rank(dest, self._size, "dest")
        with _obs.span("obj_store.send", peer=dest) as sp:
            payload = _maybe_fault("obj_store.send", peer=dest,
                                   payload=dumps(obj))
            sp.set(bytes=len(payload))
            self._mail[(dest, tag)].append(payload)
            _proto.record_op("send", tag=tag, peer=dest, payload=payload)

    def recv(self, source: int, tag: int = _tags.DEFAULT,
             dest: int = 0) -> Any:
        """Drain the mailbox of rank ``dest``.

        Under one controller there is no ambient "my rank", so the receiving
        rank is an explicit argument (default 0 mirrors the common
        root-receives pattern).  ``source`` is accepted for MPI-shaped parity
        but not matched on: messages to one rank form a single FIFO per tag,
        exactly like MPI_ANY_SOURCE.
        """
        del source
        _check_rank(dest, self._size, "dest")
        with _obs.span("obj_store.recv", peer=dest) as sp:
            _maybe_fault("obj_store.recv", peer=dest)
            box = self._mail[(dest, tag)]
            if not box:
                raise RuntimeError(
                    f"recv_obj: no message pending for rank {dest}/tag "
                    f"{tag} (single-controller recv must follow the "
                    "matching send)"
                )
            payload = box.popleft()
            sp.set(bytes=len(payload))
            # local recv has no ambient "my rank": the mailbox owner
            # (dest) stands in as the recorded peer
            _proto.record_op("recv", tag=tag, peer=dest, payload=payload)
            return _loads_checked(payload, "obj_store.recv", dest)

    def recv_for(self, dest: int, tag: int = _tags.DEFAULT) -> Any:
        return self.recv(source=-1, tag=tag, dest=dest)

    def bcast(self, obj: Any, root: int = 0) -> Any:
        # single controller: every rank's payload is this caller's payload,
        # so any in-range root broadcasts the same object
        _check_rank(root, self._size, "root")
        with _obs.span("obj_store.exchange", peer=root) as sp:
            payload = _maybe_fault("obj_store.exchange", peer=root,
                                   payload=dumps(obj))
            sp.set(bytes=len(payload))
            _proto.record_op("exchange", payload=payload)
            return _loads_checked(payload, "obj_store.exchange", root)

    def gather(self, obj: Any, root: int = 0) -> list:
        _check_rank(root, self._size, "root")
        with _obs.span("obj_store.exchange", peer=root) as sp:
            payload = _maybe_fault("obj_store.exchange", peer=root,
                                   payload=dumps(obj))
            sp.set(bytes=len(payload))
            _proto.record_op("exchange", payload=payload)
            return [_loads_checked(payload, "obj_store.exchange", root)
                    for _ in range(self._size)]

    def allgather(self, obj: Any) -> list:
        with _obs.span("obj_store.exchange") as sp:
            payload = _maybe_fault("obj_store.exchange",
                                   payload=dumps(obj))
            sp.set(bytes=len(payload))
            _proto.record_op("exchange", payload=payload)
            return [_loads_checked(payload, "obj_store.exchange")
                    for _ in range(self._size)]


class MultiprocessObjStore:
    """Cross-process object transport over the jax.distributed control plane.

    Collective ops (bcast/gather/allgather) use ``multihost_utils`` host
    collectives on the pickled payload; addressed send/recv uses the
    KV store exposed by the distributed client.
    """

    def __init__(self, size: int, rank_to_process=None):
        self._size = size
        self._seq = collections.Counter()
        # rank -> owning process index (from the topology's device order);
        # lets collective roots be expressed as *ranks*, as in the
        # reference's MPI world where rank == process.
        self._rank_to_process = (
            tuple(rank_to_process) if rank_to_process is not None else None
        )

    def _root_process(self, root: int) -> int:
        """Process index owning rank ``root``."""
        _check_rank(root, self._size, "root")
        if self._rank_to_process is None:
            # Without a topology, rank == process is only a safe reading
            # when the world has exactly one rank per process; guessing
            # otherwise would silently pick the wrong payload.
            if self._size != jax.process_count():
                raise ValueError(
                    f"root rank {root} cannot be mapped to a process "
                    "(no rank->process topology; pass rank_to_process)"
                )
            return root
        return self._rank_to_process[root]

    # -- collectives ---------------------------------------------------
    def _host_allgather_bytes(self, payload: bytes) -> list:
        """Host-collective byte exchange.

        The retryable part is the injection point, which fires BEFORE
        the collective: a rank whose injected transient fault precedes
        the exchange simply joins late on its retry — peers block in the
        collective until it arrives (tail latency, not deadlock).  The
        real ``process_allgather`` is deliberately NOT retried: a
        one-sided transient failure (rank A's receive times out after
        rank B's call already returned) would make A's retry pair with
        B's *next* exchange, silently shifting the collective stream by
        one message.  Addressed KV-store recv (idempotent reads) keeps
        the full real-failure retry path; a genuinely failed collective
        propagates as an error for auto-resume to handle.
        """
        from jax.experimental import multihost_utils

        with _obs.span("obj_store.exchange", bytes=len(payload)):
            p = _maybe_fault("obj_store.exchange", payload=payload)
            nproc = jax.process_count()
            n = len(p)
            # Single-round fast path: one fixed 4 KiB bucket carries an
            # in-band 8-byte length header plus the payload.  The fixed
            # SHAPE means process_allgather compiles exactly one XLA
            # program for every small exchange ever (compiling per
            # byte-length costs ~100 ms a shape, and two rounds —
            # lengths then payload — doubles the collective latency
            # that dominates sub-second recovery).  Only when some
            # rank's payload spills past the bucket do all ranks — each
            # reading the same gathered headers — agree to run a second
            # power-of-two-bucketed round with the full payloads.
            hdr = 8
            r1 = 4096
            buf = np.zeros((r1,), np.uint8)
            buf[:hdr] = np.frombuffer(
                np.int64(n).tobytes(), np.uint8
            )
            body = min(n, r1 - hdr)
            buf[hdr:hdr + body] = np.frombuffer(p[:body], np.uint8)
            g1 = multihost_utils.process_allgather(buf)
            lengths = [
                int(np.frombuffer(g1[q, :hdr].tobytes(), np.int64)[0])
                for q in range(nproc)
            ]
            maxlen = max(lengths)
            if maxlen <= r1 - hdr:
                out = [
                    g1[q, hdr:hdr + lengths[q]].tobytes()
                    for q in range(nproc)
                ]
            else:
                bucket = max(1 << max(maxlen - 1, 0).bit_length(), r1)
                buf2 = np.zeros((bucket,), np.uint8)
                arr = np.frombuffer(p, np.uint8)
                buf2[: arr.size] = arr
                g2 = multihost_utils.process_allgather(buf2)
                out = [
                    g2[q, : lengths[q]].tobytes() for q in range(nproc)
                ]
            # recorded on transport SUCCESS only (a lockstep retry
            # re-records on every rank together, so attempt counts
            # stay symmetric); the digest is this rank's contribution
            _proto.record_op("exchange", payload=payload)
            return out

    def bcast(self, obj: Any, root: int = 0) -> Any:
        """Every process returns the payload contributed by the process
        owning rank ``root`` (an honest arbitrary-root broadcast: the
        underlying transport is an allgather, so selecting the root's
        payload costs nothing extra)."""
        src = self._root_process(root)
        payloads = self._host_allgather_bytes(dumps(obj))
        return _loads_checked(payloads[src], "obj_store.exchange", src)

    def allgather(self, obj: Any) -> list:
        return [
            _loads_checked(p, "obj_store.exchange", i)
            for i, p in enumerate(self._host_allgather_bytes(dumps(obj)))
        ]

    def gather(self, obj: Any, root: int = 0) -> list:
        """Process-ordered list of every process's payload.

        MPI's gather delivers the list only at ``root``; the host-side
        transport here is an allgather, so every process receives it — a
        documented superset (content identical at root).  ``root`` is
        still validated so out-of-range ranks fail loudly."""
        self._root_process(root)
        return self.allgather(obj)

    # -- addressed send/recv over the KV store -------------------------
    def _kv(self):
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "multi-process obj transport requires jax.distributed."
                "initialize()"
            )
        return client

    def send(self, obj: Any, dest: int, tag: int = _tags.DEFAULT) -> None:
        with _obs.span("obj_store.send", peer=dest) as sp:
            self._send(obj, dest, tag, sp)

    def _send(self, obj: Any, dest: int, tag: int, sp) -> None:
        payload = _maybe_fault("obj_store.send", peer=dest,
                               payload=dumps(obj))
        sp.set(bytes=len(payload))
        key = f"cmn_obj/{jax.process_index()}->{dest}/{tag}/{self._seq[(dest, tag)]}"
        self._seq[(dest, tag)] += 1
        client = self._kv()

        def kv_set(k, v):
            # allow_overwrite: a retry after a PARTIALLY successful
            # publish re-sets keys that already exist; without it the
            # coordination service raises ALREADY_EXISTS and the retry
            # layer would convert a recoverable transient failure into a
            # hard crash.  The payload for a given (key, seq) is
            # deterministic, so overwriting is value-identical.
            try:
                client.key_value_set_bytes(k, v, allow_overwrite=True)
            except TypeError:  # jaxlib without the kwarg
                client.key_value_set_bytes(k, v)

        def publish():
            for i in range(0, max(len(payload), 1), MAX_OBJ_CHUNK_BYTES):
                kv_set(f"{key}/{i}", payload[i : i + MAX_OBJ_CHUNK_BYTES])
            kv_set(f"{key}/len", str(len(payload)).encode())

        call_with_retry(publish, site="obj_store.send", peer=dest,
                        policy=_obj_policy())
        _proto.record_op("send", tag=tag, peer=dest, payload=payload)

    def recv(self, source: int, tag: int = _tags.DEFAULT,
             dest: int = None) -> Any:
        if dest is not None and dest != jax.process_index():
            raise ValueError(
                f"multi-process recv_obj can only receive for this process "
                f"(index {jax.process_index()}), got dest={dest}"
            )
        key = f"cmn_obj/{source}->{jax.process_index()}/{tag}/{self._seq[('r', source, tag)]}"
        self._seq[("r", source, tag)] += 1
        client = self._kv()
        policy = _obj_policy()
        # the env timeout is the TOTAL wait budget across all attempts
        # AND all chunk gets: every blocking get's timeout is capped by
        # the remaining budget (a deadline, not a per-get slice), so a
        # dead peer mid-multi-chunk-payload still errors near the
        # configured bound instead of budget x chunks later
        import time as _time

        per_attempt = max(_recv_timeout_ms() // policy.max_attempts, 1)
        deadline = _time.monotonic() + _recv_timeout_ms() / 1000.0

        def bounded_get(k):
            remaining = int((deadline - _time.monotonic()) * 1000)
            return client.blocking_key_value_get_bytes(
                k, max(min(per_attempt, remaining), 1)
            )

        def attempt():
            _fi.fire("obj_store.recv", peer=source)
            total = int(bounded_get(f"{key}/len"))
            payload = b"".join(
                bounded_get(f"{key}/{i}")
                for i in range(0, max(total, 1), MAX_OBJ_CHUNK_BYTES)
            )
            return payload[:total]

        with _obs.span("obj_store.recv", peer=source) as sp:
            data = call_with_retry(attempt, site="obj_store.recv",
                                   peer=source, policy=policy)
            sp.set(bytes=len(data))
        _proto.record_op("recv", tag=tag, peer=source, payload=data)
        return _loads_checked(data, "obj_store.recv", source)


def create_obj_store(size: int, process_count: int = 1,
                     rank_to_process=None):
    if process_count > 1:
        return MultiprocessObjStore(size, rank_to_process=rank_to_process)
    return LocalObjStore(size)

"""Named communicator variants.

Reference parity: the communicator zoo of ``chainermn/communicators/`` —
``naive_communicator.py``, ``flat_communicator.py``,
``hierarchical_communicator.py``, ``two_dimensional_communicator.py``,
``single_node_communicator.py``, ``pure_nccl_communicator.py``,
``non_cuda_aware_communicator.py``, ``dummy_communicator.py``.

TPU-native redesign: in the reference each variant hand-writes a different
allreduce *algorithm* (NCCL reduce -> host MPI -> NCCL bcast, etc.).  On TPU
the algorithm belongs to XLA; what a variant legitimately controls is the
**mesh factorization** — how ranks map onto ICI axes and the DCN axis — plus
host-staging/no-op behaviors for the testing variants.  So:

* ``tpu`` / ``pure_nccl``  -> one flat mesh axis; collectives stay on
  ICI end-to-end (analogue of a single NCCL ring spanning all ranks).
* ``hierarchical``         -> 2-D (inter, intra) mesh: intra = chips in a
  slice (ICI), inter = slices (DCN); a psum over both axes compiles to the
  intra-reduce / inter-exchange / intra-bcast schedule the reference coded
  by hand.
* ``two_dimensional``      -> near-square 2-D factorization of the chips via
  ``mesh_utils.create_device_mesh`` so both axes ride ICI torus dimensions
  (bandwidth-optimal multi-ring, the reference's reduce-scatter/allgather
  two-level scheme).
* ``single_node``          -> flat mesh, asserts one slice.
* ``naive``                -> pure NumPy host loop, no mesh required; the
  CPU-only portability/testing backend.
* ``flat``                 -> flat mesh (reference: one big CUDA-aware MPI
  allreduce ≙ one flat XLA allreduce).
* ``non_cuda_aware``       -> host-staged: device->host, NumPy reduce,
  host->device.  Exists for parity/testing; never the fast path.
* ``dummy``                -> full pack/cast/unpack but no exchange —
  measures communication-free upper bound, as in the reference.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .communicator_base import CommunicatorBase
from ._obj_store import create_obj_store
from ._topology import Topology
from .xla_communicator_base import XlaCommunicatorBase
from ..observability import timeline as _obs


class TpuCommunicator(XlaCommunicatorBase):
    """Flat ICI communicator — the production default.

    Parity: ``PureNcclCommunicator`` (pure_nccl_communicator.py): one
    collective domain spanning every chip with no host hop in the data path.
    """


class FlatCommunicator(XlaCommunicatorBase):
    """Parity: ``FlatCommunicator`` (flat_communicator.py)."""


class SingleNodeCommunicator(XlaCommunicatorBase):
    """Parity: ``SingleNodeCommunicator`` (single_node_communicator.py):
    asserts the job spans exactly one node/slice."""

    def __init__(self, devices=None, allreduce_grad_dtype=None, **kw):
        super().__init__(devices, allreduce_grad_dtype, **kw)
        if self.inter_size != 1:
            raise ValueError(
                "SingleNodeCommunicator requires all chips in one "
                f"slice/node; topology has inter_size={self.inter_size}"
            )


class HierarchicalCommunicator(XlaCommunicatorBase):
    """Two-level (inter x intra) mesh.

    Parity: ``HierarchicalCommunicator`` (hierarchical_communicator.py).
    The reference's explicit intra-NCCL-reduce -> inter-MPI-allreduce ->
    intra-NCCL-bcast pipeline is here a single ``psum`` over the
    ('mn_inter', 'mn_intra') axes — XLA schedules the reduction
    hierarchically along the mesh, with the intra axis on ICI and the inter
    axis on DCN.

    The axis pair is also the substrate of the AUTHORED multi-hop
    schedules (``comm_wire.schedules``, ISSUE 11): the gradient wire's
    ``hier_rs_ag`` buckets stage a full-precision intra reduce-scatter,
    a codec-compressed inter all-reduce on the 1/K shard, and an intra
    all-gather; the eager ``bcast`` lowers as the two-stage
    ``bcast_tree`` multicast (inter root->leaders, intra
    leaders->slices) instead of one flat masked psum; and the eager
    ``allreduce_grad`` routes cost-model-qualified buckets through the
    staged program.  On the ragged fallback below the width-1
    ``mn_inter`` axis disqualifies every staged schedule (the planner
    collapses them to flat, loudly for explicit requests).
    """

    def _build_mesh(self) -> Mesh:
        if not self.topology.is_uniform():
            # Ragged nodes (unequal chips per slice): the reference
            # would assert; we degrade to a one-level mesh — but LOUDLY
            # (a silent fallback turns every collective into a flat
            # all-ring program while the operator believes the heavy
            # phases ride intra-slice ICI), and the documented
            # ('mn_inter', 'mn_intra') axis pair survives as a width-1
            # inter axis, so param specs / shard_map code / tests
            # written against the hierarchical axis names keep working
            # through the degradation (a width-1 axis is a no-op in
            # every collective).
            import warnings

            sizes = sorted(set(self.topology.intra_sizes))
            warnings.warn(
                "HierarchicalCommunicator: ragged topology (chips per "
                f"slice/node: {sizes}) — the two-level ICI/DCN "
                "factorization degrades to a flat mesh (width-1 "
                "'mn_inter' axis kept for axis-name compatibility); "
                "collectives will NOT be slice-staged.  Use uniform "
                "slices, or an explicit device subset, to restore the "
                "hierarchical schedule."
            )
            grid = np.array(self.devices, dtype=object).reshape(1, -1)
        else:
            # device_grid() is already (inter_size, intra_size); one
            # node arrives as (1, n) — the degenerate two-level layout,
            # so the hierarchical code path is exercised either way.
            grid = self.topology.device_grid()
        return Mesh(grid, ("mn_inter", "mn_intra"))


class TwoDimensionalCommunicator(XlaCommunicatorBase):
    """Near-square 2-D torus factorization.

    Parity: ``TwoDimensionalCommunicator``
    (two_dimensional_communicator.py) — its reduce-scatter / inter-ring /
    allgather scheme is bandwidth-optimal because both dimensions carry
    traffic concurrently; on TPU this is precisely a 2-D ICI mesh, and
    ``mesh_utils.create_device_mesh`` assigns chips so both mesh axes ride
    physical torus rings.
    """

    def _build_mesh(self) -> Mesh:
        n = self.size
        d1 = int(np.floor(np.sqrt(n)))
        while n % d1:
            d1 -= 1
        d2 = n // d1
        if d1 == 1:
            return Mesh(np.array(self.devices, dtype=object), ("mn_x",))
        try:
            from jax.experimental import mesh_utils

            grid = mesh_utils.create_device_mesh(
                (d1, d2), devices=list(self.devices)
            )
        except Exception:
            grid = np.array(self.devices, dtype=object).reshape(d1, d2)
        return Mesh(grid, ("mn_x", "mn_y"))


class HybridCommunicator(XlaCommunicatorBase):
    """2-D (data x model) mesh for hybrid DP x TP training.

    Parity: the reference's dual-parallelism story is
    ``CommunicatorBase.split`` building sub-communicators over a 2-D
    process grid (SURVEY.md section 2, "Hybrid DP x MP").  TPU-native
    form: ONE mesh with a ``mn_data`` and a ``mn_model`` axis — the batch
    shards over ``mn_data``, tensor-parallel layers shard and psum over
    ``mn_model``, and ``build_train_step(param_specs=...)`` compiles both
    into a single program (collectives ride ICI on both axes).

    ``tp_size`` sets the model-axis width; ``size`` must divide by it.
    ``mesh_utils.create_device_mesh`` lays both axes onto physical torus
    rings where possible.
    """

    def __init__(self, devices=None, allreduce_grad_dtype=None,
                 tp_size: int = 2, **kw):
        self._tp_size = int(tp_size)
        super().__init__(devices, allreduce_grad_dtype, **kw)

    def _build_mesh(self) -> Mesh:
        n, tp = self.size, self._tp_size
        if tp < 1 or n % tp:
            raise ValueError(
                f"tp_size {tp} must divide the chip count {n}"
            )
        dp = n // tp
        try:
            from jax.experimental import mesh_utils

            grid = mesh_utils.create_device_mesh(
                (dp, tp), devices=list(self.devices)
            )
        except Exception:
            grid = np.array(self.devices, dtype=object).reshape(dp, tp)
        return Mesh(grid, ("mn_data", "mn_model"))

    @property
    def data_axis_names(self) -> tuple:
        return ("mn_data",)

    @property
    def model_axis_names(self) -> tuple:
        return ("mn_model",)

    @property
    def dp_size(self) -> int:
        return self.size // self._tp_size

    @property
    def tp_size(self) -> int:
        return self._tp_size

    def _mesh_coords(self):
        """(data, model) mesh coordinate of each rank's device (the mesh
        layout may permute devices relative to rank order)."""
        coord = {
            d: ij for ij, d in np.ndenumerate(self._mesh.devices)
        }
        return [coord[d] for d in self.devices]

    def dp_groups(self):
        """Split into per-TP-coordinate data-parallel sub-communicators —
        the reference's ``split(color=model_coord)`` pattern.  Group ``m``
        contains the chips whose model coordinate is ``m`` (a DP group of
        ``dp_size`` chips)."""
        return self.split([m for _, m in self._mesh_coords()])

    def tp_groups(self):
        """Split into per-data-coordinate tensor-parallel groups."""
        return self.split([d for d, _ in self._mesh_coords()])


class MeshCommunicator(XlaCommunicatorBase):
    """3-D (data x seq x model) mesh for fully composed parallelism.

    The general form of :class:`HybridCommunicator`: one mesh whose axes
    carry every parallelism family the framework offers at once —

    * ``mn_data``  — batch sharding + gradient psum (DP; reference's
      allreduce communicators, SURVEY.md section 2 #5-12),
    * ``mn_seq``   — sequence/context parallelism: ring attention's
      ppermute ring and sp_lm_loss's boundary exchange ride this axis
      (SURVEY.md section 5.7 — the capability the reference's p2p layer
      points at),
    * ``mn_model`` — tensor-parallel column/row collectives AND the
      expert-parallel all_to_all (Megatron TP + MoE EP share the axis;
      attention/MLP shard over it, MoE layers split tokens over it).

    ``size`` must equal ``dp * sp * tp``; ``dp`` is inferred.  Axes of
    width 1 are legal (a (n,1,1) mesh is plain DP), so a single code path
    covers every factorization — which is also how the mesh-factorization
    oracle tests work: the SAME composed model run on ``(n,1,1)`` and
    ``(a,b,c)`` meshes must produce identical numerics.
    """

    def __init__(self, devices=None, allreduce_grad_dtype=None,
                 sp_size: int = 1, tp_size: int = 1, **kw):
        self._sp_size = int(sp_size)
        self._tp_size = int(tp_size)
        super().__init__(devices, allreduce_grad_dtype, **kw)

    def _build_mesh(self) -> Mesh:
        n, sp, tp = self.size, self._sp_size, self._tp_size
        if sp < 1 or tp < 1 or n % (sp * tp):
            raise ValueError(
                f"sp_size*tp_size ({sp}*{tp}) must divide the chip "
                f"count {n}"
            )
        dp = n // (sp * tp)
        try:
            from jax.experimental import mesh_utils

            grid = mesh_utils.create_device_mesh(
                (dp, sp, tp), devices=list(self.devices)
            )
        except Exception:
            grid = np.array(self.devices, dtype=object).reshape(dp, sp, tp)
        return Mesh(grid, ("mn_data", "mn_seq", "mn_model"))

    @property
    def data_axis_names(self) -> tuple:
        return ("mn_data",)

    @property
    def seq_axis_name(self) -> str:
        return "mn_seq"

    @property
    def model_axis_name(self) -> str:
        return "mn_model"

    @property
    def dp_size(self) -> int:
        return self.size // (self._sp_size * self._tp_size)

    @property
    def sp_size(self) -> int:
        return self._sp_size

    @property
    def tp_size(self) -> int:
        return self._tp_size


class NonCudaAwareCommunicator(XlaCommunicatorBase):
    """Host-staged collectives (device -> host -> reduce -> device).

    Parity: ``NonCudaAwareCommunicator`` (non_cuda_aware_communicator.py),
    which staged GPU buffers through pinned host memory for plain MPI.  On
    TPU this path exists only for API parity and as a numerics oracle; it is
    intentionally the slow tier.  Its contract is that EVERY collective
    round-trips through host memory — no XLA collective in the data path —
    so each op below is a NumPy computation bracketed by device_get/put.
    """

    def _host(self, x, stacked: bool = True):
        host = np.asarray(jax.device_get(x))
        if stacked and (host.ndim == 0 or host.shape[0] != self.size):
            raise ValueError(
                f"stacked array must have leading axis == size "
                f"({self.size}); got shape {host.shape}"
            )
        return host

    def _replicate(self, arr):
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(
            jnp.asarray(arr), NamedSharding(self.mesh, PartitionSpec())
        )

    def allreduce(self, x, op: str = "sum"):
        host = self._host(x)
        red = {
            "sum": np.sum, "mean": np.mean, "max": np.max,
            "min": np.min, "prod": np.prod,
        }[op](host, axis=0)
        return self._put(jnp.asarray(np.broadcast_to(red, host.shape).copy()))

    def bcast(self, x, root: int = 0):
        host = self._host(x)
        return self._put(np.broadcast_to(host[root], host.shape).copy())

    def allgather(self, x):
        return self._replicate(self._host(x).copy())

    def gather(self, x, root: int = 0):
        return jax.device_put(
            jnp.asarray(self._host(x).copy()), self.devices[root]
        )

    def scatter(self, x, root: int = 0):
        del root
        return self._put(np.asarray(jax.device_get(x)).copy())

    def alltoall(self, x):
        host = np.asarray(jax.device_get(x))
        if host.ndim < 2 or host.shape[0] != self.size or \
                host.shape[1] != self.size:
            raise ValueError(
                f"alltoall expects (size, size, ...); got {host.shape}"
            )
        return self._put(np.swapaxes(host, 0, 1).copy())

    def send(self, x, dest: int, source: int):
        host = self._host(x)
        out = np.zeros_like(host)
        out[dest] = host[source]
        return self._put(out)

    def reduce_scatter(self, x, op: str = "sum"):
        if op not in ("sum", "mean"):  # match the XLA tier's surface
            raise ValueError(f"reduce_scatter supports sum/mean, got {op!r}")
        host = self._host(x)
        if host.ndim != 2 or host.shape[1] % self.size:
            raise ValueError(
                f"reduce_scatter expects (size, k*size); got {host.shape}"
            )
        red = np.sum(host, axis=0)
        if op == "mean":
            red = red / self.size
        return self._put(red.reshape(self.size, -1).copy())

    def allreduce_grad(self, grads, *, mean: bool = True):
        # Host-staged contract AND numerics-oracle contract: with a wire
        # dtype, accumulation happens in that dtype (cast -> reduce ->
        # cast back -> scale), matching the XLA tier's fused program —
        # including its overflow behavior.  Bucketed: the whole tree
        # comes off the device in ONE device_get, the host reduce runs
        # per wire bucket, and each bucket returns in one device_put —
        # the plan turns a per-leaf storm of host round trips into a
        # handful (the host-staged analogue of the compiled flat wire).
        #
        # Pipelined (ISSUE 8 satellite): the bucket exchanges used to
        # run strictly serially — reduce bucket k, ship it, only then
        # touch bucket k+1.  The reductions now run on a worker thread
        # while the main thread ships finished buckets back to the
        # device, so bucket k+1's host reduce overlaps bucket k's
        # device_put (the host-staged analogue of the compiled tier's
        # bucket overlap).  Each bucket is still reduced independently
        # in plan order with the identical arithmetic, so the result is
        # bit-identical to the serial schedule (pinned by
        # tests/test_overlap.py).
        from concurrent.futures import ThreadPoolExecutor

        from .. import comm_wire as _cw

        dt = self._allreduce_grad_dtype
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        if not leaves:
            return grads
        hosts = [self._host(g) for g in jax.device_get(leaves)]
        size = self.size
        plan = _cw.make_plan([h[0] for h in hosts])

        def reduce_one(k, cat):
            # telemetry: host-reduce span for bucket k, recorded from
            # the worker thread (the timeline is thread-safe and tags
            # thread ids, so the exported trace SHOWS the pipelining:
            # wire.reduce[k+1] on the worker overlapping wire.ship[k]
            # on the main thread)
            with _obs.span("wire.reduce", bucket=k,
                           bytes=cat.nbytes // size):
                if dt is None:
                    red = cat.mean(axis=0) if mean else cat.sum(axis=0)
                else:
                    red = np.sum(cat.astype(dt), axis=0, dtype=dt)
                    red = red.astype(cat.dtype)
                    if mean:
                        red = red / size
                return np.broadcast_to(red, cat.shape).copy()

        packed = _cw.pack_stacked(plan, hosts, size, xp=np)
        placed = []
        with ThreadPoolExecutor(max_workers=1) as pool:
            # one-ahead submission, not all-at-once: a slow device_put
            # would otherwise let the worker materialize EVERY bucket's
            # (size, bucket) broadcast copy before the first ships —
            # peak host memory bounded at two reduced buckets instead
            # of n_buckets, with the same k+1-reduces-while-k-ships
            # pipelining.
            pending = pool.submit(reduce_one, 0, packed[0]) if packed \
                else None
            for k in range(len(packed)):
                nxt = (
                    pool.submit(reduce_one, k + 1, packed[k + 1])
                    if k + 1 < len(packed) else None
                )
                with _obs.span("wire.ship", bucket=k):
                    placed.append(
                        self._put(jnp.asarray(pending.result()))
                    )
                pending = nxt
        out = _cw.unpack_stacked(plan, placed, [h.shape for h in hosts])
        return jax.tree_util.tree_unflatten(treedef, out)


class NaiveCommunicator(CommunicatorBase):
    """Pure-host communicator; needs no mesh, works with zero accelerators.

    Parity: ``NaiveCommunicator`` (naive_communicator.py) — per-parameter
    host-side MPI.Allreduce, the CPU-only testing/portability backend.  All
    collectives are NumPy on stacked arrays.
    """

    def __init__(self, devices: Optional[Sequence] = None,
                 allreduce_grad_dtype=None, *, _topology=None):
        if _topology is None:
            if devices is None:
                devices = jax.devices()
            _topology = Topology.create(devices)
        super().__init__(_topology)
        self._obj_store = create_obj_store(
            self.size, self.process_count,
            rank_to_process=tuple(d.process_index for d in self.devices),
        )
        self._allreduce_grad_dtype = (
            np.dtype(allreduce_grad_dtype) if allreduce_grad_dtype else None
        )

    @property
    def mesh(self):
        return Mesh(np.array(self.devices, dtype=object), ("mn",))

    @property
    def axis_names(self):
        return ("mn",)

    def _check(self, x):
        x = np.asarray(x)
        if x.ndim == 0 or x.shape[0] != self.size:
            raise ValueError(
                f"stacked array must have leading axis == size ({self.size});"
                f" got shape {x.shape}"
            )
        return x

    def allreduce(self, x, op: str = "sum"):
        x = self._check(x)
        if self._allreduce_grad_dtype is not None:
            x = x.astype(self._allreduce_grad_dtype)
        red = {
            "sum": np.sum, "mean": np.mean, "max": np.max,
            "min": np.min, "prod": np.prod,
        }[op](x, axis=0)
        return jnp.asarray(np.broadcast_to(red, x.shape).copy())

    def bcast(self, x, root: int = 0):
        x = self._check(x)
        return jnp.asarray(np.broadcast_to(x[root], x.shape).copy())

    def allgather(self, x):
        return jnp.asarray(self._check(x).copy())

    def gather(self, x, root: int = 0):
        # Root-materialized, mirroring the XLA tier (gather puts the full
        # stack on ``devices[root]``): the naive oracle must be able to
        # catch a root-placement bug there, not blur it into allgather.
        return jax.device_put(self._check(x).copy(), self.devices[root])

    def scatter(self, x, root: int = 0):
        # Row-per-rank placement, mirroring the XLA tier's `_put`: the
        # compute is still pure NumPy; only the final placement is
        # device-aware so the oracle can catch placement bugs.
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(
            jnp.asarray(self._check(x).copy()),
            NamedSharding(self.mesh, PartitionSpec("mn")),
        )

    def alltoall(self, x):
        x = np.asarray(x)
        if x.ndim < 2 or x.shape[0] != self.size or x.shape[1] != self.size:
            raise ValueError(f"alltoall expects (size, size, ...); got {x.shape}")
        return jnp.asarray(np.swapaxes(x, 0, 1).copy())

    def send(self, x, dest: int, source: int):
        x = self._check(x)
        out = np.zeros_like(x)
        out[dest] = x[source]
        return jnp.asarray(out)

    def reduce_scatter(self, x, op: str = "sum"):
        x = self._check(x)
        red = np.sum(x, axis=0) if op == "sum" else np.mean(x, axis=0)
        return jnp.asarray(red.reshape(self.size, -1).copy())

    def split(self, colors, keys=None):
        colors = list(colors)
        if len(colors) != self.size:
            raise ValueError("split needs one color per rank")
        if keys is None:
            keys = list(range(self.size))
        groups: dict = {}
        for rank, color in enumerate(colors):
            if color is None or color < 0:
                continue
            groups.setdefault(color, []).append((keys[rank], rank))
        out = {}
        for color, members in groups.items():
            members.sort()
            out[color] = NaiveCommunicator(
                devices=[self.devices[r] for _, r in members],
                allreduce_grad_dtype=self._allreduce_grad_dtype,
            )
        return out

    def bcast_data(self, tree):
        import jax.tree_util as jtu

        if self.process_count > 1:
            from jax.experimental import multihost_utils

            tree = multihost_utils.broadcast_one_to_all(tree)
        return jtu.tree_map(jnp.asarray, tree)


class DummyCommunicator(NaiveCommunicator):
    """No actual exchange — local value passes through unchanged.

    Parity: ``DummyCommunicator`` (dummy_communicator.py), used to measure
    the communication-free throughput upper bound by subtraction.

    Works at the compiled tier too: ``build_train_step(dummy, ...)``
    builds the IDENTICAL sharded program (same mesh, batch sharding,
    loss pmean) with only the gradient exchange omitted
    (``no_exchange`` — optimizers._no_exchange), so
    ``t_sync - t_dummy`` on the same config is the exposed cost of
    gradient sync, every other byte of the program held equal.
    Data-parallel path only: the hybrid ``param_specs`` path generates
    its collectives inside autodiff (nothing to omit), so
    ``build_train_step`` rejects the combination loudly.
    """

    no_exchange = True

    def allreduce(self, x, op: str = "sum"):
        return jnp.asarray(self._check(x).copy())

    def bcast(self, x, root: int = 0):
        return jnp.asarray(self._check(x).copy())

    def send(self, x, dest: int, source: int):
        return jnp.asarray(self._check(x).copy())

    def alltoall(self, x):
        return jnp.asarray(np.asarray(x).copy())

"""Communicator factory.

Reference parity: ``chainermn/communicators/__init__.py`` —
``create_communicator(communicator_name='hierarchical', mpi_comm=None,
allreduce_grad_dtype=None)``: string -> class dispatch.

TPU-native changes: there is no ``mpi_comm`` (topology comes from
``jax.devices()``); instead an optional ``devices=`` sequence selects the
chips, which is also how tests run every variant on a virtual CPU mesh.
The default name is ``'tpu'`` (the flat-ICI production backend) rather than
``'hierarchical'``, but all reference names resolve.
"""

from __future__ import annotations

from typing import Optional, Sequence

from .communicator_base import CommunicatorBase
from .xla_communicator_base import XlaCommunicatorBase
from ._topology import Topology
from .variants import (
    DummyCommunicator,
    FlatCommunicator,
    HierarchicalCommunicator,
    HybridCommunicator,
    MeshCommunicator,
    NaiveCommunicator,
    NonCudaAwareCommunicator,
    SingleNodeCommunicator,
    TpuCommunicator,
    TwoDimensionalCommunicator,
)

_COMMUNICATORS = {
    "tpu": TpuCommunicator,
    # Reference names (chainermn/communicators/__init__.py dispatch table);
    # `pure_nccl` maps to the flat-ICI backend, its moral equivalent.
    "pure_nccl": TpuCommunicator,
    "flat": FlatCommunicator,
    "hierarchical": HierarchicalCommunicator,
    "two_dimensional": TwoDimensionalCommunicator,
    "single_node": SingleNodeCommunicator,
    "naive": NaiveCommunicator,
    "non_cuda_aware": NonCudaAwareCommunicator,
    "dummy": DummyCommunicator,
    # beyond the reference: 2-D data x model mesh for hybrid DP x TP
    "hybrid": HybridCommunicator,
    # beyond the reference: 3-D data x seq x model mesh composing
    # DP + SP (ring attention) + TP/EP in one program
    "mesh": MeshCommunicator,
}


def create_communicator(
    communicator_name: str = "tpu",
    devices: Optional[Sequence] = None,
    allreduce_grad_dtype=None,
    **kwargs,
) -> CommunicatorBase:
    """Create a communicator by name.

    Args:
      communicator_name: one of ``tpu``, ``pure_nccl``, ``flat``,
        ``hierarchical``, ``two_dimensional``, ``single_node``, ``naive``,
        ``non_cuda_aware``, ``dummy``, ``hybrid``, ``mesh``.
      devices: devices to span (default: all of ``jax.devices()``).
      allreduce_grad_dtype: optional reduced precision (e.g. ``bfloat16`` /
        ``float16``) for gradient allreduce, as in PureNcclCommunicator.
      **kwargs: variant-specific options (e.g. ``tp_size`` for ``hybrid``,
        ``sp_size``/``tp_size`` for ``mesh``; XLA-tier communicators
        accept ``wire_schedule="auto"|"flat"|"hier_rs_ag"`` — the eager
        ``allreduce_grad``'s multi-hop schedule knob, ``"flat"`` pinning
        the bit-compat single-psum baseline).
    """
    try:
        cls = _COMMUNICATORS[communicator_name]
    except KeyError:
        raise ValueError(
            f"unknown communicator {communicator_name!r}; available: "
            f"{sorted(_COMMUNICATORS)}"
        ) from None
    return cls(devices=devices, allreduce_grad_dtype=allreduce_grad_dtype,
               **kwargs)


__all__ = [
    "CommunicatorBase",
    "XlaCommunicatorBase",
    "Topology",
    "create_communicator",
    "TpuCommunicator",
    "FlatCommunicator",
    "HierarchicalCommunicator",
    "HybridCommunicator",
    "MeshCommunicator",
    "TwoDimensionalCommunicator",
    "SingleNodeCommunicator",
    "NaiveCommunicator",
    "NonCudaAwareCommunicator",
    "DummyCommunicator",
]

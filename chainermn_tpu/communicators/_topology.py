"""Device/rank topology discovery for TPU meshes.

Reference parity: ``chainermn/communicators/_communication_utility.py``
(``init_ranks`` — hostname allgather -> intra/inter rank derivation).  On TPU
there is no hostname grouping: the pod topology is discoverable directly from
``jax.devices()`` (slice index, process index, chip coords), so ``init_ranks``
becomes a pure function of the device list.

Rank model (mirrors ChainerMN's):

* ``rank``       — global index of a chip in the communicator's device order.
* ``intra_rank`` — index of the chip *within its node*.  A "node" on TPU is a
  slice (preferred, ICI-connected island) or, failing that, a host process.
* ``inter_rank`` — index of the node itself.

ChainerMN derived these by all-gathering hostnames over MPI
(``_communication_utility.init_ranks``); here they are derived from device
attributes with no communication at all.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np


def _fake_slice_size() -> int:
    """The ``CHAINERMN_TPU_FAKE_SLICE_SIZE`` knob, parsed once for both
    consumers (the slice-less ``_node_key`` path and the degenerate
    multi-process fallback in :meth:`Topology.create`): 0 means
    disabled (unset, unparseable, or non-positive)."""
    import os

    fake = os.environ.get("CHAINERMN_TPU_FAKE_SLICE_SIZE")
    if not fake:
        return 0
    try:
        k = int(fake)
    except ValueError:
        return 0
    return k if k > 0 else 0


def _node_key(device: Any) -> Any:
    """Grouping key that plays the role of ChainerMN's hostname.

    Prefer the TPU slice index (chips within a slice are ICI-connected, the
    moral equivalent of "same node" for collective topology); fall back to the
    owning host process.

    ``CHAINERMN_TPU_FAKE_SLICE_SIZE=<k>`` groups devices that carry NO
    real slice index into synthetic slices of ``k`` by device id — how
    the CPU-mesh bench rungs and tests exercise the hierarchical
    (multi-hop schedule) paths on a single host.  Devices with a real
    ``slice_index`` are never regrouped, so the knob cannot mislabel an
    actual TPU topology.
    """
    slice_index = getattr(device, "slice_index", None)
    if slice_index is not None:
        return ("slice", slice_index)
    k = _fake_slice_size()
    if k > 0:
        return ("slice", device.id // k)
    return ("process", device.process_index)


def sort_devices(devices: Sequence[Any]) -> list[Any]:
    """Canonical device order: by node, then by id within the node.

    This guarantees that ``intra_rank`` ranges are contiguous in ``rank``
    order, which is what the hierarchical communicators rely on (ChainerMN got
    the same property from ``mpi_comm.Split`` by hostname color).
    """

    def key(d: Any) -> tuple:
        nk = _node_key(d)
        coords = getattr(d, "coords", None)
        coords = tuple(coords) if coords is not None else ()
        return (nk, coords, d.id)

    return sorted(devices, key=key)


@dataclasses.dataclass(frozen=True)
class Topology:
    """Immutable rank/topology table for a set of devices.

    Parity: the rank attributes of ``CommunicatorBase``
    (chainermn/communicators/communicator_base.py — ``rank``, ``size``,
    ``intra_rank``, ``intra_size``, ``inter_rank``, ``inter_size``).
    """

    devices: tuple  # canonical order; index == rank
    node_keys: tuple  # node key per rank
    intra_ranks: tuple  # intra-node rank per rank
    inter_ranks: tuple  # node index per rank
    intra_sizes: tuple  # size of each rank's node
    inter_size: int

    @classmethod
    def create(cls, devices: Sequence[Any]) -> "Topology":
        devs = sort_devices(devices)
        keys = [_node_key(d) for d in devs]
        if (
            len(set(keys)) == 1
            and len({d.process_index for d in devs}) > 1
            and all(getattr(d, "platform", "") == "cpu" for d in devs)
        ):
            # The CPU backend reports slice_index=0 for EVERY device of
            # a multi-process (gloo) world — a degenerate single-slice
            # claim, not a real ICI island.  Fall back to the
            # reference's hostname grouping (one node per process) so
            # hierarchical layouts factorize across hosts, exactly as
            # ChainerMN's init_ranks did.  Real TPU slices spanning
            # several hosts (platform "tpu") are untouched: a
            # multi-host slice IS one ICI island.
            #
            # CHAINERMN_TPU_FAKE_SLICE_SIZE applies HERE too (fleet
            # tier): the degenerate claim hid the knob from exactly the
            # multi-process worlds whose correlated-slice-loss
            # scenarios need a synthetic grouping — a 16-process world
            # under FAKE_SLICE_SIZE=4 factorizes into 4 synthetic
            # slices of 4, so losing "slice 3" is a correlated loss
            # the topology actually sees.  Grouping is by DENSE
            # position in the canonical device order, not raw id: the
            # multi-process CPU backend strides global ids by 2**17
            # per process, which would put every device in its own
            # "slice" (the single-process bench path keeps id-based
            # grouping — its ids are dense and pre-sort order must not
            # matter there).  Devices carrying a REAL (non-degenerate)
            # slice layout never reach this branch.
            k = _fake_slice_size()
            if k > 0:
                keys = [("slice", i // k) for i in range(len(devs))]
            else:
                keys = [("process", d.process_index) for d in devs]
        unique_keys: list = []
        for k in keys:
            if k not in unique_keys:
                unique_keys.append(k)
        inter_ranks = [unique_keys.index(k) for k in keys]
        counts: dict = {}
        intra_ranks = []
        for k in keys:
            intra_ranks.append(counts.get(k, 0))
            counts[k] = counts.get(k, 0) + 1
        intra_sizes = [counts[k] for k in keys]
        return cls(
            devices=tuple(devs),
            node_keys=tuple(keys),
            intra_ranks=tuple(intra_ranks),
            inter_ranks=tuple(inter_ranks),
            intra_sizes=tuple(intra_sizes),
            inter_size=len(unique_keys),
        )

    @property
    def size(self) -> int:
        return len(self.devices)

    def is_uniform(self) -> bool:
        """True if every node holds the same number of chips (required by the
        hierarchical / two-dimensional layouts, as in ChainerMN)."""
        return len(set(self.intra_sizes)) <= 1

    def device_grid(self) -> np.ndarray:
        """Devices as an (inter_size, intra_size) grid for 2-D meshes."""
        if not self.is_uniform():
            raise ValueError(
                "hierarchical topology requires the same number of chips per "
                f"node; got intra sizes {sorted(set(self.intra_sizes))}"
            )
        intra = self.intra_sizes[0] if self.devices else 0
        return np.array(self.devices, dtype=object).reshape(
            self.inter_size, intra
        )

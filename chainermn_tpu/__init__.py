"""chainermn_tpu — TPU-native distributed training framework.

A ground-up JAX/XLA/Pallas rebuild of the capability surface of
levelfour/chainermn (see SURVEY.md): communicator backends lowering to XLA
collectives over ICI/DCN, a multi-node optimizer wrapper, dataset
scattering, synchronized/multi-node iterators, a multi-node evaluator,
synchronized batch normalization, differentiable point-to-point and
collective communication, a MultiNodeChainList-style model-parallel API,
ring-attention / Ulysses sequence parallelism, and distributed
checkpoint/resume.

Facade parity: ``chainermn/__init__.py`` re-exports (component #1 in
SURVEY.md section 2).
"""

from chainermn_tpu import _compat  # noqa: F401  (jax API shims; must be first)
from chainermn_tpu.communicators import (  # noqa: F401
    CommunicatorBase,
    create_communicator,
)
from chainermn_tpu.optimizers import (  # noqa: F401
    create_multi_node_optimizer,
    build_train_step,
)
from chainermn_tpu.datasets import (  # noqa: F401
    scatter_dataset,
    create_empty_dataset,
)
from chainermn_tpu.extensions import (  # noqa: F401
    create_multi_node_evaluator,
    create_multi_node_checkpointer,
    AllreducePersistent,
)
from chainermn_tpu import global_except_hook  # noqa: F401

__version__ = "0.2.0"


def __getattr__(name):
    # Heavier subsystems load lazily to keep import light.
    if name in ("functions", "links", "iterators", "training", "parallel",
                "models", "ops", "utils", "resilience", "comm_wire",
                "observability", "serving", "fleet"):
        import importlib

        return importlib.import_module(f"chainermn_tpu.{name}")
    if name == "MultiNodeChainList":
        from chainermn_tpu.link import MultiNodeChainList

        return MultiNodeChainList
    if name == "create_multi_node_iterator":
        from chainermn_tpu.iterators import create_multi_node_iterator

        return create_multi_node_iterator
    if name == "create_synchronized_iterator":
        from chainermn_tpu.iterators import create_synchronized_iterator

        return create_synchronized_iterator
    if name == "prefetch_to_device":
        from chainermn_tpu.iterators import prefetch_to_device

        return prefetch_to_device
    raise AttributeError(name)

"""chainermn_tpu — TPU-native distributed training framework.

A ground-up JAX/XLA/Pallas rebuild of the capability surface of
levelfour/chainermn (see SURVEY.md): communicator backends lowering to XLA
collectives over ICI/DCN, a multi-node optimizer wrapper, dataset
scattering, synchronized/multi-node iterators, a multi-node evaluator,
synchronized batch normalization, differentiable point-to-point and
collective communication, a MultiNodeChainList-style model-parallel API,
ring-attention / Ulysses sequence parallelism, and distributed
checkpoint/resume.
"""

from chainermn_tpu.communicators import (  # noqa: F401
    CommunicatorBase,
    create_communicator,
)

__version__ = "0.1.0"

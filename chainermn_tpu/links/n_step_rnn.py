"""Multi-node N-step RNN.

Reference parity: ``chainermn/links/n_step_rnn.py`` —
``create_multi_node_n_step_rnn(link, comm, rank_in, rank_out)``: wraps a
Chainer NStepRNN so the final hidden states stream to the neighbor rank
(and are received from the previous one) — the building block of the
model-parallel seq2seq example (encoder on one rank, decoder on the next).

TPU-native redesign: the recurrence itself is a ``lax.scan`` over time (one
compiled loop, MXU-friendly fused gates); the hidden-state hand-off is a
sharded p2p (``functions.send``/``recv`` lowering to ppermute) when placed
in a ``MultiNodeChainList`` pipeline.  The RNN module returns
``(hidden_states, outputs)`` so the hand-off is an ordinary activation edge
rather than a special side channel.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax


class LSTMStack(nn.Module):
    """Multi-layer LSTM over a full sequence via ``lax.scan``.

    Gates for all four matrices are one fused matmul (MXU tiling); time is
    a compiled scan, layers a Python loop (static depth).
    """

    hidden_size: int
    num_layers: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xs: jnp.ndarray, init_state=None):
        """xs: (batch, time, features). Returns ((h, c), outputs)."""
        b, t, _ = xs.shape
        h_all, c_all = [], []
        if init_state is None:
            init_state = (
                jnp.zeros((self.num_layers, b, self.hidden_size), self.dtype),
                jnp.zeros((self.num_layers, b, self.hidden_size), self.dtype),
            )
        h0, c0 = init_state
        seq = xs
        for layer in range(self.num_layers):
            cell_in = nn.Dense(4 * self.hidden_size, dtype=self.dtype,
                               name=f"wx_{layer}")
            # Precompute input projections for the whole sequence in one
            # (b*t, 4H) matmul — large MXU tiles instead of t small ones.
            xproj = cell_in(seq)  # (b, t, 4H)
            # Recurrent weight as an explicit param so the scan body is a
            # pure function (flax submodule calls inside raw lax.scan leak
            # tracers during init).
            wh = self.param(
                f"wh_{layer}",
                nn.initializers.lecun_normal(),
                (self.hidden_size, 4 * self.hidden_size),
                jnp.float32,
            ).astype(self.dtype)

            def step(carry, xp):
                h, c = carry
                gates = xp + h.astype(self.dtype) @ wh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
                h = jax.nn.sigmoid(o) * jnp.tanh(c)
                return (h, c), h

            (h_f, c_f), ys = lax.scan(
                step, (h0[layer], c0[layer]), jnp.swapaxes(xproj, 0, 1)
            )
            ys = jnp.swapaxes(ys, 0, 1)  # (t, b, H) -> (b, t, H)
            h_all.append(h_f)
            c_all.append(c_f)
            seq = ys
        return (jnp.stack(h_all), jnp.stack(c_all)), seq


class MultiNodeNStepRNN(nn.Module):
    """LSTM stack packaged for pipeline placement.

    ``__call__(xs, incoming_state)`` consumes a neighbor's final state (or
    ``None`` for the first stage) and returns ``(state, outputs)`` where
    ``state`` is what streams to ``rank_out``.
    """

    hidden_size: int
    num_layers: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xs, incoming_state=None):
        rnn = LSTMStack(self.hidden_size, self.num_layers, self.dtype)
        state, ys = rnn(xs, incoming_state)
        return state, ys


def create_multi_node_n_step_rnn(hidden_size: int, num_layers: int = 1,
                                 comm=None, rank_in: Optional[int] = None,
                                 rank_out: Optional[int] = None,
                                 dtype=jnp.float32):
    """Factory mirroring the reference signature
    (``create_multi_node_n_step_rnn(link, comm, rank_in, rank_out)``).

    When ``rank_in``/``rank_out`` are given, the result is a
    :class:`~chainermn_tpu.link.PlacedModule` carrying that routing —
    registering it with ``MultiNodeChainList.add_link(placed)`` applies
    the declared edges (hidden states stream from ``rank_in``'s stage and
    toward ``rank_out``'s), so the arguments genuinely take effect.
    With neither given, returns the bare module.
    """
    del comm  # routing needs no communicator handle; kept for parity
    rnn = MultiNodeNStepRNN(hidden_size=hidden_size, num_layers=num_layers,
                            dtype=dtype)
    if rank_in is None and rank_out is None:
        return rnn
    from ..link import PlacedModule

    return PlacedModule(rnn, rank_in=rank_in, rank_out=rank_out)

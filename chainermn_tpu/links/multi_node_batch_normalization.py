"""Synchronized (multi-node) batch normalization.

Reference parity: ``chainermn/links/multi_node_batch_normalization.py`` —
``MultiNodeBatchNormalization(size, comm, ...)``: forward all-reduces the
per-batch mean and squared mean across ranks before normalizing; backward
all-reduces the gradient statistics — batch-norm statistics over the
*global* data-parallel batch.

TPU-native redesign: a flax ``nn.Module`` whose statistics reduction names
the communicator's mesh axes.  Inside ``shard_map`` the ``lax.pmean`` runs
over ICI; under plain ``jit`` + GSPMD-sharded batch the same code needs no
axis at all (a global-batch mean already lowers to a cross-chip reduce), so
``axis_name=None`` degrades gracefully.  The backward allreduce the
reference hand-wrote is *generated* here: differentiating ``pmean`` inserts
the transpose collective automatically.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple, Union

import jax.numpy as jnp
from flax import linen as nn
from jax import lax

# cross-chip statistics ride the audited collective wrappers
# (analysis.lint forbids raw lax collectives outside the comm layer)
from chainermn_tpu.functions import collectives as _cc


def _reduce_axes_mean(x: jnp.ndarray, reduction_axes, axis_names):
    """Mean over local reduction axes, then over mesh axes if bound."""
    m = jnp.mean(x, axis=reduction_axes)
    if axis_names:
        m = _cc.pmean(m, axis_names)
    return m


class MultiNodeBatchNormalization(nn.Module):
    """BatchNorm whose batch statistics span the whole data-parallel job.

    Args:
      size: number of features (channel dimension).
      axis_name: mesh axis name(s) to reduce statistics over.  Pass
        ``comm.axis_names`` when the module runs inside ``shard_map``;
        leave ``None`` under plain jit + sharded batch (GSPMD makes the
        batch mean global already).
      momentum / epsilon / use_bias / use_scale: as in standard BN.
      dtype: computation dtype (statistics always accumulate in float32 —
        on TPU the input is typically bfloat16 and fp32 accumulation is
        both free and necessary for stable variance).
    """

    size: int
    axis_name: Optional[Union[str, Tuple[str, ...]]] = None
    momentum: float = 0.9
    epsilon: float = 2e-5
    use_bias: bool = True
    use_scale: bool = True
    dtype: Any = jnp.float32
    axis: int = -1  # feature axis
    scale_init: Any = nn.initializers.ones
    bias_init: Any = nn.initializers.zeros

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        feature_axis = self.axis % x.ndim
        reduction_axes = tuple(
            i for i in range(x.ndim) if i != feature_axis
        )
        ra_mean = self.variable(
            "batch_stats", "mean",
            lambda: jnp.zeros((self.size,), jnp.float32),
        )
        ra_var = self.variable(
            "batch_stats", "var",
            lambda: jnp.ones((self.size,), jnp.float32),
        )

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32)
            # Allreduce mean and mean-of-squares together (the reference
            # packed both into one allreduce; here they fuse into one XLA
            # collective as a (2, C) stack).
            stats = jnp.stack(
                [
                    jnp.mean(xf, axis=reduction_axes),
                    jnp.mean(jnp.square(xf), axis=reduction_axes),
                ]
            )
            if self.axis_name and not self.is_initializing():
                # Outside shard_map this raises NameError (unbound axis) —
                # deliberately not swallowed: a wrong axis name silently
                # disabling cross-chip sync is the exact failure mode this
                # link exists to prevent.  Eval-mode calls (running stats)
                # and init never reach here.
                stats = _cc.pmean(stats, self.axis_name)
            mean, sq_mean = stats[0], stats[1]
            var = sq_mean - jnp.square(mean)
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value + (1 - self.momentum) * mean
                )
                ra_var.value = (
                    self.momentum * ra_var.value + (1 - self.momentum) * var
                )

        shape = [1] * x.ndim
        shape[feature_axis] = self.size
        # Statistics accumulate in fp32 above; the NORMALIZATION
        # arithmetic runs in self.dtype, matching flax BatchNorm — for
        # bf16 models this is the round-3 MFU lever (the per-element
        # scale/shift stream halves its bytes), with the fp32 mean/inv
        # folded into one per-channel multiplier and offset first so
        # the precision-sensitive part stays fp32.
        inv = lax.rsqrt(var + self.epsilon)
        if self.use_scale:
            gamma = self.param(
                "scale", self.scale_init, (self.size,), jnp.float32
            )
            inv = inv * gamma
        offset = -mean * inv
        if self.use_bias:
            beta = self.param(
                "bias", self.bias_init, (self.size,), jnp.float32
            )
            offset = offset + beta
        y = (
            x.astype(self.dtype) * inv.reshape(shape).astype(self.dtype)
            + offset.reshape(shape).astype(self.dtype)
        )
        return y.astype(self.dtype)

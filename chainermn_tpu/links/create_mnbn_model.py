"""Recursively replace BatchNorm with MultiNodeBatchNormalization.

Reference parity: ``chainermn/links/create_mnbn_model.py`` —
``create_mnbn_model(link, comm)``: clone a model, substituting every
``BatchNormalization`` child with ``MultiNodeBatchNormalization``.

TPU-native form: flax modules are immutable dataclass pytrees, so instead
of cloning a mutable link tree we rebuild the module with
``nn.BatchNorm``-typed fields/submodules swapped.  Because flax modules
declare submodules in ``setup``/``__call__`` rather than as runtime
children, wholesale substitution is done by a module transform: models in
``chainermn_tpu.models`` accept a ``norm`` factory argument, and
``create_mnbn_model`` returns the same model class re-parameterized with a
MultiNodeBatchNormalization factory bound to the communicator's axes.
"""

from __future__ import annotations

import dataclasses
import warnings

from flax import linen as nn

from .multi_node_batch_normalization import MultiNodeBatchNormalization


def mnbn_factory(comm, **bn_kwargs):
    """A ``norm`` factory usable by models: ``norm(size) -> Module``."""

    def make(size: int, **kw):
        # call-site kwargs (scale_init, the model's compute dtype) are
        # defaults; anything the user pinned in bn_kwargs wins — an
        # explicit create_mnbn_model(model, comm, dtype=float32) must
        # not be silently overridden by the model's bf16
        merged = dict(kw)
        merged.update(bn_kwargs)
        return MultiNodeBatchNormalization(
            size=size, axis_name=comm.axis_names, **merged
        )

    return make


def create_mnbn_model(model: nn.Module, comm, **bn_kwargs) -> nn.Module:
    """Return ``model`` with synchronized batch normalization.

    Works with any model exposing a ``norm`` dataclass field (the convention
    used throughout ``chainermn_tpu.models``); for foreign modules with an
    ``axis_name`` field on their BatchNorms, those are rebound instead.
    """
    if hasattr(model, "norm"):
        return dataclasses.replace(model, norm=mnbn_factory(comm, **bn_kwargs))
    if isinstance(model, (nn.BatchNorm,)):
        return MultiNodeBatchNormalization(
            size=model.num_features if hasattr(model, "num_features") else 0,
            axis_name=comm.axis_names,
        )
    # Reference parity: create_mnbn_model recursively copies a chain,
    # replacing BatchNormalization children.  flax submodules declared in
    # setup/__call__ are invisible from outside, but dataclass *fields*
    # holding modules are inspectable — if any field subtree contains an
    # nn.BatchNorm, conversion is needed yet impossible, so refuse rather
    # than silently keep unsynchronized BN.
    bn = _find_batchnorm_field(model)
    if bn is not None:
        raise TypeError(
            f"create_mnbn_model: {type(model).__name__} holds a "
            f"{type(bn).__name__} submodule but exposes no `norm` factory "
            "field, so it cannot be converted to synchronized BN.  Adopt "
            "the chainermn_tpu.models convention: accept a `norm` factory "
            "(norm(size) -> Module) and construct normalization through it."
        )
    warnings.warn(
        f"create_mnbn_model: {type(model).__name__} exposes no `norm` "
        "factory field (chainermn_tpu.models convention); returning it "
        "unchanged.  No BatchNorm was found among its dataclass fields, "
        "but submodules constructed inside setup()/__call__() cannot be "
        "inspected — if the model creates BatchNorm internally it will "
        "remain UNsynchronized.",
        stacklevel=2,
    )
    return model


def _find_batchnorm_field(model: nn.Module, _depth: int = 0):
    """Best-effort scan of dataclass fields for a BatchNorm descendant."""
    if _depth > 8:
        return None
    for f in dataclasses.fields(model):
        try:
            v = getattr(model, f.name, None)
        except Exception:
            continue
        for sub in _iter_modules(v):
            if isinstance(sub, nn.BatchNorm):
                return sub
            found = _find_batchnorm_field(sub, _depth + 1)
            if found is not None:
                return found
    return None


def _iter_modules(v):
    if isinstance(v, nn.Module):
        yield v
    elif isinstance(v, (list, tuple)):
        for x in v:
            yield from _iter_modules(x)
    elif isinstance(v, dict):
        for x in v.values():
            yield from _iter_modules(x)

"""Recursively replace BatchNorm with MultiNodeBatchNormalization.

Reference parity: ``chainermn/links/create_mnbn_model.py`` —
``create_mnbn_model(link, comm)``: clone a model, substituting every
``BatchNormalization`` child with ``MultiNodeBatchNormalization``.

TPU-native form: flax modules are immutable dataclass pytrees, so instead
of cloning a mutable link tree we rebuild the module with
``nn.BatchNorm``-typed fields/submodules swapped.  Because flax modules
declare submodules in ``setup``/``__call__`` rather than as runtime
children, wholesale substitution is done by a module transform: models in
``chainermn_tpu.models`` accept a ``norm`` factory argument, and
``create_mnbn_model`` returns the same model class re-parameterized with a
MultiNodeBatchNormalization factory bound to the communicator's axes.
"""

from __future__ import annotations

import dataclasses
import functools
import warnings
from typing import Any, Optional

from flax import linen as nn

from .multi_node_batch_normalization import MultiNodeBatchNormalization


def mnbn_factory(comm, **bn_kwargs):
    """A ``norm`` factory usable by models: ``norm(size) -> Module``."""

    def make(size: int, **kw):
        merged = dict(bn_kwargs)
        merged.update(kw)
        return MultiNodeBatchNormalization(
            size=size, axis_name=comm.axis_names, **merged
        )

    return make


def create_mnbn_model(model: nn.Module, comm, **bn_kwargs) -> nn.Module:
    """Return ``model`` with synchronized batch normalization.

    Works with any model exposing a ``norm`` dataclass field (the convention
    used throughout ``chainermn_tpu.models``); for foreign modules with an
    ``axis_name`` field on their BatchNorms, those are rebound instead.
    """
    if hasattr(model, "norm"):
        return dataclasses.replace(model, norm=mnbn_factory(comm, **bn_kwargs))
    if isinstance(model, (nn.BatchNorm,)):
        return MultiNodeBatchNormalization(
            size=model.num_features if hasattr(model, "num_features") else 0,
            axis_name=comm.axis_names,
        )
    # Reference parity: create_mnbn_model recursively copies a chain,
    # replacing BatchNormalization children — a chain with none comes back
    # unchanged.  Models without the `norm` factory field are treated as
    # BN-free; warn in case the caller expected a conversion.
    warnings.warn(
        f"create_mnbn_model: {type(model).__name__} exposes no `norm` "
        "factory field (chainermn_tpu.models convention); returning it "
        "unchanged (BN-free models need no sync-BN)",
        stacklevel=2,
    )
    return model

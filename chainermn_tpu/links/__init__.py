from .multi_node_batch_normalization import MultiNodeBatchNormalization  # noqa: F401
from .create_mnbn_model import create_mnbn_model  # noqa: F401
from .n_step_rnn import create_multi_node_n_step_rnn, MultiNodeNStepRNN  # noqa: F401

__all__ = [
    "MultiNodeBatchNormalization",
    "create_mnbn_model",
    "create_multi_node_n_step_rnn",
    "MultiNodeNStepRNN",
]

"""Composable fault schedules for fleet-shaped chaos scenarios.

The fault injector (``resilience.fault_injection``) is deliberately
low-level: one :class:`~chainermn_tpu.resilience.fault_injection.
FaultSpec` is one rule at one site.  A fleet scenario needs *waves* —
"four of these sixteen processes die within this window", "every
process of slice 2 disappears together", "the straggler migrates from
rank 3 to rank 9 after the first report window" — and composing those
by hand into spec lists is exactly the error-prone bookkeeping a chaos
tier must not leave to each scenario.

:class:`FaultSchedule` is that composition layer.  Every method appends
specs (and returns ``self``, so schedules chain); :meth:`env` renders
the whole schedule into the environment contract the injector already
honors (``CHAINERMN_TPU_FAULTS`` + ``CHAINERMN_TPU_FAULT_SEED``, plus
``CHAINERMN_TPU_FAKE_SLICE_SIZE`` when a synthetic slice grouping is in
play) — which is how :class:`~chainermn_tpu.fleet.world.FleetWorld`
delivers it into spawned workers it cannot reach by object reference.

Timing model: the injector is call-count-addressed, not wall-clock
addressed (determinism contract — see its module docstring), so a
schedule "window" is a span of 1-based call counts at a site.  For the
training scenarios the natural site is ``trainer.update`` (one call per
step), so windows read as step ranges.
"""

from __future__ import annotations

import copy
import json
from typing import Dict, List, Optional, Sequence

from ..resilience.fault_injection import (
    ENV_SEED,
    ENV_SPEC,
    FaultSpec,
)

ENV_SLICE = "CHAINERMN_TPU_FAKE_SLICE_SIZE"

# default sites: one trainer.update fire per step (the step clock), the
# obj-store exchange underneath every agreement (plan/trace/inventory)
STEP_SITE = "trainer.update"
AGREEMENT_SITE = "obj_store.exchange"


def _check_window(window: Sequence[int]) -> tuple:
    lo, hi = (int(window[0]), int(window[1]))
    if lo < 1 or hi < lo:
        raise ValueError(
            f"window must be (lo, hi) with 1 <= lo <= hi, got {window!r}"
        )
    return lo, hi


def _check_processes(processes: Sequence[int]) -> List[int]:
    procs = [int(p) for p in processes]
    if not procs:
        raise ValueError("a wave needs at least one target process")
    if len(set(procs)) != len(procs):
        raise ValueError(f"duplicate wave targets: {sorted(procs)}")
    if min(procs) < 0:
        raise ValueError(f"negative process index in {sorted(procs)}")
    return procs


class FaultSchedule:
    """A composable, env-renderable list of fault-injector specs.

    ``seed`` feeds the injector's RNG (probabilistic specs); the
    deterministic wave/straggler/torn methods below never draw from it,
    so two schedules built the same way compile to byte-identical env
    payloads.
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._specs: List[dict] = []
        self.slice_size: Optional[int] = None

    # -- composition ----------------------------------------------------
    def fault(self, site: str, kind: str, **kwargs) -> "FaultSchedule":
        """Raw escape hatch: one FaultSpec, validated eagerly (a typo'd
        kind must fail at schedule build, not inside a spawned worker
        where the traceback dies with the process)."""
        spec = {"site": site, "kind": kind, **kwargs}
        FaultSpec(**spec)  # validate now
        self._specs.append(spec)
        return self

    def preemption_wave(self, processes: Sequence[int], *,
                        window: Sequence[int], site: str = STEP_SITE,
                        exit_code: int = 43) -> "FaultSchedule":
        """``k`` processes die within a call-count window at ``site``.

        Each target is assigned one call in ``[lo, hi]``, spread evenly
        and deterministically by its position in ``processes`` — a
        one-call window is a simultaneous wave, a wider window is a
        rolling reclaim.  Lockstep caveat, by design: once the earliest
        victim dies, every later step's collectives block on it, so
        survivors of a rolling wave stall rather than advance — exactly
        the production behavior (recovery happens at restart, which is
        the next :class:`~chainermn_tpu.fleet.chain.ElasticityChain`
        leg's job).
        """
        procs = _check_processes(processes)
        lo, hi = _check_window(window)
        span = hi - lo + 1
        for i, p in enumerate(procs):
            at = lo + (i * span) // len(procs)
            self.fault(site, "die", at=[at], process=p,
                       exit_code=exit_code)
        return self

    def slice_loss(self, slice_index: int, *, slice_size: int,
                   at: int, site: str = STEP_SITE,
                   exit_code: int = 44) -> "FaultSchedule":
        """Correlated loss of one synthetic slice: every process of
        slice ``slice_index`` (the ``CHAINERMN_TPU_FAKE_SLICE_SIZE``
        grouping — processes ``[k*size, (k+1)*size)``) dies at the same
        call.  :meth:`env` exports the slice size so the workers'
        topology actually factorizes into the slices being lost.

        ``slice_size`` counts PROCESSES.  The topology env knob counts
        device positions, so :meth:`FleetWorld.env_for` scales the
        exported value by ``local_devices`` — the two groupings always
        name the same process sets."""
        if slice_size < 1:
            raise ValueError(f"slice_size must be >= 1, got {slice_size}")
        if self.slice_size is not None and self.slice_size != slice_size:
            raise ValueError(
                f"one schedule, one slice grouping: already "
                f"{self.slice_size}, got {slice_size}"
            )
        self.slice_size = int(slice_size)
        procs = range(slice_index * slice_size,
                      (slice_index + 1) * slice_size)
        return self.preemption_wave(list(procs), window=(at, at),
                                    site=site, exit_code=exit_code)

    def torn_payload(self, calls: Sequence[int] = (1,), *,
                     truncate_to: int = 4,
                     site: str = AGREEMENT_SITE,
                     process: Optional[int] = None) -> "FaultSchedule":
        """Torn payloads during agreement exchanges (plan / trace /
        inventory all ride ``obj_store.exchange``): each listed call's
        payload is truncated, surfacing as ``PayloadCorruptionError`` on
        every rank in lockstep — the retry path the agreement stack
        exists to survive.  ``process=None`` tears on every rank (the
        lockstep case); an int targets one rank's outbound payload."""
        for c in calls:
            self.fault(site, "truncate", at=[int(c)],
                       truncate_to=truncate_to, process=process)
        return self

    def straggler(self, process: int, *, window: Sequence[int],
                  delay: float = 0.25,
                  site: str = STEP_SITE) -> "FaultSchedule":
        """One process is slow for every step of a window.  Call it
        again with a different process and a later window to make the
        straggler *migrate* between ranks — the case the leave-one-out
        detector must track across report windows."""
        lo, hi = _check_window(window)
        self.fault(site, "delay", at=list(range(lo, hi + 1)),
                   delay=float(delay), process=int(process))
        return self

    def pace(self, *, window: Sequence[int], delay: float = 0.05,
             site: str = STEP_SITE) -> "FaultSchedule":
        """EVERY process is slowed by the same ``delay`` for each step
        of a window — a world-wide pace floor.  On a timeshared host
        the natural per-step variance can rival the injected straggler
        delays; pinning a common floor makes step-mean RATIOS (the
        straggler rule, the probation rule) noise-robust without making
        any process a relative straggler."""
        lo, hi = _check_window(window)
        self.fault(site, "delay", at=list(range(lo, hi + 1)),
                   delay=float(delay), process=None)
        return self

    def compose(self, other: "FaultSchedule") -> "FaultSchedule":
        """A new schedule carrying both spec lists (seed from ``self``;
        slice groupings must agree — two different synthetic slice
        sizes cannot coexist in one world)."""
        if (self.slice_size is not None and other.slice_size is not None
                and self.slice_size != other.slice_size):
            raise ValueError(
                f"cannot compose slice groupings {self.slice_size} and "
                f"{other.slice_size}"
            )
        out = FaultSchedule(seed=self.seed)
        out._specs = copy.deepcopy(self._specs) + copy.deepcopy(
            other._specs
        )
        out.slice_size = (self.slice_size if self.slice_size is not None
                          else other.slice_size)
        return out

    # -- rendering ------------------------------------------------------
    def specs(self) -> List[dict]:
        return copy.deepcopy(self._specs)

    def to_faultspecs(self) -> List[FaultSpec]:
        return [FaultSpec(**d) for d in self._specs]

    def env(self) -> Dict[str, str]:
        """The env-var rendering the injector's ``_from_env`` consumes
        in every spawned worker."""
        out = {
            ENV_SPEC: json.dumps(self._specs),
            ENV_SEED: str(self.seed),
        }
        if self.slice_size is not None:
            out[ENV_SLICE] = str(self.slice_size)
        return out

    def describe(self) -> str:
        """Human-readable summary (the loud-teardown report and the
        fleet post-mortem both lead with it)."""
        if not self._specs:
            return "FaultSchedule(empty)"
        lines = [f"FaultSchedule(seed={self.seed}, "
                 f"{len(self._specs)} spec(s))"]
        for d in self._specs:
            proc = ("all processes" if d.get("process") is None
                    else f"process {d['process']}")
            lines.append(
                f"  {d['kind']}@{d['site']} at={sorted(d.get('at', []))} "
                f"[{proc}]"
            )
        return "\n".join(lines)

    def __len__(self):
        return len(self._specs)

    def __repr__(self):
        return (f"<FaultSchedule seed={self.seed} n={len(self._specs)} "
                f"slice_size={self.slice_size}>")

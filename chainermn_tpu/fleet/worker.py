"""Fleet worker: one process of a simulated 16-64-rank world.

Spawned by :class:`~chainermn_tpu.fleet.world.FleetWorld` (never by
hand):

    python -m chainermn_tpu.fleet.worker <scenario> <port> <pid> \
        <nproc> <scratch> <label> <args_json>

Each worker initializes ``jax.distributed`` against the local
coordinator on a gloo CPU backend, installs telemetry plus the
streaming resilience sink (so a process killed by a ``die`` fault still
leaves its events on disk), runs one scenario, exports its timeline
with the wall-clock anchor, and prints ``RESULT <json>``.

Scenarios are the fleet tier's reusable building blocks — the
elasticity-chain leg (:func:`scenario_chain_leg`), the fleet-shaped
serving churn (:func:`scenario_serving_wave` /
:func:`scenario_serving_resume`), and the world-formation rendezvous —
driven by tests and ``benchmarks/fleet_chaos_bench.py`` alike.
"""

from __future__ import annotations

import json
import os
import sys
import time

_CTX: dict = {}


def _lockstep_allgather(comm, payload, site: str = "fleet.rendezvous"):
    """The agreement-shaped exchange (``resilience.retry.
    lockstep_allgather``): a torn payload or transient fault fails —
    and re-exchanges — on all ranks together, exactly like
    ``plan_agreement`` / ``newest_common_step``."""
    from chainermn_tpu.resilience.retry import lockstep_allgather

    return lockstep_allgather(comm, payload, site=site)


def _export_artifacts() -> None:
    """Flush this worker's post-mortem artifacts (idempotent)."""
    tel = _CTX.get("telemetry")
    if tel is not None:
        tel.timeline.to_jsonl(_CTX["trace_path"], meta=True)
    rec = _CTX.get("protocol")
    if rec is not None:
        rec.to_jsonl(_CTX["protocol_path"])


def finish_and_exit(out: dict, code: int = 0,
                    linger_s: float = 0.0) -> None:
    """Survivor epilogue for wave scenarios: export artifacts and print
    the RESULT payload FIRST (the runtime's peer-death propagation may
    reap this process at any moment once victims die — paperwork before
    linger), then optionally linger (keeping the coordinator alive for
    late victims), then ``os._exit`` — a graceful interpreter exit
    would hang in ``jax.distributed`` teardown waiting for the wave's
    victims, exactly like a real preemption (recovery happens at
    restart, the next leg)."""
    _export_artifacts()
    print("RESULT " + json.dumps(out or {}), flush=True)
    sys.stdout.flush()
    sys.stderr.flush()
    if linger_s > 0:
        time.sleep(linger_s)
    os._exit(code)


# ----------------------------------------------------------------------
def scenario_rendezvous(pid, nproc, scratch, label, args):
    """World formation at fleet width: create the communicator, run one
    lockstep agreement exchange (the schedule may tear it — the retry
    is the point), and report the injector's observations."""
    import chainermn_tpu as cmn
    from chainermn_tpu.resilience import fault_injection as fi

    comm = cmn.create_communicator(args.get("comm", "tpu"))
    assert comm.process_count == nproc, (comm.process_count, nproc)
    got = _lockstep_allgather(comm, pid, site="fleet.rendezvous")
    assert got == list(range(nproc)), got
    inj = fi.active()
    counts = dict(inj.log.counts) if inj is not None else {}
    desc = comm.world_descriptor()
    return {
        "size": comm.size,
        "world": desc["world_size"],
        "mesh_axes": desc["mesh_axes"],
        "faults": counts.get("fault_injected", 0),
    }


def scenario_sleep(pid, nproc, scratch, label, args):
    """Wedge on purpose — the budget-teardown test's subject."""
    time.sleep(float(args.get("sleep_s", 3600)))
    return {}


# ----------------------------------------------------------------------
def _chain_pieces(comm, scratch, lr, mom, dim):
    """One elasticity-chain leg's training pieces: a ZeRO (sgd+momentum)
    world — momentum state genuinely blocked over the ranks, the state
    that must reshard N→M — over a loss whose gradient is world-size
    independent.

    Every process feeds the SAME two local rows {0, 1}: the per-chip
    batch mean is 0.5 at any world size, so the gradient is elementwise
    ``w - 0.5`` on every leg of any chain and the single-world numpy
    oracle (:func:`~chainermn_tpu.fleet.chain.momentum_oracle`) prices
    the whole trajectory with no replay.
    """
    import numpy as np
    import jax.numpy as jnp
    import optax
    import chainermn_tpu as cmn
    from chainermn_tpu.optimizers import build_train_step

    def loss_fn(params, batch):
        return 0.5 * jnp.sum((params["w"] - batch.mean(axis=0)) ** 2)

    opt = cmn.create_multi_node_optimizer(
        optax.sgd(lr, momentum=mom), comm, zero_redundancy=True
    )
    step = build_train_step(comm, loss_fn, opt, donate=False)
    ckpt = cmn.create_multi_node_checkpointer(
        "chain", comm, path=os.path.join(scratch, "chain_ckpt")
    )
    rows = [np.zeros((dim,), np.float32), np.ones((dim,), np.float32)]
    return opt, step, ckpt, rows


def scenario_chain_leg(pid, nproc, scratch, label, args):
    """One leg of an elasticity chain (driven by
    :class:`~chainermn_tpu.fleet.chain.ElasticityChain`).

    Wave leg (``wave_at`` set — chain-initial): rendezvous (the
    schedule may tear the exchange → lockstep retry), then train and
    collectively snapshot steps ``1..wave_at-1``, each step checked
    against the oracle; then fire the wave site — the schedule's
    victims die there, the survivors linger (so every victim's exit
    lands while the coordinator still serves) and are reaped with the
    job, exactly like a real preemption wave.

    Resume leg: ``Trainer.run_elastic`` re-forms the world, restores
    THROUGH the checkpoint resharder (the elected snapshot's manifest
    names the previous leg's world), and runs to ``n_steps`` with
    per-iteration snapshots; the final params must land on the
    uninterrupted single-world oracle trajectory.

    ``resume_wave`` composes the two: the wave leg first restores the
    elected snapshot through the resharder (so a breathing world can be
    preempted AGAIN after it grew), then runs the manual loop from the
    restored step to ``wave_at`` — the absolute step the wave fires at.
    """
    import warnings

    import numpy as np
    from chainermn_tpu.fleet.chain import momentum_oracle
    from chainermn_tpu.resilience import fault_injection as fi

    lr = float(args.get("lr", 0.1))
    mom = float(args.get("mom", 0.9))
    dim = int(args.get("dim", 4))
    n_steps = int(args["n_steps"])
    wave_at = args.get("wave_at")
    linger = float(args.get("linger_s", 1.5))
    oracle = momentum_oracle(n_steps, lr=lr, mom=mom, dim=dim)

    if wave_at is not None:
        # -- wave leg (manual loop: Trainer.run would hang in the wave
        # step's collective once the first victim dies) --------------
        import jax.numpy as jnp
        import chainermn_tpu as cmn

        wave_at = int(wave_at)
        comm = cmn.create_communicator("tpu")
        got = _lockstep_allgather(comm, pid, site="fleet.chain_leg.rendezvous")
        assert got == list(range(nproc)), got
        opt, step, ckpt, rows = _chain_pieces(comm, scratch, lr, mom, dim)
        p0 = {"w": jnp.zeros((dim,))}
        params, opt_state = step.place(p0, opt.init(p0))
        start = 1
        if args.get("resume_wave"):
            # mid-chain wave: restore the elected snapshot THROUGH the
            # checkpoint resharder first (a throwaway Trainer carries
            # the state templates), then run the manual loop from the
            # restored step to the ABSOLUTE wave step
            from chainermn_tpu.iterators import SerialIterator
            from chainermn_tpu.training.trainer import Trainer, Updater

            it = SerialIterator(rows, 2, shuffle=False)
            t = Trainer(Updater(it, step, params, opt_state),
                        stop_trigger=(wave_at, "iteration"))
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                restored = ckpt.restore_trainer(t)
            assert restored is not None, "resume_wave needs a snapshot"
            params, opt_state = t.updater.params, t.updater.opt_state
            start = int(restored) + 1
        batch = np.stack(rows)
        for s in range(start, wave_at):
            fi.fire("trainer.update")
            params, opt_state, _m = step(params, opt_state, batch)
            ckpt.save(s, {
                "params": params,
                "opt_state": opt_state,
                "trainer": {"iteration": s, "iterator": None},
            })
            np.testing.assert_allclose(
                np.asarray(params["w"]), oracle[s - 1], rtol=1e-5
            )
        # Wide-world defect, surfaced by this scenario at 16 processes
        # and never at 2: the instant the wave's victims die, the
        # coordination service broadcasts the dead peers and every
        # SURVIVOR's error-poll thread hard-aborts its own process
        # (xla's client.h "Terminating process..." path) — racing the
        # survivor's epilogue.  So the epilogue runs BEFORE the wave
        # point: artifacts exported, RESULT printed, stdout flushed —
        # the post-mortem is already safe when the runtime reaps the
        # survivors, exactly as in a real preemption (the launcher
        # accepts runtime-reaped survivors for wave legs: see
        # FleetWorld.REAPED).  The victims' own die records reach disk
        # through the streaming sink inside fire().
        _export_artifacts()
        print("RESULT " + json.dumps({
            "steps_saved": wave_at - start,
            "resumed_step": start - 1 if start > 1 else None,
            "w": float(np.asarray(params["w"])[0]),
        }), flush=True)
        sys.stdout.flush()
        fi.fire("trainer.update")  # the wave: victims die in here
        # survivors linger so every victim's exit lands while the
        # coordinator still serves, then exit hard — the runtime may
        # reap them first, which is fine: the paperwork is done
        time.sleep(linger)
        os._exit(0)

    # -- resume leg ----------------------------------------------------
    from chainermn_tpu import observability as obs
    from chainermn_tpu.iterators import SerialIterator
    from chainermn_tpu.training.trainer import Trainer, Updater

    straggler = args.get("straggler")
    report_holder = {}

    def build(comm):
        import jax.numpy as jnp

        opt, step, ckpt, rows = _chain_pieces(comm, scratch, lr, mom, dim)
        p0 = {"w": jnp.zeros((dim,))}
        params, opt_state = step.place(p0, opt.init(p0))
        it = SerialIterator(rows, 2, shuffle=False)
        trainer = Trainer(Updater(it, step, params, opt_state),
                          stop_trigger=(n_steps, "iteration"))
        trainer.extend(ckpt, trigger=(1, "iteration"))
        if straggler:
            # per-iteration windows: the first window after a resume is
            # compile-dominated and excluded from conviction BY
            # CONTRACT (MetricsReport's warmup_windows=1 default — the
            # trainer log carries elastic_restart at initialize), so
            # conviction comes from the later, steady windows — the leg
            # reports the UNION of flags across windows (read off the
            # straggler events)
            rep = obs.MetricsReport(
                comm, trigger=(int(args.get("report_every", 1)),
                               "iteration"),
                filename=None,
            )
            trainer.extend(rep)
            report_holder["rep"] = rep
        return trainer

    with warnings.catch_warnings():
        # the resharder warns (by design) about reset trainer-template
        # slots the wave leg's manual saves did not carry
        warnings.simplefilter("ignore")
        trainer = Trainer.run_elastic(build, communicator_name="tpu")

    ev = trainer.resilience_log.events("elastic_restart")
    assert ev, "run_elastic must record its restart"
    restored = ev[0].info.get("restored_step")
    resized = ev[0].info.get("resized")
    assert trainer.iteration == n_steps, trainer.iteration
    got = np.asarray(trainer.updater.params["w"])
    ok = bool(np.allclose(got, oracle[n_steps - 1], rtol=1e-5))
    assert ok, (got, oracle[n_steps - 1])
    # events recorded directly on the trainer log (elastic_restart,
    # restart) never reach the global sink — export them for the report
    from chainermn_tpu.fleet.report import export_resilience_log

    export_resilience_log(
        trainer.resilience_log,
        os.path.join(scratch, f"{label}_p{pid}_trainer_events.jsonl"),
    )
    stragglers = None
    if report_holder.get("rep") is not None:
        stragglers = sorted({
            int(e.info["process"])
            for e in trainer.resilience_log.events("straggler")
        })
    return {
        "resumed_step": restored,
        "resized": list(resized) if resized else None,
        "oracle_match": ok,
        "iteration": trainer.iteration,
        "final_w": float(got[0]),
        "stragglers": stragglers,
    }


# ----------------------------------------------------------------------
def scenario_adaptive_leg(pid, nproc, scratch, label, args):
    """The self-healing runtime's demote leg (ISSUE 15): a straggler
    (possibly migrating between ranks — the schedule decides) is
    convicted by ``MetricsReport``, the :class:`~chainermn_tpu.
    resilience.adaptive.AdaptPolicy` first REBALANCES (weighted
    re-scatter of the shared dataset, agreed cross-rank through the
    lockstep-retried exchange, live iterator cursor remapped) and, once
    the conviction streak outlives the hysteresis window, DEMOTES: a
    snapshot is committed at the decision iteration and
    ``DemotionRequiredError`` raises on every rank together.  The next
    leg (the plain ``chain_leg`` resume at N−1) re-forms the world and
    must land on the single-world oracle from exactly that step.

    The dataset is constant 0.5-rows scattered across processes, so ANY
    weighted shard's batch mean is 0.5 — the numpy sgd+momentum oracle
    holds through every rebalance, making the data skew a real
    re-scatter rather than a decision-only event.
    """
    import numpy as np
    import jax.numpy as jnp

    import chainermn_tpu as cmn
    from chainermn_tpu import observability as obs
    from chainermn_tpu.datasets import scatter_dataset
    from chainermn_tpu.fleet.chain import momentum_oracle
    from chainermn_tpu.fleet.report import export_resilience_log
    from chainermn_tpu.iterators import SerialIterator
    from chainermn_tpu.resilience.adaptive import (
        AdaptiveExecution,
        AdaptPolicy,
    )
    from chainermn_tpu.resilience.errors import DemotionRequiredError
    from chainermn_tpu.training.trainer import Trainer, Updater

    lr = float(args.get("lr", 0.1))
    mom = float(args.get("mom", 0.9))
    dim = int(args.get("dim", 4))
    n_steps = int(args["n_steps"])

    comm = cmn.create_communicator("tpu")
    got = _lockstep_allgather(comm, pid, site="fleet.adaptive_leg.rendezvous")
    assert got == list(range(nproc)), got

    # the SAME pieces (loss, ZeRO sgd+momentum optimizer, step, and —
    # critically — the checkpointer name/path the N−1 chain_leg resume
    # elects from) as every chain leg; only the dataset differs
    opt, step, ckpt, _rows = _chain_pieces(comm, scratch, lr, mom, dim)
    full = [np.full((dim,), 0.5, np.float32)] * (nproc * 4)
    shard = scatter_dataset(full, comm, shuffle=False, seed=0)
    width0 = len(shard)
    p0 = {"w": jnp.zeros((dim,))}
    params, opt_state = step.place(p0, opt.init(p0))
    it = SerialIterator(shard, 2, shuffle=False)
    trainer = Trainer(Updater(it, step, params, opt_state),
                      stop_trigger=(n_steps, "iteration"))
    trainer.extend(ckpt, trigger=(1, "iteration"))
    trainer.extend(obs.MetricsReport(comm, trigger=(1, "iteration"),
                                     filename=None))
    policy = AdaptPolicy(
        rebalance_after=int(args.get("rebalance_after", 1)),
        demote_after=int(args.get("demote_after", 3)),
        cooldown_windows=int(args.get("cooldown_windows", 1)),
        max_rebalances=int(args.get("max_rebalances", 2)),
    )
    trainer.extend(AdaptiveExecution(policy, comm=comm))

    demoted = None
    try:
        trainer.run()
    except DemotionRequiredError as err:
        demoted = int(err.peer)
    # the completed prefix sits on the oracle (the rebalances changed
    # shard maps, never batch statistics)
    w = np.asarray(trainer.updater.params["w"])
    oracle_ok = True
    if trainer.iteration > 0:
        oracle = momentum_oracle(trainer.iteration, lr=lr, mom=mom,
                                 dim=dim)
        oracle_ok = bool(np.allclose(
            w, oracle[trainer.iteration - 1], rtol=1e-5
        ))
    rebalances = trainer.resilience_log.events(
        "adapt_action", "adaptive.rebalance"
    )
    export_resilience_log(
        trainer.resilience_log,
        os.path.join(scratch, f"{label}_p{pid}_trainer_events.jsonl"),
    )
    stragglers = sorted({
        int(e.info["process"])
        for e in trainer.resilience_log.events("straggler")
    })
    out = {
        "demoted": demoted,
        "iteration": trainer.iteration,
        "oracle_match": oracle_ok,
        "stragglers": stragglers,
        "n_rebalances": len(rebalances),
        "rebalance_applied": bool(
            rebalances and rebalances[0].info.get("applied")
        ),
        "shard_width": [width0,
                        len(trainer.updater.iterator.dataset)],
        "w": float(w[0]),
    }
    # every rank exits together after the agreed demotion, but the exit
    # race with the runtime's peer-death propagation is real (the first
    # os._exit may reap the rest) — paperwork first, REAPED accepted
    finish_and_exit(out, linger_s=float(args.get("linger_s", 1.5)))


# ----------------------------------------------------------------------
def scenario_grow_leg(pid, nproc, scratch, label, args):
    """The scale-UP leg (ISSUE 16): an N-process training world runs
    with a :class:`~chainermn_tpu.resilience.adaptive.CapacityWatcher`
    over the shared scratch's presence manifests.  Candidate hosts
    (concurrent 1-process ``probe_host`` worlds) publish per-window
    manifests; the watcher holds each under probation until its probe
    step means clear the straggler rule for ``probation_windows``
    consecutive NEW windows, the policy holds the ready set until
    ``promote_quorum`` hosts can join in ONE restart, and the agreed
    decision commits a snapshot and raises
    :class:`~chainermn_tpu.resilience.errors.PromotionRequiredError`
    on every rank together.  The next leg (a plain ``chain_leg`` resume
    at N+k) re-forms the world and must land on the single-world oracle
    from exactly the decision step.
    """
    import warnings

    import numpy as np
    import jax.numpy as jnp

    import chainermn_tpu as cmn
    from chainermn_tpu import observability as obs
    from chainermn_tpu.datasets import scatter_dataset
    from chainermn_tpu.fleet.chain import momentum_oracle
    from chainermn_tpu.fleet.report import export_resilience_log
    from chainermn_tpu.iterators import SerialIterator
    from chainermn_tpu.resilience.adaptive import (
        AdaptiveExecution,
        AdaptPolicy,
        CapacityWatcher,
    )
    from chainermn_tpu.resilience.errors import PromotionRequiredError
    from chainermn_tpu.training.trainer import Trainer, Updater

    lr = float(args.get("lr", 0.1))
    mom = float(args.get("mom", 0.9))
    dim = int(args.get("dim", 4))
    n_steps = int(args["n_steps"])

    comm = cmn.create_communicator("tpu")
    got = _lockstep_allgather(comm, pid, site="fleet.grow_leg.rendezvous")
    assert got == list(range(nproc)), got

    # the SAME pieces (and checkpointer root) as every chain leg, so
    # the N+k resume elects this leg's decision snapshot
    opt, step, ckpt, _rows = _chain_pieces(comm, scratch, lr, mom, dim)
    full = [np.full((dim,), 0.5, np.float32)] * (nproc * 4)
    shard = scatter_dataset(full, comm, shuffle=False, seed=0)
    p0 = {"w": jnp.zeros((dim,))}
    params, opt_state = step.place(p0, opt.init(p0))
    it = SerialIterator(shard, 2, shuffle=False)
    trainer = Trainer(Updater(it, step, params, opt_state),
                      stop_trigger=(n_steps, "iteration"))
    trainer.extend(ckpt, trigger=(1, "iteration"))
    trainer.extend(obs.MetricsReport(
        comm,
        trigger=(int(args.get("report_every", 1)), "iteration"),
        filename=None,
    ))
    policy = AdaptPolicy(
        demote_after=int(args.get("demote_after", 3)),
        probation_windows=int(args.get("probation_windows", 2)),
        promote_quorum=int(args.get("promote_quorum", 1)),
        readmit_cooldown_windows=int(
            args.get("readmit_cooldown_windows", 0)
        ),
    )
    watcher = CapacityWatcher(
        scratch,
        probation_windows=policy.probation_windows,
        straggler_factor=float(args.get("probe_straggler_factor", 1.5)),
    )
    trainer.extend(AdaptiveExecution(
        policy, comm=comm, watcher=watcher,
        hosts=[f"h{i}" for i in range(nproc)],
    ))
    restored = None
    if args.get("resume"):
        with warnings.catch_warnings():
            # the resharder warns about reset trainer-template slots a
            # wave leg's manual saves did not carry
            warnings.simplefilter("ignore")
            restored = ckpt.restore_trainer(trainer)
    promote = None
    try:
        trainer.run()
    except PromotionRequiredError as err:
        promote = {"hosts": [str(h) for h in err.hosts],
                   "new_world": int(err.new_world)}
    # the completed prefix sits on the oracle (probation is decision
    # state, never batch statistics)
    w = np.asarray(trainer.updater.params["w"])
    oracle_ok = True
    if trainer.iteration > 0:
        oracle = momentum_oracle(trainer.iteration, lr=lr, mom=mom,
                                 dim=dim)
        oracle_ok = bool(np.allclose(
            w, oracle[trainer.iteration - 1], rtol=1e-5
        ))
    export_resilience_log(
        trainer.resilience_log,
        os.path.join(scratch, f"{label}_p{pid}_trainer_events.jsonl"),
    )
    out = {
        "promote": promote,
        "iteration": trainer.iteration,
        "resumed_step": restored,
        "oracle_match": oracle_ok,
        "promote_total": policy.totals.get("promote", 0),
        "w": float(w[0]),
    }
    # every rank exits together after the agreed promotion, but the
    # exit race with the runtime's peer-death propagation is real —
    # paperwork first, REAPED accepted (same epilogue as the demote leg)
    finish_and_exit(out, linger_s=float(args.get("linger_s", 1.5)))


def scenario_probe_host(pid, nproc, scratch, label, args):
    """A returning/new host's probation protocol (ISSUE 16): a
    1-process world that trains on a WEIGHT-0 scatter shard (pure
    permutation-head padding — rank ``world`` of a ``world+1``-wide
    weighted split owns no sample, so it steps at world cadence while
    holding no state; and it mounts NO checkpointer, so the chain's
    snapshot root is untouched).  Each report window it measures its
    step mean through ``MetricsReport`` and publishes one presence
    manifest (atomic tmp+rename), pacing itself to the training world's
    window cadence.  It keeps probing until the training world's agreed
    promote decision posts its ADMISSION marker
    (``AdaptiveExecution._promote`` publishes it on rank 0 and
    withdraws the presence manifest) — the candidate exits on the
    marker; the N+k resume leg is its first participation in the
    world.  A schedule may
    straggle its early steps (``delay`` at ``trainer.update``): the
    watcher holds it (``probation_hold``) until the dirty windows age
    out, which is the heal-then-readmit path.
    """
    import numpy as np
    import jax.numpy as jnp
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu import observability as obs
    from chainermn_tpu.datasets.scatter_dataset import scatter_index
    from chainermn_tpu.iterators import SerialIterator
    from chainermn_tpu.optimizers import build_train_step
    from chainermn_tpu.resilience.adaptive import (
        admission_path,
        clear_admission,
        clear_presence,
        publish_presence,
    )
    from chainermn_tpu.training.trainer import Trainer, Updater

    assert nproc == 1, "a probe is a 1-process world"
    host = str(args["host"])
    world = int(args.get("world", 1))  # the training world it joins
    spw = int(args.get("steps_per_window", 3))
    max_windows = int(args.get("max_windows", 200))
    window_sleep = float(args.get("window_sleep_s", 0.25))
    lr = float(args.get("lr", 0.1))
    mom = float(args.get("mom", 0.9))
    dim = int(args.get("dim", 4))

    comm = cmn.create_communicator("tpu")
    # the candidate's shard: rank ``world`` of a ``world+1``-wide split
    # with weight 0 — an equalized pad drawn from the permutation head,
    # so the probe steps in world cadence while OWNING no sample
    full = [np.full((dim,), 0.5, np.float32)] * (world * 4)
    order, start, end = scatter_index(
        len(full), world + 1, world,
        weights=[1.0] * world + [0.0], equalize=True,
    )
    shard = [full[int(i)] for i in order[start:end]]
    assert shard, "the equalized weight-0 shard pads, never empties"

    def loss_fn(params, batch):
        return 0.5 * jnp.sum((params["w"] - batch.mean(axis=0)) ** 2)

    opt = cmn.create_multi_node_optimizer(
        optax.sgd(lr, momentum=mom), comm, zero_redundancy=True
    )
    step = build_train_step(comm, loss_fn, opt, donate=False)
    p0 = {"w": jnp.zeros((dim,))}
    params, opt_state = step.place(p0, opt.init(p0))
    it = SerialIterator(shard, 2, shuffle=False)
    trainer = Trainer(Updater(it, step, params, opt_state),
                      stop_trigger=(max_windows * spw, "iteration"))
    rep = obs.MetricsReport(comm, trigger=(spw, "iteration"),
                            filename=None)
    trainer.extend(rep)

    # a fresh probe must not read its ancestor's admission
    clear_admission(scratch, host)
    state = {"window": 0}

    class _Promoted(Exception):
        pass

    class _Publish:
        """Presence publisher: one manifest per report window."""

        priority = 80  # after MetricsReport (120) in the same pass
        trigger = (spw, "iteration")
        name = "presence"

        def __call__(self, t):
            if os.path.exists(admission_path(scratch, host)):
                # the agreed decision answered: admitted
                raise _Promoted()
            mean = rep.process_means("step").get(0)
            if mean is None:
                return  # no measurement yet — publish nothing
            state["window"] += 1
            publish_presence(scratch, host, window=state["window"],
                             step_mean_s=mean)
            # pace probe windows to the training world's cadence: the
            # watcher only advances a streak on NEW windows, one per
            # scan, so racing far ahead just freezes the manifest
            time.sleep(window_sleep)

    trainer.extend(_Publish())
    promoted = False
    admission = None
    try:
        trainer.run()
    except _Promoted:
        promoted = True
        with open(admission_path(scratch, host)) as f:
            admission = json.load(f)
    clear_presence(scratch, host)  # idempotent: gone if promoted
    return {
        "host": host,
        "promoted": promoted,
        "admission": admission,
        "windows": state["window"],
        "steps": trainer.iteration,
    }


# ----------------------------------------------------------------------
def _assert_bit_identical(a, b, what):
    """0-tolerance leaf equality, shard-aware: a ZeRO leaf is a global
    array whose host view is per-process — compare addressable shards
    by index instead of materializing (np.asarray on a cross-process
    global array raises)."""
    import jax
    import numpy as np

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb), (what, len(la), len(lb))
    for x, y in zip(la, lb):
        if hasattr(x, "is_fully_addressable") and \
                not x.is_fully_addressable:
            sx = sorted(x.addressable_shards, key=lambda s: str(s.index))
            sy = sorted(y.addressable_shards, key=lambda s: str(s.index))
            assert len(sx) == len(sy), (what, len(sx), len(sy))
            for u, v in zip(sx, sy):
                assert u.index == v.index, (what, u.index, v.index)
                assert np.array_equal(
                    np.asarray(u.data), np.asarray(v.data)
                ), f"{what}: shard {u.index} differs"
        else:
            assert np.array_equal(np.asarray(x), np.asarray(y)), \
                f"{what}: leaf differs"


def _recover_trainer(step, opt, rows, dim, n_steps):
    """A throwaway Trainer carrying the state templates a collective
    restore needs (the resume_wave pattern)."""
    import jax.numpy as jnp
    from chainermn_tpu.iterators import SerialIterator
    from chainermn_tpu.training.trainer import Trainer, Updater

    p0 = {"w": jnp.zeros((dim,))}
    params, opt_state = step.place(p0, opt.init(p0))
    it = SerialIterator(rows, 2, shuffle=False)
    return Trainer(Updater(it, step, params, opt_state),
                   stop_trigger=(n_steps, "iteration"))


def scenario_peer_recover_leg(pid, nproc, scratch, label, args):
    """The sub-second-recovery A/B leg (ISSUE 19): one world trains
    the standard chain pieces, snapshotting each step into ONE tier —
    ``tier="peer"`` replicates into the RAM ring
    (:class:`~chainermn_tpu.resilience.peer_ckpt.PeerCheckpointStore`),
    ``tier="fs"`` saves through the shared-FS checkpointer — then a
    single rank loses its state at ``lose_at`` (modeled in-process:
    params/opt_state re-zeroed, its peer RAM forgotten; the world stays
    formed so the A/B times RECOVERY, not relaunch) and every rank runs
    the collective restore.  The ``recover_action`` → ``recovered``
    event gap is the tier's recovery latency; the bench prices the two
    legs against each other.

    The peer leg additionally FS-saves the election step (outside the
    timed window) and, after recovery, restores it back through the FS
    checkpointer to pin the acceptance contract: peer-restored state is
    bit-identical — 0 tolerance, ZeRO blocked leaves included — to the
    FS restore of the same step.  Both legs then train on to
    ``n_steps`` and must land on the single-world numpy oracle."""
    import warnings

    import numpy as np
    import jax.numpy as jnp
    import chainermn_tpu as cmn
    from chainermn_tpu.fleet.chain import momentum_oracle
    from chainermn_tpu.resilience import PeerCheckpointStore
    from chainermn_tpu.resilience.log import emit

    lr = float(args.get("lr", 0.1))
    mom = float(args.get("mom", 0.9))
    dim = int(args.get("dim", 4))
    n_steps = int(args["n_steps"])
    lose_at = int(args["lose_at"])
    tier = str(args.get("tier", "peer"))
    victim = int(args.get("victim", 1))
    assert victim != 0, "process 0 is the jax.distributed coordinator"
    assert 1 < lose_at <= n_steps, (lose_at, n_steps)

    comm = cmn.create_communicator("tpu")
    got = _lockstep_allgather(comm, pid, site="fleet.peer_recover.rendezvous")
    assert got == list(range(nproc)), got
    opt, step, ckpt, rows = _chain_pieces(comm, scratch, lr, mom, dim)
    peer = PeerCheckpointStore(comm) if tier == "peer" else None
    oracle = momentum_oracle(n_steps, lr=lr, mom=mom, dim=dim)
    # the throwaway restore target doubles as the trainer-state
    # template: manual saves must carry the full state_dict shape or
    # the same-world orbax restore rejects the like-template mismatch
    t = _recover_trainer(step, opt, rows, dim, n_steps)
    p0 = {"w": jnp.zeros((dim,))}
    params, opt_state = step.place(p0, opt.init(p0))
    batch = np.stack(rows)
    for s in range(1, lose_at):
        params, opt_state, _m = step(params, opt_state, batch)
        state = {"params": params, "opt_state": opt_state,
                 "trainer": dict(t.state_dict(), iteration=s)}
        if peer is not None:
            peer.replicate(s, state)
            if s == lose_at - 1:
                # the election step also lands on the FS tier — OUTSIDE
                # the timed window — purely for the post-recovery
                # bit-identity cross-check below
                ckpt.save(s, state)
        else:
            ckpt.save(s, state)

    # -- the loss: one rank's state (and peer RAM) evaporates.  Purely
    # local (drop the references): a victim-only re-place would run a
    # host collective alone and shift the world's exchange stream -----
    if pid == victim:
        params = opt_state = None
        if peer is not None:
            peer.forget()
    emit("recover_action", "fleet.recover", tier=tier, victim=victim,
         step=lose_at - 1)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        if peer is not None:
            restored = peer.restore_trainer(t)
        else:
            restored = ckpt.restore_trainer(t)
    assert restored == lose_at - 1, (restored, lose_at - 1)
    params, opt_state = t.updater.params, t.updater.opt_state
    emit("recovered", "fleet.recover", tier=tier, step=int(restored))

    bit_identical = None
    if peer is not None:
        # acceptance pin: the SAME step back through the FS cold tier
        # must match the peer restore bit for bit
        t2 = _recover_trainer(step, opt, rows, dim, n_steps)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            fs_step = ckpt.restore_trainer(t2)
        assert fs_step == restored, (fs_step, restored)
        _assert_bit_identical(params, t2.updater.params, "params")
        _assert_bit_identical(opt_state, t2.updater.opt_state,
                              "opt_state")
        bit_identical = True

    for s in range(int(restored) + 1, n_steps + 1):
        params, opt_state, _m = step(params, opt_state, batch)
        if peer is not None:
            peer.replicate(s, {
                "params": params, "opt_state": opt_state,
                "trainer": {"iteration": s, "iterator": None},
            })
    w = np.asarray(params["w"])
    np.testing.assert_allclose(w, oracle[n_steps - 1], rtol=1e-5)
    return {
        "tier": tier,
        "restored_step": int(restored),
        "bit_identical": bit_identical,
        "oracle_match": True,
        "w": float(w[0]),
    }


def scenario_peer_ring_broken(pid, nproc, scratch, label, args):
    """Correlated loss (ISSUE 19 satellite): a rank AND its ring
    replica holder lose their RAM in one wave — the slice-loss shape —
    so no peer snapshot has complete owner coverage.  The collective
    peer restore must detect the broken ring (``peer_ring_broken``
    logged), return empty-handed, and the survivors degrade to the FS
    COLD tier (the per-step checkpoints the same loop committed),
    landing on the single-world numpy oracle."""
    import warnings

    import numpy as np
    import jax.numpy as jnp
    import chainermn_tpu as cmn
    from chainermn_tpu.fleet.chain import momentum_oracle
    from chainermn_tpu.resilience import PeerCheckpointStore
    from chainermn_tpu.resilience.log import emit

    lr = float(args.get("lr", 0.1))
    mom = float(args.get("mom", 0.9))
    dim = int(args.get("dim", 4))
    n_steps = int(args["n_steps"])
    lose_at = int(args["lose_at"])
    victim = int(args.get("victim", 1))
    assert victim != 0, "process 0 is the jax.distributed coordinator"

    comm = cmn.create_communicator("tpu")
    got = _lockstep_allgather(comm, pid, site="fleet.ring_broken.rendezvous")
    assert got == list(range(nproc)), got
    opt, step, ckpt, rows = _chain_pieces(comm, scratch, lr, mom, dim)
    peer = PeerCheckpointStore(comm)
    holder = peer.holder if pid == victim else (victim + 1) % nproc
    oracle = momentum_oracle(n_steps, lr=lr, mom=mom, dim=dim)
    t = _recover_trainer(step, opt, rows, dim, n_steps)
    p0 = {"w": jnp.zeros((dim,))}
    params, opt_state = step.place(p0, opt.init(p0))
    batch = np.stack(rows)
    for s in range(1, lose_at):
        params, opt_state, _m = step(params, opt_state, batch)
        state = {"params": params, "opt_state": opt_state,
                 "trainer": dict(t.state_dict(), iteration=s)}
        peer.replicate(s, state)
        ckpt.save(s, state)  # the cold tier the fallback lands on

    # correlated loss: the victim AND its replica holder forget — the
    # victim's envelope now survives NOWHERE in the ring.  Purely
    # local, as in the A/B leg (no victim-only collectives)
    if pid in (victim, holder):
        params = opt_state = None
        peer.forget()
    emit("recover_action", "fleet.recover", tier="peer_then_fs",
         victim=victim, holder=holder, step=lose_at - 1)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        restored = peer.restore_trainer(t)
        assert restored is None, "a broken ring must not elect"
        restored = ckpt.restore_trainer(t)  # the FS cold fallback
    assert restored == lose_at - 1, (restored, lose_at - 1)
    params, opt_state = t.updater.params, t.updater.opt_state
    emit("recovered", "fleet.recover", tier="fs_cold",
         step=int(restored))
    for s in range(int(restored) + 1, n_steps + 1):
        params, opt_state, _m = step(params, opt_state, batch)
    w = np.asarray(params["w"])
    np.testing.assert_allclose(w, oracle[n_steps - 1], rtol=1e-5)
    return {
        "restored_step": int(restored),
        "fell_back": True,
        "oracle_match": True,
        "w": float(w[0]),
    }


# ----------------------------------------------------------------------
def _serving_fixture(n_requests: int):
    """Deterministic tiny LM (same seed on every process → identical
    params → greedy decode of any request is bit-identical no matter
    which replica runs it) + the scripted request stream."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from chainermn_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab_size=64, d_model=32, n_heads=4,
                          n_layers=2, max_len=64)
    params = model.init(
        {"params": jax.random.PRNGKey(0),
         "dropout": jax.random.PRNGKey(1)},
        jnp.zeros((1, 8), jnp.int32),
    )
    rng = np.random.RandomState(5)
    stream = [
        ("c%d" % i, rng.randint(0, 64, int(rng.randint(3, 10))).tolist(),
         6)
        for i in range(n_requests)
    ]
    return model, params, stream


def _serving_engine(model, params):
    from chainermn_tpu.serving.decode import DecodeEngine

    return DecodeEngine(model, params, capacity=2, page_size=8)


def scenario_serving_wave(pid, nproc, scratch, label, args):
    """Fleet-shaped serving churn, phase 1: N replicas (>= 4) partition
    one journaled stream by ``seq % N``; the schedule kills several in
    ONE wave (process-targeted ``die`` at ``serving.decode_step``).
    Survivors complete exactly their own shares — verified against the
    seq-mod contract — and the victims' shares stay journaled."""
    from chainermn_tpu.serving.batcher import Request
    from chainermn_tpu.serving.replica import DecodeReplica, RequestJournal

    n_requests = int(args.get("n_requests", 16))
    model, params, stream = _serving_fixture(n_requests)
    journal = RequestJournal(os.path.join(scratch, "serve_journal"))
    if pid == 0:
        journal.submit_all([Request(p, m, id=i) for i, p, m in stream])
    # journal-level rendezvous (no collectives: a dead peer must never
    # wedge a survivor)
    journal.wait_until(len(stream))
    replica = DecodeReplica(
        _serving_engine(model, params), journal,
        replica_index=pid, n_replicas=nproc,
    )
    served = replica.serve()  # victims die inside (schedule spec)
    # the survivor served ITS seq-mod share, whole and nothing else
    by_id = {r["id"]: r for r in journal.requests()}
    for rid in served:
        assert int(by_id[rid]["seq"]) % nproc == pid, (rid, pid)
    want = {r["id"] for r in by_id.values()
            if int(r["seq"]) % nproc == pid}
    assert set(served) == want, (sorted(served), sorted(want))
    # RESULT before the linger: the survivor may be reaped by the
    # runtime's peer-death propagation at any point after the kill
    # (the launcher accepts REAPED for wave survivors)
    finish_and_exit({"served": sorted(served), "replica": pid},
                    linger_s=float(args.get("linger_s", 1.5)))


def scenario_serving_resume(pid, nproc, scratch, label, args):
    """Phase 2: the survivors re-form at the new replica count via
    ``serve_elastic``; the pending partition re-derives over ``seq %
    n_survivors``, so the dead replicas' shares migrate without
    coordination, and every journaled request completes bit-identically
    to a fresh single-engine oracle."""
    from chainermn_tpu.serving.replica import (
        RequestJournal,
        serve_elastic,
    )

    n_requests = int(args.get("n_requests", 16))
    model, params, stream = _serving_fixture(n_requests)
    root = os.path.join(scratch, "serve_journal")
    journal = RequestJournal(root)
    pending_before = len(journal.pending())
    assert pending_before > 0, "phase 1 should have left unserved work"
    # the re-derived partition this replica is about to claim
    my_share = {r["id"] for r in journal.pending()
                if int(r["seq"]) % nproc == pid}

    def build(comm):
        from chainermn_tpu.serving.replica import DecodeReplica

        return DecodeReplica(
            _serving_engine(model, params), journal,
            replica_index=pid, n_replicas=nproc,
        )

    replica = serve_elastic(
        build, root, communicator_name="tpu",
        replica_index=pid, n_replicas=nproc,
    )
    served = set(replica.batcher.finished)
    assert served == my_share, (sorted(served), sorted(my_share))
    # wait for the OTHER survivors' results before the global checks
    journal.wait_until_complete(n_requests)
    results = journal.results()
    assert sorted(results) == sorted(i for i, _p, _m in stream)
    oracle_eng = _serving_engine(model, params)
    mismatches = [
        rid for rid, prompt, max_new in stream
        if results[rid]["tokens"] != oracle_eng.generate(prompt, max_new)
    ]
    assert not mismatches, mismatches
    return {
        "pending_before": pending_before,
        "completed": len(results),
        "bit_identical": True,
        "served": sorted(served),
    }


def scenario_serving_autoscale(pid, nproc, scratch, label, args):
    """Load-driven autoscale over a pool of resident replica slots
    (ISSUE 16): every process is one slot serving in pool mode
    (``serve(until_complete=...)``); the highest slots start
    drain-marked (standbys).  Process 0 is ALSO the single decision
    maker: it trickles the offered load into the journal and runs one
    :class:`~chainermn_tpu.serving.replica.ReplicaAutoscaler` observe
    per decision window — the opening burst's backlog scales the pool
    UP (``clear_draining``; the standby's ``seq % n`` share re-derives
    on its next claim pass), and the post-load calm scales it back DOWN
    to ``min_replicas`` (``mark_draining``).  The atomic drain markers
    are the only coordination.  Every request completes bit-identically
    to a fresh single-engine oracle; an ownership handoff at an
    activation instant may duplicate decode WORK (the claim is
    lease-free by design), but greedy decode is deterministic and
    result writes are idempotent overwrites, so never a result."""
    import threading

    from chainermn_tpu.serving.batcher import Request
    from chainermn_tpu.serving.replica import (
        DecodeReplica,
        ReplicaAutoscaler,
        RequestJournal,
    )

    n_requests = int(args.get("n_requests", 30))
    burst = int(args.get("burst", 18))
    wave = int(args.get("wave", 4))
    min_replicas = int(args.get("min_replicas", 2))
    observe_s = float(args.get("observe_s", 0.4))
    serve_timeout = float(args.get("serve_timeout_s", 200.0))
    model, params, stream = _serving_fixture(n_requests)
    journal = RequestJournal(os.path.join(scratch, "serve_journal"))
    if pid == 0:
        # standbys first (markers must precede any claimable work),
        # then the opening burst
        for slot in range(min_replicas, nproc):
            journal.mark_draining(slot)
        journal.submit_all([Request(p, m, id=i)
                            for i, p, m in stream[:burst]])
    # journal-level rendezvous (no collectives: autoscale must never
    # couple the slots' control planes)
    journal.wait_until(burst)
    replica = DecodeReplica(_serving_engine(model, params), journal,
                            replica_index=pid, n_replicas=nproc)

    def serve():
        return replica.serve(until_complete=n_requests,
                             timeout_s=serve_timeout)

    if pid != 0:
        served = serve()
        journal.wait_until_complete(n_requests,
                                    timeout_s=serve_timeout)
        return {"served": sorted(served), "replica": pid,
                "was_standby": pid >= min_replicas}

    # process 0: its replica slot serves in a thread; the main thread
    # is the pool's one decision maker
    served_box = {}
    t = threading.Thread(target=lambda: served_box.update(serve()))
    t.start()
    scaler = ReplicaAutoscaler(
        journal, nproc, min_replicas=min_replicas,
        queue_per_replica=int(args.get("queue_per_replica", 4)),
        scale_after=int(args.get("scale_after", 2)),
        cooldown_windows=int(args.get("cooldown_windows", 1)),
    )
    actions = []
    submitted = burst
    deadline = time.monotonic() + serve_timeout
    while time.monotonic() < deadline:
        if submitted < n_requests:  # the trickle behind the burst
            nxt = stream[submitted:submitted + wave]
            journal.submit_all([Request(p, m, id=i)
                                for i, p, m in nxt])
            submitted += len(nxt)
        a = scaler.observe()
        if a:
            actions.append(a)
        done = len(journal.results()) >= n_requests
        # keep observing through the post-load calm until the pool has
        # breathed back down — relief at an empty queue is the
        # scale-down signal, exactly like a real idle pool
        if (done and scaler.totals["scale_down"] >= 1
                and len(scaler.active()) <= min_replicas):
            break
        time.sleep(observe_s)
    t.join(timeout=60)
    results = journal.results()
    assert len(results) == n_requests, (len(results), n_requests)
    oracle_eng = _serving_engine(model, params)
    mismatches = [
        rid for rid, prompt, max_new in stream
        if results[rid]["tokens"] != oracle_eng.generate(prompt, max_new)
    ]
    assert not mismatches, mismatches
    return {
        "served": sorted(served_box), "replica": 0,
        "actions": actions,
        "totals": dict(scaler.totals),
        "active_final": scaler.active(),
    }


def scenario_serving_drain_cycle(pid, nproc, scratch, label, args):
    """Drain -> heal -> re-claim, end to end (ISSUE 16 satellite):
    replica ``nproc-1`` starts drain-marked (``drain_replica`` — the
    adaptive-layer entry point, so the report carries the decision
    trail) and polls as a standby while the healthy replicas complete
    batch 1, the drained slot's reassigned share included.  Once batch
    1 is fully served — the queue is empty, so ownership can change
    with NOTHING pending — process 0 lifts the marker and submits batch
    2: the returned replica re-derives its pure ``seq % n`` share of
    the new work.  With the marker flip at a pending-empty instant the
    shares are disjoint BY CONSTRUCTION (same seqs, same draining set
    on every reader): no request is served twice and none is
    orphaned."""
    import threading

    from chainermn_tpu.resilience.adaptive import drain_replica
    from chainermn_tpu.serving.batcher import Request
    from chainermn_tpu.serving.replica import DecodeReplica, RequestJournal

    b1 = int(args.get("batch1", 12))
    b2 = int(args.get("batch2", 12))
    total = b1 + b2
    serve_timeout = float(args.get("serve_timeout_s", 200.0))
    model, params, stream = _serving_fixture(total)
    journal = RequestJournal(os.path.join(scratch, "serve_journal"))
    drained = nproc - 1
    if pid == 0:
        drain_replica(journal, drained)
        journal.submit_all([Request(p, m, id=i)
                            for i, p, m in stream[:b1]])
    journal.wait_until(b1)
    replica = DecodeReplica(_serving_engine(model, params), journal,
                            replica_index=pid, n_replicas=nproc)

    def serve():
        return replica.serve(until_complete=total,
                             timeout_s=serve_timeout)

    if pid != 0:
        served = serve()
        journal.wait_until_complete(total, timeout_s=serve_timeout)
        return {"served": sorted(served), "replica": pid}
    served_box = {}
    t = threading.Thread(target=lambda: served_box.update(serve()))
    t.start()
    # batch 1 completes WITHOUT the drained slot: its share migrated
    journal.wait_until_complete(b1, timeout_s=serve_timeout)
    assert journal.draining() == [drained], journal.draining()
    journal.clear_draining(drained)  # heal: re-admit the slot
    journal.submit_all([Request(p, m, id=i)
                        for i, p, m in stream[b1:]])
    results = journal.wait_until_complete(total, timeout_s=serve_timeout)
    t.join(timeout=60)
    oracle_eng = _serving_engine(model, params)
    mismatches = [
        rid for rid, prompt, max_new in stream
        if results[rid]["tokens"] != oracle_eng.generate(prompt, max_new)
    ]
    assert not mismatches, mismatches
    return {"served": sorted(served_box), "replica": 0,
            "batch1": b1, "batch2": b2}


# ----------------------------------------------------------------------
def _spec_fixture(n_requests: int):
    """Speculative-burst stream: every prompt opens with the SAME
    page-aligned 8-token system prefix (page_size is 8, so admission
    aliases exactly one page cross-request), then a distinct tail.
    ``max_new`` is staggered so requests retire at different steps and
    the shared page's refcount walks down one release at a time."""
    import numpy as np

    model, params, _ = _serving_fixture(0)
    rng = np.random.RandomState(11)
    sys_prefix = rng.randint(0, 64, 8).tolist()
    stream = [
        ("s%d" % i,
         sys_prefix + rng.randint(0, 64, 1 + i % 3).tolist(),
         5 + i % 3)
        for i in range(n_requests)
    ]
    return model, params, stream


def _spec_replica(model, params, journal, pid, nproc, k):
    """A :class:`DecodeReplica` running a :class:`SpeculativeBatcher`:
    a half-width 1-layer draft (deterministic seed — identical on every
    process) proposes against the target fixture, with the draft cache
    built to the target's exact geometry."""
    import jax
    import jax.numpy as jnp
    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving.decode import DecodeEngine
    from chainermn_tpu.serving.replica import DecodeReplica
    from chainermn_tpu.serving.speculative import SpeculativeBatcher

    engine = _serving_engine(model, params)
    draft_model = TransformerLM(vocab_size=64, d_model=16, n_heads=2,
                                n_layers=1, max_len=64)
    draft_params = draft_model.init(
        {"params": jax.random.PRNGKey(7),
         "dropout": jax.random.PRNGKey(8)},
        jnp.zeros((1, 8), jnp.int32),
    )
    draft = DecodeEngine(
        draft_model, draft_params,
        capacity=engine.capacity, page_size=engine.page_size,
        pages_per_slot=engine.pages_per_slot,
        num_pages=engine.cache.num_pages,
    )
    batcher = SpeculativeBatcher(engine, draft, k=k)
    return DecodeReplica(engine, journal, replica_index=pid,
                         n_replicas=nproc, batcher=batcher), batcher


def scenario_serving_spec_burst(pid, nproc, scratch, label, args):
    """ISSUE 17 fleet leg, phase 1: N speculative replicas (draft +
    target riding one allocator each) partition a shared-prefix stream;
    the schedule kills one replica at its 2nd ``serving.spec_verify``
    call — mid-burst, with draft proposals in flight, live shared pages
    (refcount > 1), and the target cache mid-reservation.  Survivors
    complete exactly their own shares; each checks its allocator drained
    clean (refcount invariants hold, every page back on the free list,
    in BOTH caches) — a speculative crash must not leak the survivors'
    sharing state."""
    from chainermn_tpu.serving.batcher import Request
    from chainermn_tpu.serving.replica import RequestJournal, claim

    n_requests = int(args.get("n_requests", 12))
    k = int(args.get("k", 4))
    model, params, stream = _spec_fixture(n_requests)
    journal = RequestJournal(os.path.join(scratch, "serve_journal"))
    if pid == 0:
        journal.submit_all([Request(p, m, id=i) for i, p, m in stream])
    journal.wait_until(len(stream))
    replica, batcher = _spec_replica(model, params, journal, pid, nproc,
                                     k)
    served = replica.serve()  # the victim dies inside (schedule spec)
    by_id = {r["id"]: r for r in journal.requests()}
    want = {r["id"] for r in claim(list(by_id.values()), pid, nproc)}
    assert set(served) == want, (sorted(served), sorted(want))
    # the speculative path actually ran, and sharing was live
    assert batcher.verify_steps > 0, "no verify step fired"
    assert batcher.prefix_hits >= 1, "shared prefix never aliased"
    # drained clean: refcounts walked back to zero, conservation holds
    for cache in (replica.engine.cache, batcher.draft.cache):
        cache.check_invariants()
        assert cache.used_pages == 0, cache.used_pages
    finish_and_exit({
        "served": sorted(served), "replica": pid,
        "verify_steps": batcher.verify_steps,
        "prefix_hits": batcher.prefix_hits,
        "tokens_proposed": batcher.tokens_proposed,
        "tokens_accepted": batcher.tokens_accepted,
    }, linger_s=float(args.get("linger_s", 1.5)))


def scenario_serving_spec_resume(pid, nproc, scratch, label, args):
    """Phase 2: the survivors re-form at the new replica count; the
    victim's pending share re-derives over ``seq % n_survivors`` and
    each resumed request serves SPECULATIVELY again — and every
    journaled request, phase-1 and resumed alike, matches a fresh
    single-engine plain-decode oracle bit-for-bit (greedy-exact
    acceptance makes the speculative transcript the plain transcript by
    construction, draft crash or no)."""
    from chainermn_tpu.serving.replica import RequestJournal, claim

    n_requests = int(args.get("n_requests", 12))
    k = int(args.get("k", 4))
    model, params, stream = _spec_fixture(n_requests)
    journal = RequestJournal(os.path.join(scratch, "serve_journal"))
    pending = journal.pending()
    pending_before = len(pending)
    assert pending_before > 0, "phase 1 should have left unserved work"
    my_share = {r["id"] for r in claim(pending, pid, nproc)}
    replica, batcher = _spec_replica(model, params, journal, pid, nproc,
                                     k)
    served = replica.serve()
    assert set(served) == my_share, (sorted(served), sorted(my_share))
    journal.wait_until_complete(n_requests)
    results = journal.results()
    assert sorted(results) == sorted(i for i, _p, _m in stream)
    oracle_eng = _serving_engine(model, params)
    mismatches = [
        rid for rid, prompt, max_new in stream
        if results[rid]["tokens"] != oracle_eng.generate(prompt, max_new)
    ]
    assert not mismatches, mismatches
    for cache in (replica.engine.cache, batcher.draft.cache):
        cache.check_invariants()
        assert cache.used_pages == 0, cache.used_pages
    return {
        "served": sorted(served), "replica": pid,
        "pending_before": pending_before,
        "completed": len(results),
        "bit_identical": True,
        "verify_steps": batcher.verify_steps,
        "prefix_hits": batcher.prefix_hits,
        "acceptance_rate": batcher.acceptance_rate,
    }


def scenario_serving_disagg(pid, nproc, scratch, label, args):
    """ISSUE 18 fleet leg: disaggregated role pools under a prefill
    death.  4 processes: pids 0/1 are the DECODE pool
    (``DisaggDecodeReplica``, ingesting published handoffs), pids 2/3
    the PREFILL pool (``seq % 2`` over the pool-scoped drain markers)
    — the victim must NOT be process 0, whose death would take the
    ``jax.distributed`` coordinator (and so every survivor) down with
    it.  The schedule kills prefill replica 0 (process 2) at its 4th
    ``serving.prefill`` call — mid-share, with handoffs published and
    the rest of its share unpublished.  Prefill replica 1 finishes its
    own share, then (after an idle grace with uncovered requests still
    pending) marks the dead replica draining in the PREFILL namespace
    and re-derives its share; publishing is idempotent, so a racing
    duplicate overwrites with identical bytes.  The decode pool never
    orphans (generous ``handoff_timeout_s``) — every request completes
    FROM A HANDOFF, bit-identical to the fresh single-engine oracle."""
    from chainermn_tpu.serving.batcher import Request
    from chainermn_tpu.serving.disagg import (
        DisaggDecodeReplica,
        PrefillReplica,
    )
    from chainermn_tpu.serving.replica import RequestJournal, claim

    assert nproc == 4, "scenario is shaped for 2 decode + 2 prefill"
    n_requests = int(args.get("n_requests", 12))
    grace_s = float(args.get("grace_s", 1.5))
    serve_timeout = float(args.get("serve_timeout_s", 240.0))
    model, params, stream = _serving_fixture(n_requests)
    journal = RequestJournal(os.path.join(scratch, "serve_journal"))
    if pid == 0:
        journal.submit_all([Request(p, m, id=i) for i, p, m in stream])
    journal.wait_until(len(stream))

    if pid in (2, 3):
        pr = PrefillReplica(
            _serving_engine(model, params), journal,
            replica_index=pid - 2, n_replicas=2, codec="bf16",
        )
        # process 2 dies inside (schedule spec); process 3 loops until
        # every still-pending request is covered by a handoff, marking
        # the dead replica draining after the idle grace
        marked = False
        deadline = time.monotonic() + grace_s
        while True:
            n = pr.prefill_round()
            todo = [d for d in journal.pending()
                    if not journal.has_handoff(d["id"])]
            if not todo:
                break
            if n > 0:
                deadline = time.monotonic() + grace_s
            elif not marked and time.monotonic() > deadline:
                # replica 0's share is uncovered and nothing claims it:
                # declare it dead in the prefill marker namespace
                journal.mark_draining(0, pool=pr.pool)
                marked = True
            else:
                time.sleep(0.05)
        finish_and_exit({
            "replica": pid - 2, "pool": "prefill",
            "published": pr.published, "rederived": marked,
            "wire_bytes": pr.wire_bytes,
        }, linger_s=float(args.get("linger_s", 1.5)))

    dr = DisaggDecodeReplica(
        _serving_engine(model, params), journal,
        replica_index=pid, n_replicas=2,
        handoff_timeout_s=float(args.get("handoff_timeout_s", 300.0)),
    )
    served = dr.serve(until_complete=n_requests, timeout_s=serve_timeout)
    by_id = {r["id"]: r for r in journal.requests()}
    want = {r["id"] for r in claim(list(by_id.values()), pid, 2)}
    assert set(served) == want, (sorted(served), sorted(want))
    # every request rode a handoff — the death never forced an orphan
    # fallback, and the allocator drained clean
    assert dr.local_prefills == 0, dr.local_prefills
    assert dr.ingested == len(served), (dr.ingested, len(served))
    dr.engine.cache.check_invariants()
    assert dr.engine.cache.used_pages == 0
    journal.wait_until_complete(n_requests)
    results = journal.results()
    assert sorted(results) == sorted(i for i, _p, _m in stream)
    oracle_eng = _serving_engine(model, params)
    mismatches = [
        rid for rid, prompt, max_new in stream
        if results[rid]["tokens"] != oracle_eng.generate(prompt, max_new)
    ]
    assert not mismatches, mismatches
    finish_and_exit({
        "replica": pid, "pool": "decode",
        "served": sorted(served), "ingested": dr.ingested,
        "local_prefills": dr.local_prefills,
        "completed": len(results), "bit_identical": True,
    }, linger_s=float(args.get("linger_s", 1.5)))


# ----------------------------------------------------------------------
def main():
    scenario, port, pid, nproc, scratch, label, args_json = sys.argv[1:8]
    pid, nproc = int(pid), int(nproc)
    args = json.loads(args_json)

    # process-targeted FaultSpec(process=k) resolves the index from this
    # env var — the launcher sets it, but belt-and-braces for direct use
    os.environ.setdefault("CHAINERMN_TPU_FAULT_PROCESS_INDEX", str(pid))

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        # older jax needs gloo selected explicitly for cross-process CPU
        # collectives; newer releases default to it (or drop the option)
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    jax.distributed.initialize(
        f"127.0.0.1:{port}", num_processes=nproc, process_id=pid
    )

    from chainermn_tpu import observability as obs
    from chainermn_tpu.resilience.log import JsonlFileSink, attach

    tel = obs.Telemetry(label=f"{label}_p{pid}")
    obs.install(tel)
    # the streaming sink: every fault/retry/reform/reshard event is on
    # disk the moment it is emitted, so even a `die` victim's record
    # survives for the merged FleetReport
    sink = JsonlFileSink(
        os.path.join(scratch, f"{label}_p{pid}_events.jsonl")
    )
    attach(sink)
    # opt-in host-protocol recorder (CHAINERMN_TPU_PROTOCOL_RECORD=1):
    # every obj-store exchange this worker issues is logged in order,
    # exported next to the trace for FleetReport.protocol_divergence
    from chainermn_tpu.resilience import protocol as _proto

    rec = _proto.install_from_env(
        label=f"{label}_p{pid}", rank=pid, world=nproc
    )
    _CTX.update(
        telemetry=tel,
        trace_path=os.path.join(scratch, f"{label}_p{pid}_trace.jsonl"),
        protocol=rec,
        protocol_path=os.path.join(
            scratch, f"{label}_p{pid}_protocol.jsonl"
        ),
    )

    out = globals()[f"scenario_{scenario}"](pid, nproc, scratch, label,
                                            args)
    _export_artifacts()
    print("RESULT " + json.dumps(out or {}), flush=True)


if __name__ == "__main__":
    main()

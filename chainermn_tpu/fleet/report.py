"""FleetReport: one post-mortem timeline for an N-process world.

Every fleet worker leaves two artifacts in the shared scratch:

* ``{label}_p{k}_events.jsonl`` — the streaming resilience sink
  (:class:`~chainermn_tpu.resilience.log.JsonlFileSink`): every fault,
  retry, reform, reshard, and restart, flushed per event so even a
  process that ``os._exit``s inside a ``die`` fault leaves its record;
  plus ``{label}_p{k}_trainer_events.jsonl``, the post-run export of
  ``trainer.resilience_log`` (events recorded directly on the trainer
  log — ``elastic_restart``, ``restart`` — never reach the global sink
  registry; the overlap between the two files is deduplicated here by
  the shared event timestamps).
* ``{label}_p{k}_trace.jsonl`` — the telemetry span timeline, exported
  with its wall-clock anchor row (``Timeline.to_jsonl(meta=True)``).

:class:`FleetReport` merges every process's artifacts across every leg
of a scenario into ONE wall-clock-ordered timeline, so a post-mortem
reads detect→decide→act→recover end to end: the ``die`` fault on leg-0
process 5, the lockstep retry of the torn agreement payload, the
re-formed world, the reshard, and the resumed run — in order, each
stamped with the leg and process it happened on.  :meth:`assert_order`
is the scenario-facing contract: the first occurrence of each named
kind must appear, in the given order.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, List, Optional

from ..resilience.log import ResilienceLog, event_row

_EVENTS_RE = re.compile(r"(?P<label>.+)_p(?P<pid>\d+)(?:_trainer)?_events\.jsonl$")
_TRACE_RE = re.compile(r"(?P<label>.+)_p(?P<pid>\d+)_trace\.jsonl$")
_PROTOCOL_RE = re.compile(r"(?P<label>.+)_p(?P<pid>\d+)_protocol\.jsonl$")


def export_resilience_log(log: ResilienceLog, path: str) -> str:
    """Write a log's events in the JSONL row shape the report parses
    (the post-run complement of the streaming sink, for events recorded
    directly on a trainer's log rather than emitted globally)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a", encoding="utf-8") as f:
        for ev in log:
            f.write(json.dumps(event_row(ev)) + "\n")
    return path


def _read_jsonl(path: str) -> List[dict]:
    rows = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue  # torn final line of a killed process
    except OSError:
        pass
    return rows


class FleetReport:
    """Merged, wall-clock-ordered fleet timeline.

    ``entries``: dicts with ``wall`` (float seconds), ``leg`` (the
    world label), ``process``, ``kind`` (resilience kind, or
    ``span:<name>`` for telemetry spans), ``site``, ``info``.
    """

    def __init__(self, entries: List[dict],
                 protocol: Optional[Dict[tuple, List[dict]]] = None):
        self.entries = sorted(entries, key=lambda e: e["wall"])
        # (leg, pid) -> ordered recorder rows from
        # {label}_p{k}_protocol.jsonl (the host-protocol recorder's
        # export) — kept apart from the wall-clock timeline because a
        # protocol is an ORDERED SEQUENCE contract, not an instant
        self.protocol: Dict[tuple, List[dict]] = protocol or {}

    # -- construction ---------------------------------------------------
    @classmethod
    def from_scratch(cls, scratch: str) -> "FleetReport":
        """Merge every ``*_events.jsonl`` / ``*_trace.jsonl`` under
        ``scratch`` (all legs, all processes)."""
        entries: List[dict] = []
        seen = set()
        for path in sorted(glob.glob(
                os.path.join(scratch, "*_events.jsonl"))):
            m = _EVENTS_RE.search(os.path.basename(path))
            if not m:
                continue
            label = m.group("label")
            for row in _read_jsonl(path):
                if "kind" not in row or "time" not in row:
                    continue
                # one event can appear in both the streaming sink and
                # the trainer-log export (emit fans out to both); the
                # shared event object means identical timestamps
                key = (label, row.get("process"), row["kind"],
                       row.get("site"), round(row.get("monotonic", 0.0), 7))
                if key in seen:
                    continue
                seen.add(key)
                entries.append({
                    "wall": float(row["time"]),
                    "leg": label,
                    "process": int(row.get("process", 0)),
                    "kind": row["kind"],
                    "site": row.get("site"),
                    "info": row.get("info") or {},
                })
        for path in sorted(glob.glob(
                os.path.join(scratch, "*_trace.jsonl"))):
            m = _TRACE_RE.search(os.path.basename(path))
            if not m:
                continue
            label = m.group("label")
            rows = _read_jsonl(path)
            wall0 = None
            for row in rows:
                if row.get("type") == "meta":
                    wall0 = float(row["args"]["wall0"])
                    break
            if wall0 is None:
                continue  # no anchor: cannot place on the shared clock
            for row in rows:
                if row.get("type") != "span":
                    continue  # resilience instants live in events files
                entries.append({
                    "wall": wall0 + float(row["t"]),
                    "leg": label,
                    "process": int(row.get("process", 0)),
                    "kind": f"span:{row['name']}",
                    "site": None,
                    "info": dict(row.get("args") or {},
                                 dur=row.get("dur")),
                })
        protocol: Dict[tuple, List[dict]] = {}
        for path in sorted(glob.glob(
                os.path.join(scratch, "*_protocol.jsonl"))):
            m = _PROTOCOL_RE.search(os.path.basename(path))
            if not m:
                continue
            rows = [r for r in _read_jsonl(path) if "token" in r]
            rows.sort(key=lambda r: r.get("seq", 0))
            protocol[(m.group("label"), int(m.group("pid")))] = rows
        return cls(entries, protocol)

    # -- queries --------------------------------------------------------
    def filter(self, *, legs: Optional[List[str]] = None,
               kinds: Optional[List[str]] = None,
               processes: Optional[List[int]] = None) -> "FleetReport":
        """A sub-report over a slice of the timeline — how a scenario
        points :meth:`assert_order`'s FIRST-OCCURRENCE semantics at one
        chain of interest (e.g. the legs a promotion ran on) when the
        full merged timeline contains earlier occurrences of the same
        kinds from unrelated legs.  ``None`` means no constraint."""
        legs_s = None if legs is None else {str(x) for x in legs}
        kinds_s = None if kinds is None else {str(x) for x in kinds}
        procs_s = (None if processes is None
                   else {int(x) for x in processes})
        return FleetReport([
            e for e in self.entries
            if (legs_s is None or e["leg"] in legs_s)
            and (kinds_s is None or e["kind"] in kinds_s)
            and (procs_s is None or e["process"] in procs_s)
        ], self.protocol)

    def between(self, t0: Optional[float] = None,
                t1: Optional[float] = None) -> "FleetReport":
        """A wall-clock slice ``[t0, t1]`` of the timeline (either end
        open when ``None``) — the complement of :meth:`filter` for
        isolating one leg's span of a shared-scratch run."""
        return FleetReport([
            e for e in self.entries
            if (t0 is None or e["wall"] >= float(t0))
            and (t1 is None or e["wall"] <= float(t1))
        ], self.protocol)

    def events(self, kind: Optional[str] = None) -> List[dict]:
        if kind is None:
            return list(self.entries)
        return [e for e in self.entries if e["kind"] == kind]

    def first(self, kind: str) -> Optional[dict]:
        for e in self.entries:
            if e["kind"] == kind:
                return e
        return None

    @property
    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.entries:
            out[e["kind"]] = out.get(e["kind"], 0) + 1
        return out

    @property
    def processes(self) -> Dict[str, List[int]]:
        """leg label -> sorted process indices that left any record."""
        out: Dict[str, set] = {}
        for e in self.entries:
            out.setdefault(e["leg"], set()).add(e["process"])
        return {k: sorted(v) for k, v in out.items()}

    def protocol_sequences(self, leg: Optional[str] = None
                           ) -> Dict[int, List[str]]:
        """pid -> ordered symmetric exchange tokens for ``leg`` (the
        only leg when ``None`` and unambiguous).  Tokens are the
        recorder's ``exchange|site`` / ``op|tag=..|peer=+d`` strings;
        by-design-asymmetric rows (peer-ckpt healing) are excluded,
        mirroring :meth:`ProtocolRecorder.signature`."""
        legs = sorted({l for (l, _pid) in self.protocol})
        if leg is None:
            if len(legs) > 1:
                raise ValueError(
                    f"protocol_sequences: multiple legs {legs}; pick one"
                )
            leg = legs[0] if legs else None
        return {
            pid: [r["token"] for r in rows if not r.get("asymmetric")]
            for (l, pid), rows in sorted(self.protocol.items())
            if l == leg
        }

    def protocol_divergence(self, leg: Optional[str] = None
                            ) -> Optional[dict]:
        """The first index where the per-process exchange sequences
        disagree: ``{"leg", "index", "tokens": {pid: token-or-None}}``,
        or ``None`` when every recorded process agrees (or fewer than
        two processes left a protocol file)."""
        seqs = self.protocol_sequences(leg)
        if len(seqs) < 2:
            return None
        legs = sorted({l for (l, _pid) in self.protocol})
        leg = leg if leg is not None else (legs[0] if legs else None)
        for i in range(max(len(s) for s in seqs.values())):
            toks = {pid: (s[i] if i < len(s) else None)
                    for pid, s in seqs.items()}
            if len(set(toks.values())) > 1:
                return {"leg": leg, "index": i, "tokens": toks}
        return None

    # -- contracts ------------------------------------------------------
    def assert_order(self, *kinds: str) -> List[dict]:
        """The first occurrence of each kind exists and the sequence is
        strictly wall-clock-ordered — the detect→decide→act→recover
        contract (e.g. ``fault_injected``, ``retry``,
        ``world_reformed``, ``elastic_reshard``, ``elastic_restart``).
        Returns the matched entries; raises ``AssertionError`` with the
        rendered post-mortem on any violation."""
        firsts = []
        for k in kinds:
            e = self.first(k)
            if e is None:
                raise AssertionError(
                    f"fleet report: no '{k}' event in the merged "
                    f"timeline (have {sorted(self.counts)})\n"
                    + self.post_mortem()
                )
            firsts.append(e)
        for a, b in zip(firsts, firsts[1:]):
            if not a["wall"] < b["wall"]:
                raise AssertionError(
                    f"fleet report: '{a['kind']}' "
                    f"(leg {a['leg']}, p{a['process']}) does not "
                    f"precede '{b['kind']}' (leg {b['leg']}, "
                    f"p{b['process']})\n" + self.post_mortem()
                )
        return firsts

    # -- rendering ------------------------------------------------------
    def post_mortem(self, max_rows: Optional[int] = 120,
                    include_spans: bool = False) -> str:
        """The human-readable merged timeline, times relative to the
        first entry."""
        rows = [e for e in self.entries
                if include_spans or not e["kind"].startswith("span:")]
        if not rows and not self.protocol:
            return "FleetReport(empty)"
        t0 = rows[0]["wall"] if rows else 0.0
        lines = [f"FleetReport: {len(rows)} event(s), "
                 f"legs {sorted({e['leg'] for e in rows})}"]
        shown = rows if max_rows is None else rows[:max_rows]
        for e in shown:
            info = "".join(
                f" {k}={v}" for k, v in sorted(e["info"].items())
                if v is not None
            )
            lines.append(
                f"  +{e['wall'] - t0:8.3f}s {e['leg']}/p{e['process']:<3d} "
                f"{e['kind']}"
                + (f" @{e['site']}" if e["site"] else "")
                + info
            )
        if max_rows is not None and len(rows) > max_rows:
            lines.append(f"  ... {len(rows) - max_rows} more")
        for leg in sorted({l for (l, _pid) in self.protocol}):
            div = self.protocol_divergence(leg)
            if div is not None:
                toks = ", ".join(
                    f"p{pid}={tok!r}"
                    for pid, tok in sorted(div["tokens"].items())
                )
                lines.append(
                    f"  protocol divergence on leg {leg} at exchange "
                    f"#{div['index']}: {toks}"
                )
        return "\n".join(lines)

    def to_jsonl(self, path: str) -> str:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            for e in self.entries:
                f.write(json.dumps(e, default=str) + "\n")
        return path

    def __len__(self):
        return len(self.entries)

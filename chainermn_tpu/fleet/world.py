"""FleetWorld: supervised launch of simulated 16-64-rank worlds.

Every multi-process scenario before this tier spawned 2 processes from
a test file; the fleet tier makes the launcher a *subsystem*: process
supervision with per-process output capture, shared-filesystem scratch,
the env wiring that delivers a :class:`~chainermn_tpu.fleet.schedule.
FaultSchedule` and the fault injector's per-process targeting index
into workers it cannot reach by object reference, and a bounded
wall-clock budget whose overrun tears the whole world down LOUDLY
(every process killed, every tail quoted) instead of letting a wedged
collective eat a CI job's full timeout.

The worlds are gloo-CPU ``jax.distributed`` processes (virtual CPU
devices standing in for per-host chips — the same substrate as the
2-proc mp tier, at production shape).  One core machine note: the
workers timeshare, so budgets are wall-clock generous; the budget is a
deadlock detector, not a performance assertion.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, NamedTuple, Optional, Sequence

from .schedule import ENV_SLICE, FaultSchedule

_FLEET_DIR = os.path.dirname(os.path.abspath(__file__))
DEFAULT_WORKER = os.path.join(_FLEET_DIR, "worker.py")
_REPO_ROOT = os.path.dirname(os.path.dirname(_FLEET_DIR))

# the injector's targeting index — must be set before any worker import
# can activate the env-spec injector (fault_injection._from_env)
ENV_PROCESS_INDEX = "CHAINERMN_TPU_FAULT_PROCESS_INDEX"


class FleetBudgetError(RuntimeError):
    """The world outlived its wall-clock budget and was torn down."""


# expect_exit sentinel for a preemption wave's SURVIVORS: the process
# must have finished its paperwork (printed RESULT), but its exit may
# be a clean 0 OR a runtime reap (negative: killed by signal) — when
# the wave's victims die, the coordination service's error propagation
# hard-aborts surviving peers, racing their exit.  Scenarios therefore
# publish results BEFORE the wave point and the launcher accepts either
# ending, exactly like a real preemption where survivors are reaped
# with the job.  A POSITIVE non-matching exit (a Python failure) still
# fails the world.
REAPED = "reaped"


class FleetProcResult(NamedTuple):
    process: int
    returncode: Optional[int]  # None: killed by the budget teardown
    output: str

    @property
    def payload(self) -> Optional[dict]:
        """The worker's ``RESULT <json>`` line, parsed (last one wins),
        or None when the process printed none (died, or by design)."""
        line = None
        for l in self.output.splitlines():
            if l.startswith("RESULT "):
                line = l
        if line is None:
            return None
        try:
            return json.loads(line[len("RESULT "):])
        except ValueError:
            return None

    def tail(self, n: int = 2000) -> str:
        return self.output[-n:]


class FleetResult:
    """One launched world's outcome: per-process results + helpers."""

    def __init__(self, label: str, scenario: str,
                 procs: List[FleetProcResult], elapsed_s: float,
                 budget_s: float):
        self.label = label
        self.scenario = scenario
        self.procs = procs
        self.elapsed_s = elapsed_s
        self.budget_s = budget_s

    def payloads(self) -> Dict[int, dict]:
        """process index -> RESULT payload, for processes that printed
        one."""
        return {p.process: p.payload for p in self.procs
                if p.payload is not None}

    def assert_ok(self, expect_exit: Optional[Dict[int, object]] = None
                  ) -> Dict[int, dict]:
        """Every process exited with its expected code (default 0;
        ``expect_exit`` overrides per process — how a preemption wave's
        victims assert their injected exit codes, and
        :data:`REAPED` marks its survivors), and every expected-0 or
        REAPED process printed a RESULT payload.  Returns the
        payloads."""
        expect_exit = expect_exit or {}
        problems = []
        for p in self.procs:
            want = expect_exit.get(p.process, 0)
            if want == REAPED:
                # paperwork done + (clean exit | runtime reap)
                if p.payload is None:
                    problems.append(
                        f"[{self.label}/{self.scenario}] process "
                        f"{p.process} (wave survivor) printed no RESULT "
                        f"before the reap\n--- tail ---\n{p.tail()}"
                    )
                elif p.returncode is not None and p.returncode > 0:
                    problems.append(
                        f"[{self.label}/{self.scenario}] process "
                        f"{p.process} (wave survivor) exited "
                        f"{p.returncode} — a failure, not a reap\n"
                        f"--- tail ---\n{p.tail()}"
                    )
                continue
            if p.returncode != want:
                problems.append(
                    f"[{self.label}/{self.scenario}] process {p.process} "
                    f"exited {p.returncode}, expected {want}\n"
                    f"--- tail ---\n{p.tail()}"
                )
            elif want == 0 and p.payload is None:
                problems.append(
                    f"[{self.label}/{self.scenario}] process {p.process} "
                    f"printed no RESULT\n--- tail ---\n{p.tail()}"
                )
        if problems:
            raise AssertionError("\n\n".join(problems))
        return self.payloads()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class FleetWorld:
    """Launch ``n_procs`` workers over a shared scratch, under a budget.

    ``schedule``: a :class:`FaultSchedule` rendered into each worker's
    env.  ``local_devices``: virtual CPU devices per process.
    ``budget_s``: hard wall-clock bound for the whole world — overrun
    kills every process and raises :class:`FleetBudgetError` quoting
    the schedule and every process's output tail.
    """

    def __init__(self, n_procs: int, scratch: str, *,
                 local_devices: int = 1, budget_s: float = 300.0,
                 schedule: Optional[FaultSchedule] = None,
                 extra_env: Optional[Dict[str, str]] = None,
                 label: str = "fleet",
                 worker: str = DEFAULT_WORKER):
        if n_procs < 1:
            raise ValueError(f"n_procs must be >= 1, got {n_procs}")
        self.n_procs = int(n_procs)
        self.scratch = str(scratch)
        self.local_devices = int(local_devices)
        self.budget_s = float(budget_s)
        self.schedule = schedule
        self.extra_env = dict(extra_env or {})
        self.label = label
        self.worker = worker
        os.makedirs(self.scratch, exist_ok=True)

    # -- env wiring -----------------------------------------------------
    def env_for(self, process_index: int) -> Dict[str, str]:
        """The spawned worker's environment: CPU-mesh substrate (ambient
        JAX_PLATFORMS popped — the host env may claim a real TPU), the
        repo on PYTHONPATH, the fault injector's targeting index, and
        the schedule's rendered specs."""
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={self.local_devices}"
        )
        env["PYTHONPATH"] = (
            _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        )
        env[ENV_PROCESS_INDEX] = str(process_index)
        if self.schedule is not None:
            env.update(self.schedule.env())
            if self.schedule.slice_size and self.local_devices != 1:
                # unit reconciliation: the schedule's slice_size counts
                # PROCESSES, but Topology.create's fake-slice grouping
                # counts DEVICE positions — with L local devices per
                # process the topology slice must span
                # slice_size * L device positions to group exactly the
                # processes the schedule's slice_loss will kill
                env[ENV_SLICE] = str(
                    self.schedule.slice_size * self.local_devices
                )
        env.update(self.extra_env)
        return env

    # -- launch ---------------------------------------------------------
    def start(self, scenario: str, args: Optional[dict] = None
              ) -> "FleetWorld":
        """Spawn the world WITHOUT blocking and return ``self``.

        The async half of :meth:`launch` — how a driver runs several
        worlds concurrently (the scale-up scenario: an N-proc training
        world plus 1-proc probe worlds publishing presence manifests
        into the same scratch).  Collect with :meth:`wait`; the budget
        clock starts here."""
        if getattr(self, "_pending", None) is not None:
            raise RuntimeError(
                f"fleet world '{self.label}' already started — wait() "
                "first"
            )
        port = _free_port()
        args_json = json.dumps(args or {})
        outs: list = []
        procs: list = []
        t0 = time.monotonic()
        try:
            for i in range(self.n_procs):
                out = open(os.path.join(
                    self.scratch, f"{self.label}_p{i}.out"), "w+b")
                outs.append(out)
                procs.append(subprocess.Popen(
                    [sys.executable, self.worker, scenario, str(port),
                     str(i), str(self.n_procs), self.scratch,
                     self.label, args_json],
                    env=self.env_for(i), stdout=out,
                    stderr=subprocess.STDOUT,
                ))
        except BaseException:
            # never leave a half-launched world running; close the
            # output file a failed Popen orphaned
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for out in outs:
                out.close()
            raise
        self._pending = (scenario, outs, procs, t0)
        return self

    def running(self) -> bool:
        """True while any started process is still alive."""
        if getattr(self, "_pending", None) is None:
            return False
        return any(p.poll() is None for p in self._pending[2])

    def wait(self, *, expect_exit: Optional[Dict[int, object]] = None
             ) -> FleetResult:
        """Block until the started world exits (or its budget — counted
        from :meth:`start` — expires), collect outputs, and return the
        :class:`FleetResult`."""
        if getattr(self, "_pending", None) is None:
            raise RuntimeError(
                f"fleet world '{self.label}' was never started"
            )
        scenario, outs, procs, t0 = self._pending
        self._pending = None
        try:
            deadline = t0 + self.budget_s
            pending = set(range(self.n_procs))
            while pending and time.monotonic() < deadline:
                for i in list(pending):
                    if procs[i].poll() is not None:
                        pending.discard(i)
                if pending:
                    time.sleep(0.05)
            if pending:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        pass
                raise FleetBudgetError(self._overrun_report(
                    scenario, outs, procs, time.monotonic() - t0,
                    sorted(pending),
                ))
        finally:
            # safety net for exceptional exits (interrupt): never
            # leave the world running
            for p in procs:
                if p.poll() is None:
                    p.kill()
            results = []
            for i, (p, out) in enumerate(zip(procs, outs)):
                out.flush()
                out.seek(0)
                text = out.read().decode("utf-8", "replace")
                out.close()
                results.append(FleetProcResult(i, p.poll(), text))
        result = FleetResult(self.label, scenario, results,
                             time.monotonic() - t0, self.budget_s)
        if expect_exit is not None:
            result.assert_ok(expect_exit)
        return result

    def launch(self, scenario: str, args: Optional[dict] = None,
               *, expect_exit: Optional[Dict[int, object]] = None
               ) -> FleetResult:
        """Spawn the world, wait under the budget, return the result.

        ``args`` is delivered to every worker as a JSON argv (the
        scenario's parameter block).  ``expect_exit`` forwards to
        :meth:`FleetResult.assert_ok` when given; without it the caller
        asserts explicitly.  Equivalent to ``start(...)`` + ``wait()``.
        """
        self.start(scenario, args)
        return self.wait(expect_exit=expect_exit)

    def _overrun_report(self, scenario: str, outs, procs,
                        elapsed: float, stuck: Sequence[int]) -> str:
        lines = [
            f"fleet world '{self.label}' scenario '{scenario}' "
            f"({self.n_procs} procs) exceeded its {self.budget_s:.0f}s "
            f"wall-clock budget (ran {elapsed:.1f}s); processes "
            f"{list(stuck)} never exited — world torn down.",
        ]
        if self.schedule is not None:
            lines.append(self.schedule.describe())
        for i, out in enumerate(outs):
            try:
                out.flush()
                out.seek(0)
                tail = out.read().decode("utf-8", "replace")[-1500:]
            except Exception:
                tail = "<unreadable>"
            rc = procs[i].poll()
            lines.append(f"--- process {i} (rc={rc}) tail ---\n{tail}")
        return "\n".join(lines)

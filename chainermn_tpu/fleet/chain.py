"""ElasticityChain: back-to-back N→M reshards under a numpy oracle.

PR 7's elastic tier proved ONE resize (2→1, ``spot_reclaim``).  A
production fleet reshards repeatedly — a preemption wave shrinks the
world, capacity comes back, another wave hits — and the claim that
matters is that the *composition* of reshards stays on the trajectory
the uninterrupted single world would have produced: the ZeRO blocked
leaves re-partition bit-identically at every leg, so the chain's final
params are the oracle's, not "close to" them.

:class:`ElasticityChain` drives that: each :class:`ChainLeg` is one
:class:`~chainermn_tpu.fleet.world.FleetWorld` launch of the
``chain_leg`` scenario (``fleet/worker.py``) over one shared scratch —
the first leg may carry a preemption wave (victims die mid-run, the
leg's snapshots are what survives), every later leg resumes through
``Trainer.run_elastic`` at its own world size and must land on
:func:`momentum_oracle`.  The merged
:class:`~chainermn_tpu.fleet.report.FleetReport` over the scratch then
shows the whole detect→retry→reform→reshard→resume story end to end.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from .report import FleetReport
from .schedule import FaultSchedule
from .world import REAPED, FleetWorld


def momentum_oracle(n_steps: int, *, lr: float = 0.1, mom: float = 0.9,
                    c: float = 0.5, dim: int = 4) -> List[np.ndarray]:
    """The single-world trajectory: sgd+momentum on grad ``w - c`` from
    ``w0 = 0``, simulated in numpy with no world at all.  Every chain
    leg's loss is built so its gradient is exactly this at ANY world
    size (see ``worker._chain_pieces``), which is what makes one
    world-free simulation the oracle for every resize point."""
    w = np.zeros(dim)
    v = np.zeros(dim)
    traj = []
    for _ in range(int(n_steps)):
        g = w - c
        v = mom * v + g
        w = w - lr * v
        traj.append(w.copy())
    return traj


class ChainLeg(NamedTuple):
    """One leg: a world size and the (absolute) iteration to reach.

    ``wave_at``/``wave_processes``: a preemption wave — the listed
    processes die at step ``wave_at`` (only legal on the first leg: a
    wave mid-chain would be a new chain over the surviving scratch).
    ``straggler``: ``{"process": k, "delay": s}`` — that process is
    slow for every step of the leg (resume legs attach a
    ``MetricsReport`` whose conviction rides back in the payload).
    ``torn_calls``: agreement-exchange call counts to tear this leg
    (lockstep-retried by the agreement stack).
    """

    n_procs: int
    n_steps: int
    wave_at: Optional[int] = None
    wave_processes: Tuple[int, ...] = ()
    straggler: Optional[dict] = None
    torn_calls: Tuple[int, ...] = ()


class ElasticityChain:
    """Drive the legs over one scratch; verify each against the oracle.

    ``budget_s`` bounds EACH leg's wall clock (the fleet worlds
    timeshare the host, so this is a deadlock detector, not a perf
    assertion).  ``run()`` returns ``{"legs": [per-leg payload dict],
    "report": FleetReport}``.
    """

    def __init__(self, scratch: str, legs: Sequence[ChainLeg], *,
                 lr: float = 0.1, mom: float = 0.9, dim: int = 4,
                 seed: int = 0, budget_s: float = 300.0,
                 linger_s: float = 1.5, report_every: int = 1,
                 exit_code: int = 43):
        if not legs:
            raise ValueError("a chain needs at least one leg")
        for k, leg in enumerate(legs):
            if leg.wave_at is not None:
                if k != 0:
                    raise ValueError(
                        f"leg {k}: a preemption wave is only legal on "
                        "the first leg (a mid-chain wave is a new "
                        "chain over the surviving scratch)"
                    )
                if not leg.wave_processes:
                    raise ValueError("wave_at set but no wave_processes")
                if 0 in leg.wave_processes:
                    raise ValueError(
                        "process 0 hosts the coordination service and "
                        "cannot be a wave victim (a real scheduler "
                        "restarts the coordinator host last)"
                    )
                if not 1 <= leg.wave_at <= leg.n_steps:
                    raise ValueError(
                        f"wave_at {leg.wave_at} outside 1..{leg.n_steps}"
                    )
                if max(leg.wave_processes) >= leg.n_procs:
                    raise ValueError(
                        f"wave targets {leg.wave_processes} outside the "
                        f"{leg.n_procs}-process world"
                    )
            elif leg.wave_processes:
                raise ValueError(f"leg {k}: wave_processes without wave_at")
        self.scratch = str(scratch)
        self.legs = list(legs)
        self.lr, self.mom, self.dim = float(lr), float(mom), int(dim)
        self.seed = int(seed)
        self.budget_s = float(budget_s)
        self.linger_s = float(linger_s)
        self.report_every = int(report_every)
        self.exit_code = int(exit_code)

    def _schedule_for(self, k: int, leg: ChainLeg,
                      resumed_from: int) -> FaultSchedule:
        sched = FaultSchedule(seed=self.seed)
        if leg.torn_calls:
            sched.torn_payload(leg.torn_calls)
        if leg.wave_at is not None:
            sched.preemption_wave(
                leg.wave_processes, window=(leg.wave_at, leg.wave_at),
                exit_code=self.exit_code,
            )
        if leg.straggler:
            # window in per-leg trainer.update calls: every step this
            # leg will actually run
            n_calls = max(leg.n_steps - resumed_from, 1)
            sched.straggler(
                int(leg.straggler["process"]), window=(1, n_calls),
                delay=float(leg.straggler.get("delay", 0.25)),
            )
        return sched

    def run(self) -> Dict:
        oracle = momentum_oracle(
            max(l.n_steps for l in self.legs),
            lr=self.lr, mom=self.mom, dim=self.dim,
        )
        payloads: List[Dict[int, dict]] = []
        resumed_from = 0
        prev_world: Optional[int] = None
        for k, leg in enumerate(self.legs):
            sched = self._schedule_for(k, leg, resumed_from)
            world = FleetWorld(
                leg.n_procs, self.scratch, label=f"leg{k}",
                schedule=sched, budget_s=self.budget_s,
            )
            args = {
                "n_steps": leg.n_steps, "wave_at": leg.wave_at,
                "lr": self.lr, "mom": self.mom, "dim": self.dim,
                "linger_s": self.linger_s,
                "straggler": bool(leg.straggler),
                "report_every": self.report_every,
            }
            if leg.wave_at is not None:
                # victims: their injected exit code, exactly; the
                # survivors publish results BEFORE the wave point and
                # may then be reaped by the runtime's peer-death
                # propagation (see worker.scenario_chain_leg)
                expect = {
                    p: (self.exit_code if p in leg.wave_processes
                        else REAPED)
                    for p in range(leg.n_procs)
                }
            else:
                expect = {}
            res = world.launch("chain_leg", args, expect_exit=expect)
            got = res.payloads()
            if leg.wave_at is not None:
                for pid, p in got.items():
                    assert p["steps_saved"] == leg.wave_at - 1, p
                resumed_from = leg.wave_at - 1
            else:
                # a chain may legally START with a plain leg: nothing
                # to resume yet, and run_elastic records restored_step
                # None for a fresh scratch
                want_resumed = resumed_from if resumed_from > 0 else None
                for pid, p in got.items():
                    assert p["oracle_match"] is True, (pid, p)
                    assert p["resumed_step"] == want_resumed, (pid, p)
                    if prev_world is not None and \
                            prev_world != leg.n_procs:
                        assert p["resized"] == [prev_world,
                                                leg.n_procs], (pid, p)
                    want_w = float(oracle[leg.n_steps - 1][0])
                    assert abs(p["final_w"] - want_w) < 1e-4, (pid, p)
                resumed_from = leg.n_steps
            payloads.append(got)
            prev_world = leg.n_procs
        return {
            "legs": payloads,
            "report": FleetReport.from_scratch(self.scratch),
        }

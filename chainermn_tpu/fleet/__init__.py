"""Fleet chaos tier: simulated 16-64-rank worlds with composable fault
schedules and elasticity chains.

Every other subsystem's multi-process claims were proven in 2-process
worlds; this tier is the first whose *subject is the system itself at
production shape*.  Four pieces:

* :class:`~chainermn_tpu.fleet.world.FleetWorld` — supervised launch of
  N gloo-CPU ``jax.distributed`` processes over a shared scratch, env
  wiring for the fault injector's per-process targeting, and a hard
  wall-clock budget with a loud teardown (every tail quoted) on
  overrun.
* :class:`~chainermn_tpu.fleet.schedule.FaultSchedule` — the DSL that
  composes the existing fault taxonomy into timed waves: preemption
  waves, correlated synthetic-slice loss, torn agreement payloads,
  stragglers that migrate between ranks across windows.
* :class:`~chainermn_tpu.fleet.chain.ElasticityChain` — back-to-back
  N→M reshards (e.g. 16→12→14) through ``Trainer.run_elastic``, every
  leg verified against the single-world numpy oracle
  (:func:`~chainermn_tpu.fleet.chain.momentum_oracle`) and the ZeRO
  bit-identity contract.
* :class:`~chainermn_tpu.fleet.report.FleetReport` — every process's
  telemetry export and resilience log merged into ONE wall-ordered
  timeline, with :meth:`~chainermn_tpu.fleet.report.FleetReport.
  assert_order` pinning the detect→retry→reform→reshard→resume story.

See docs/resilience.md ("Fleet chaos tier") and tests/README.md for
the test-tier split (the 16+-process scenarios are ``slow``; one
8-process smoke of the same machinery rides tier-1).
"""

from .chain import ChainLeg, ElasticityChain, momentum_oracle  # noqa: F401
from .report import FleetReport, export_resilience_log  # noqa: F401
from .schedule import FaultSchedule  # noqa: F401
from .world import (  # noqa: F401
    REAPED,
    FleetBudgetError,
    FleetProcResult,
    FleetResult,
    FleetWorld,
)

__all__ = [
    "ChainLeg",
    "ElasticityChain",
    "FaultSchedule",
    "FleetBudgetError",
    "FleetProcResult",
    "FleetReport",
    "FleetResult",
    "FleetWorld",
    "REAPED",
    "export_resilience_log",
    "momentum_oracle",
]

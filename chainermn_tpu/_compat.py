"""JAX version compatibility shims.

The framework targets the current jax API (``jax.shard_map`` with
``check_vma``); older runtimes (<= 0.4.x) ship the same machinery as
``jax.experimental.shard_map.shard_map`` with the ``check_rep`` spelling.
Importing ``chainermn_tpu`` installs a forwarding shim onto the ``jax``
module when (and only when) the attribute is missing, so every caller —
package modules, tests, examples — works unchanged on both.  On a jax
that already has ``jax.shard_map`` this module is a no-op.
"""

from __future__ import annotations

import jax

# True on the old-shard_map tier (<= 0.4.x).  Consumers: the hybrid
# DP x TP step must manually psum replicated-param cotangents there,
# because check_rep=False (the only mode whose out_specs validation
# accepts psum-built optimizer states) also disables the replication
# rewrite that inserts those psums in autodiff.
OLD_SHARD_MAP = not hasattr(jax, "shard_map")


def _install_axis_size_shim() -> None:
    from jax import lax

    if hasattr(lax, "axis_size"):
        return

    def axis_size(axis_name):
        # the classic pre-axis_size idiom: a psum of the literal 1 over
        # a bound axis constant-folds to the static axis size
        return lax.psum(1, axis_name)

    lax.axis_size = axis_size


def _install_shard_map_shim() -> None:
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs, check_vma=None, **kw):
        # check_vma=False maps directly onto check_rep=False.  A caller
        # that OMITS check_vma wants the current-jax vma machinery (the
        # hybrid DP x TP step) — old shard_map's check_rep=True cannot
        # statically infer replication for those out_specs (psum-built
        # optimizer states), so the closest working translation is
        # check_rep=False: gradients are still correct (the transpose
        # psums come from the out_specs, not the rep checker), only the
        # static replication VALIDATION is lost on this jax tier.
        kw.setdefault(
            "check_rep", bool(check_vma) if check_vma is not None else False
        )
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )

    jax.shard_map = shard_map


_install_shard_map_shim()
_install_axis_size_shim()

"""Global exception hook — distributed failure containment.

Reference parity: ``chainermn/global_except_hook.py`` — installs a
``sys.excepthook`` that prints the traceback and calls
``MPI_Abort(COMM_WORLD)``, so one crashed rank kills the whole job instead
of leaving the other ranks deadlocked inside a collective.

TPU-native redesign: the failure domain is the ``jax.distributed`` client.
On an uncaught exception the hook prints the traceback (prefixed with the
process index), best-effort shuts down the distributed client (which
releases the coordination service and makes peers fail fast instead of
hanging on the next collective), and exits non-zero.  Under a single
controller it degrades to print + exit.  An environment switch
``CHAINERMN_TPU_FORCE_ABORT_ON_EXCEPTION`` skips the graceful shutdown and
hard-exits, mirroring the reference's force-abort behavior.
"""

from __future__ import annotations

import os
import sys
import traceback

_hook_installed = False


def _global_except_hook(exctype, value, tb):
    try:
        pid = "?"
        try:
            import jax

            pid = str(jax.process_index())
        except Exception:
            pass
        sys.stderr.write(
            f"\n*** chainermn_tpu: uncaught exception on process {pid} — "
            "aborting the distributed job ***\n"
        )
        try:
            # Resilience taxonomy: an uncaught ResilienceError means the
            # retry/auto-resume layers gave up (or were not enabled);
            # print the structured diagnostics (site, peer, attempts,
            # elapsed) before the raw traceback so a wedged-job postmortem
            # starts with WHERE and HOW MANY TIMES, not a jax stack.
            from chainermn_tpu.resilience.errors import ResilienceError

            if isinstance(value, ResilienceError):
                sys.stderr.write(
                    f"*** resilience: {value.describe()} ***\n"
                )
        except Exception:
            pass
        traceback.print_exception(exctype, value, tb)
        sys.stderr.flush()
        if os.environ.get("CHAINERMN_TPU_FORCE_ABORT_ON_EXCEPTION"):
            os._exit(1)
        try:
            import jax

            jax.distributed.shutdown()
        except Exception:
            pass
    finally:
        os._exit(1)


def add_hook() -> None:
    """Install the hook (idempotent).  Parity:
    ``chainermn.global_except_hook.add_hook()``."""
    global _hook_installed
    if not _hook_installed:
        sys.excepthook = _global_except_hook
        _hook_installed = True


def remove_hook() -> None:
    global _hook_installed
    if _hook_installed:
        sys.excepthook = sys.__excepthook__
        _hook_installed = False

"""Tensor (operator) parallel layers.

Reference parity: the reference ships no ready-made sharded layers — its
docs show hand-building "parallel convolution"-style layers from the
collective functions (SURVEY.md section 2, row MP/TP).  These are those
patterns productized: Megatron-style column/row-parallel Dense pairs whose
collectives ride the ``axis_name`` mesh axis inside ``shard_map``.

ColumnParallelDense: Y = X @ [W1 | W2 | ...] — each chip holds a column
block; outputs are feature-sharded (no comm in forward;
``gather_output=True`` all-gathers).

RowParallelDense: Y = sum_i X_i @ W_i — inputs feature-sharded, one psum
in forward.  The canonical MLP block is Column(gather=False) -> activation
-> Row(): exactly one all-reduce per MLP, the Megatron recipe.

Under plain ``jit`` + GSPMD, prefer annotating an ordinary Dense's kernel
with ``PartitionSpec(None, 'tp')`` and letting the partitioner insert the
same collectives; these explicit modules are for shard_map-style code and
for teaching the cost model.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax


class ColumnParallelDense(nn.Module):
    """Dense whose output features are sharded across ``axis_name``.

    ``features`` is the *global* output width; each chip materializes
    ``features / axis_size`` columns.
    """

    features: int
    axis_name: str = "tp"
    use_bias: bool = True
    gather_output: bool = False
    dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        n = lax.axis_size(self.axis_name)
        if self.features % n:
            raise ValueError(
                f"features ({self.features}) not divisible by tp size {n}"
            )
        local = self.features // n
        # Per-chip init: fold the chip index into the RNG so column blocks
        # are independent draws (matches a sharded global init).
        kernel = self.param(
            "kernel", _sharded_init(self.kernel_init, self.axis_name),
            (x.shape[-1], local), jnp.float32,
        )
        y = x.astype(self.dtype) @ kernel.astype(self.dtype)
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros, (local,), jnp.float32
            )
            y = y + bias.astype(self.dtype)
        if self.gather_output:
            y = lax.all_gather(y, self.axis_name, axis=y.ndim - 1,
                               tiled=True)
        return y


class RowParallelDense(nn.Module):
    """Dense whose input features are sharded across ``axis_name``; the
    partial products are psum-reduced (one allreduce)."""

    features: int
    axis_name: str = "tp"
    use_bias: bool = True
    dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", _sharded_init(self.kernel_init, self.axis_name),
            (x.shape[-1], self.features), jnp.float32,
        )
        partial = x.astype(self.dtype) @ kernel.astype(self.dtype)
        y = lax.psum(partial, self.axis_name)
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros, (self.features,), jnp.float32
            )
            y = y + bias.astype(self.dtype)
        return y


# Extra markers the spec-derivation helpers recognize as column-/row-
# parallel owners, matched against a flax path segment EXACTLY.
# "ColumnParallel"/"RowParallel" always match as substrings (covering
# every auto-generated name like "ColumnParallelDense_0" — which is why
# the in-repo transformer modules deliberately do NOT rename their TP
# projections).  Users who do pass ``name=`` can register those names
# here; exact matching keeps an unrelated module named e.g. "audio_proj"
# from being silently mis-sharded.  Or build the spec tree by hand — it
# is plain data.
COLUMN_PARALLEL_NAMES: tuple = ()
ROW_PARALLEL_NAMES: tuple = ()
VOCAB_PARALLEL_NAMES: tuple = ()


def _path_keys(path):
    keys = [getattr(k, "key", getattr(k, "name", None)) for k in path]
    return [k for k in keys if isinstance(k, str)]


def _tp_owner_kind(keys) -> Optional[str]:
    """'col' / 'row' / 'vocab' / None for a flax param path, innermost
    match wins."""
    for k in reversed(keys):
        if "ColumnParallel" in k or k in COLUMN_PARALLEL_NAMES:
            return "col"
        if "RowParallel" in k or k in ROW_PARALLEL_NAMES:
            return "row"
        if "VocabParallel" in k or k in VOCAB_PARALLEL_NAMES:
            return "vocab"
    return None


class VocabParallelEmbed(nn.Module):
    """Embedding table sharded over the vocab dimension (Megatron's
    VocabParallelEmbedding): chip ``i`` holds rows
    ``[i*V/n, (i+1)*V/n)``.  Lookup masks out-of-range tokens locally and
    psums the partial embeddings — one allreduce; ``attend(x)`` is the
    weight-tied output head, returning the LOCAL vocab block's logits
    (feed them to :func:`vocab_parallel_cross_entropy` / the model-level
    ``vp_lm_loss``, which never materialize the full-vocab row)."""

    vocab_size: int
    features: int
    axis_name: str = "tp"
    dtype: Any = jnp.float32
    embedding_init: Callable = nn.initializers.normal(0.02)

    def setup(self):
        n = lax.axis_size(self.axis_name)
        if self.vocab_size % n:
            raise ValueError(
                f"vocab_size ({self.vocab_size}) not divisible by the "
                f"'{self.axis_name}' axis size ({n})"
            )
        self.embedding = self.param(
            "embedding",
            _sharded_init(self.embedding_init, self.axis_name),
            (self.vocab_size // n, self.features), jnp.float32,
        )

    def _range(self):
        local_v = self.embedding.shape[0]
        start = lax.axis_index(self.axis_name) * local_v
        return start, local_v

    def __call__(self, tokens):
        start, local_v = self._range()
        local = tokens - start
        in_range = (local >= 0) & (local < local_v)
        safe = jnp.clip(local, 0, local_v - 1)
        out = jnp.take(self.embedding, safe, axis=0)
        out = jnp.where(in_range[..., None], out, 0)
        return lax.psum(out.astype(self.dtype), self.axis_name)

    def attend(self, x):
        """(..., features) -> (..., local_vocab) logits against this
        chip's vocab block (the tied head; no collective here)."""
        return x @ self.embedding.T.astype(x.dtype)


def vocab_parallel_cross_entropy(logits_local: jnp.ndarray,
                                 targets: jnp.ndarray,
                                 axis_name: str) -> jnp.ndarray:
    """Per-position cross entropy from vocab-sharded logits.

    ``logits_local``: (..., V/n) — this chip's vocab block;
    ``targets``: (...) global token ids.  The softmax statistics are
    assembled with one pmax and two psums; the (..., V) full-vocab row
    never exists on any chip (Megatron's parallel cross entropy).
    """
    local_v = logits_local.shape[-1]
    start = lax.axis_index(axis_name) * local_v
    logits_f = logits_local.astype(jnp.float32)
    # the max is a pure numerical-stability shift (lse is exactly
    # invariant to it), so stopping its gradient is exact — and pmax has
    # no differentiation rule anyway
    m = lax.pmax(
        lax.stop_gradient(jnp.max(logits_f, axis=-1)), axis_name
    )
    z = lax.psum(
        jnp.sum(jnp.exp(logits_f - m[..., None]), axis=-1), axis_name
    )
    lse = m + jnp.log(z)
    local_t = targets - start
    in_range = (local_t >= 0) & (local_t < local_v)
    safe = jnp.clip(local_t, 0, local_v - 1)
    picked = jnp.take_along_axis(
        logits_f, safe[..., None], axis=-1
    )[..., 0]
    target_logit = lax.psum(jnp.where(in_range, picked, 0.0), axis_name)
    return lse - target_logit


def _tp_leaf_spec(keys, model_axis):
    """The Megatron sharding convention for one flax param path, or None
    when the leaf belongs to no column/row-parallel owner: column kernels
    shard output features (``P(None, axis)``, bias ``P(axis)``), row
    kernels shard input features (``P(axis, None)``, bias replicated)."""
    from jax.sharding import PartitionSpec as P

    last = keys[-1] if keys else ""
    kind = _tp_owner_kind(keys)
    if kind == "col":
        return P(None, model_axis) if last == "kernel" else P(model_axis)
    if kind == "row":
        return P(model_axis, None) if last == "kernel" else P()
    if kind == "vocab":
        return P(model_axis, None) if last == "embedding" else P()
    return None


def megatron_param_specs(params, model_axis: str = "tp"):
    """Derive the ``param_specs`` pytree for ``build_train_step``'s hybrid
    DP x TP mode from a parameter tree containing Column/RowParallelDense
    modules (auto-generated names matched by substring, plus the exact
    path segments in ``COLUMN_PARALLEL_NAMES`` / ``ROW_PARALLEL_NAMES``).

    Column kernels shard their output features (``P(None, axis)``, bias
    ``P(axis)``); Row kernels shard their input features
    (``P(axis, None)``, bias replicated); VocabParallelEmbed tables shard
    their vocab rows (``P(axis, None)``); everything else replicates.
    For custom-named modules, register the name in the ``*_NAMES``
    tuples above or build the spec tree by hand — it is plain data.
    """
    from jax.sharding import PartitionSpec as P
    import jax.tree_util as jtu

    def leaf_spec(path, leaf):
        spec = _tp_leaf_spec(_path_keys(path), model_axis)
        return P() if spec is None else spec

    return jtu.tree_map_with_path(leaf_spec, params)


def tp_flow_specs(params, model_axis: str = "tp",
                  batch_spec=None) -> dict:
    """The tensor-parallel step's sharding declaration for the analysis
    pass (``analysis.shardflow``): the Megatron param layout
    (:func:`megatron_param_specs`) bundled with the activation/batch
    layout so the sharding-flow pass can seed a hybrid DP x TP step's
    invars in one call.  Activations between TP blocks are replicated
    along features by construction (Column(gather=False) -> Row ends in
    its psum), which is why a correctly-composed Megatron block adds no
    partitioner-inserted collectives — the attribution check's
    invariant for this layout."""
    from jax.sharding import PartitionSpec as P

    return {
        "params": megatron_param_specs(params, model_axis),
        "batch": P() if batch_spec is None else batch_spec,
        "out": P(),
    }


def sharded_init(init_fn: Callable, mesh, in_specs, param_specs_fn,
                 *args):
    """Initialize a model whose parameters live sharded on ``mesh``.

    Runs ``init_fn(*args) -> params`` per-shard under ``shard_map`` twice:
    once abstractly (``eval_shape``) to discover the parameter tree, once
    for real with ``out_specs = param_specs_fn(abstract_params)`` so
    sharded leaves (TP kernels, expert blocks) assemble into global arrays
    while replicated leaves stay replicated.  Returns ``(params, specs)``
    — feed both to ``build_train_step(param_specs=specs)``.

    ``in_specs``: PartitionSpec(s) for ``*args`` (e.g. the sample batch's
    layout).  ``init_fn`` typically closes over the module and RNG key:
    ``lambda x: model.init(jax.random.PRNGKey(0), x)``.
    """
    from jax.sharding import PartitionSpec as P

    abstract = jax.eval_shape(
        jax.shard_map(
            init_fn, mesh=mesh, in_specs=in_specs, out_specs=P(),
            check_vma=False,
        ),
        *args,
    )
    specs = param_specs_fn(abstract)
    params = jax.jit(
        jax.shard_map(
            init_fn, mesh=mesh, in_specs=in_specs, out_specs=specs,
            check_vma=False,
        )
    )(*args)
    return params, specs


def _sharded_init(init: Callable, axis_name: str) -> Callable:
    """Make an initializer draw a different block per chip (fold the axis
    index into the key) while staying deterministic per chip."""

    def wrapped(key, shape, dtype=jnp.float32):
        try:
            idx = lax.axis_index(axis_name)
            key = jax.random.fold_in(key, idx)
        except NameError:
            pass  # single-device init outside shard_map
        return init(key, shape, dtype)

    return wrapped

"""Ring attention — sequence/context parallelism over ICI.

The reference predates attention entirely (SURVEY.md section 5.7): its
closest primitives are the differentiable p2p send/recv
(point_to_point_communication.py) and the hidden-state-streaming RNN.
This module is the modern capability those primitives point at: shard the
*sequence* across chips and compute exact attention by rotating key/value
blocks around the ICI ring (Liu et al., "Ring Attention with Blockwise
Transformers"), overlapping each block's compute with the next block's
transfer.

Design: runs inside ``shard_map`` with queries resident and K/V blocks
circulating via ``lax.ppermute``; softmax is computed online (running max
and normalizer), so memory is O(seq_shard) regardless of total sequence
length.  Causal masking uses the ring step to decide block visibility —
entire future blocks are skipped numerically (their contribution is
masked), keeping control flow static for XLA.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _block_attend(q, k, v, bias, scale):
    """One (q_block, k_block) attention partial: returns (unnormalized
    numerator, running max, running denominator) pieces."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1, keepdims=True)  # (b, h, q, 1)
    p = jnp.exp(s - m)
    num = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    den = jnp.sum(p, axis=-1, keepdims=True)  # (b, h, q, 1)
    return num, den, m


_BLOCK_NEG = -1e30  # finite "minus infinity": exp() underflows cleanly


def _ring_flash(q, k, v, axis_name, causal, scale, block_q, block_k,
                interpret):
    """Ring attention with the Pallas flash kernel as the per-block core.

    Each ring step runs :func:`flash_attention_with_lse` on the resident
    queries against the circulating K/V block; partial outputs merge
    exactly via their log-sum-exp.  Causality at block granularity: the
    diagonal block (owner == self) runs the kernel's causal mode, blocks
    entirely in the past run full attention, blocks entirely in the
    future are skipped (a runtime branch — each chip takes its own).
    Gradients flow through the merge weights because the lse output is
    differentiable (its VJP rides the same backward kernels).
    """
    from chainermn_tpu.ops.pallas_attention import flash_attention_with_lse

    if causal and q.shape[1] != k.shape[1]:
        # Block-granular causality classifies whole blocks by owner
        # index, which is only a global-position mask when q and k
        # shards are the same length; the plain path masks by global
        # position and handles the ragged case.
        raise ValueError(
            f"ring flash with causal=True needs equal q/k shard lengths "
            f"(got {q.shape[1]} vs {k.shape[1]}); use use_flash=False"
        )
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)

    def block(kb, vb, blk_causal):
        return flash_attention_with_lse(
            q, kb, vb, blk_causal, scale, block_q, block_k, interpret
        )

    def step_out(kb, vb, owner):
        if not causal:
            return block(kb, vb, False)

        def diag(args):
            return block(*args, True)

        def full(args):
            return block(*args, False)

        def skip(args):
            del args
            o = (q * 0).astype(q.dtype)
            # (b, s, h) in the kernel's fp32 lse dtype, q's vma
            lse = (q[..., 0] * 0).astype(jnp.float32) + _BLOCK_NEG
            return o, lse

        return lax.cond(
            owner == my, diag,
            lambda a: lax.cond(owner < my, full, skip, a),
            (kb, vb),
        )

    def body(carry, step):
        kb, vb, o, lse = carry
        owner = (my - step) % n
        o_b, lse_b = step_out(kb, vb, owner)
        # exact two-way online-softmax merge via log-sum-exp
        m = jnp.maximum(lse, lse_b)
        w = jnp.exp(lse - m)
        w_b = jnp.exp(lse_b - m)
        den = w + w_b
        o = (
            o * (w / den)[..., None]
            + o_b.astype(jnp.float32) * (w_b / den)[..., None]
        )
        lse = m + jnp.log(den)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (kb, vb, o, lse), None

    o0 = (q * 0).astype(jnp.float32)
    lse0 = (q[..., 0] * 0).astype(jnp.float32) + _BLOCK_NEG
    (_, _, o, _), _ = lax.scan(body, (k, v, o0, lse0), jnp.arange(n))
    return o.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    use_flash: Optional[bool] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Exact attention over a sequence sharded along ``axis_name``.

    Args:
      q, k, v: (batch, seq_shard, heads, head_dim) — the local sequence
        block of each chip.  Must be called inside ``shard_map`` with the
        sequence axis bound to ``axis_name``.
      causal: apply a causal mask consistent with the *global* sequence
        order (shard r holds positions [r*S, (r+1)*S)).
      use_flash: run each per-block attention through the Pallas flash
        kernel (:func:`~chainermn_tpu.ops.flash_attention_with_lse`),
        merging blocks via their log-sum-exp — the long-context
        performance tier.  ``None`` auto-selects: flash on a TPU backend
        when the local sequence shard fills a lane tile (>= 128).
        ``block_q``/``block_k``/``interpret`` configure the kernel.
    Returns:
      (batch, seq_shard, heads, head_dim) attention output for the local
      queries, numerically identical to full attention over the gathered
      sequence.
    """
    if use_flash is None:
        try:
            from chainermn_tpu.ops.pallas_attention import PALLAS_AVAILABLE
        except ImportError:  # pragma: no cover
            PALLAS_AVAILABLE = False
        use_flash = (
            PALLAS_AVAILABLE
            and jax.default_backend() == "tpu"
            and q.shape[1] >= 128
            and k.shape[1] >= 128
            and (not causal or q.shape[1] == k.shape[1])
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if use_flash:
        return _ring_flash(q, k, v, axis_name, causal, scale, block_q,
                           block_k, interpret)
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s_q, h, d = q.shape
    s_k = k.shape[1]

    neg = jnp.asarray(jnp.finfo(q.dtype).min, q.dtype)

    def causal_bias(kv_owner):
        """Bias for my query block attending kv_owner's key block."""
        # global positions: q_pos = my*s_q + i ; k_pos = kv_owner*s_k + j
        qi = my * s_q + jnp.arange(s_q)[:, None]
        kj = kv_owner * s_k + jnp.arange(s_k)[None, :]
        return jnp.where(qi >= kj, 0.0, neg).astype(q.dtype)[None, None]

    def body(carry, step):
        kb, vb, num, den, mx = carry
        owner = (my - step) % n  # whose block we currently hold
        bias = causal_bias(owner) if causal else None
        bnum, bden, bm = _block_attend(q, kb, vb, bias, scale)
        # online softmax merge
        new_m = jnp.maximum(mx, bm)
        corr_old = jnp.exp(mx - new_m)
        corr_new = jnp.exp(bm - new_m)
        # (b,h,q,1) -> (b,q,h,1) to broadcast against num's (b,q,h,d)
        num = num * jnp.swapaxes(corr_old, 1, 2) + bnum * jnp.swapaxes(
            corr_new, 1, 2
        )
        den = den * corr_old + bden * corr_new
        # rotate K/V to the next chip (overlaps with next iteration's
        # compute under XLA's async collective scheduling)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (kb, vb, num, den, new_m), None

    # Derive the initial carries from q (x*0 keeps the varying-manual-axes
    # marking that fresh zeros would lack — required by vma-checked
    # shard_map, whose scan demands carry-in/carry-out vma equality).
    num0 = q * 0
    col0 = jnp.swapaxes(q[..., :1] * 0, 1, 2)  # (b, h, s_q, 1), q's vma
    den0 = col0
    m0 = col0 + neg
    (_, _, num, den, _), _ = lax.scan(
        body, (k, v, num0, den0, m0), jnp.arange(n)
    )
    out = num / jnp.swapaxes(jnp.maximum(den, 1e-20), 1, 2)
    return out.astype(q.dtype)

"""Ring attention — sequence/context parallelism over ICI.

The reference predates attention entirely (SURVEY.md section 5.7): its
closest primitives are the differentiable p2p send/recv
(point_to_point_communication.py) and the hidden-state-streaming RNN.
This module is the modern capability those primitives point at: shard the
*sequence* across chips and compute exact attention by rotating key/value
blocks around the ICI ring (Liu et al., "Ring Attention with Blockwise
Transformers"), overlapping each block's compute with the next block's
transfer.

Design: runs inside ``shard_map`` with queries resident and K/V blocks
circulating via ``lax.ppermute``; softmax is computed online (running max
and normalizer), so memory is O(seq_shard) regardless of total sequence
length.  Causal masking uses the ring step to decide block visibility —
entire future blocks are skipped numerically (their contribution is
masked), keeping control flow static for XLA.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _block_attend(q, k, v, bias, scale):
    """One (q_block, k_block) attention partial: returns (unnormalized
    numerator, running max, running denominator) pieces."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1, keepdims=True)  # (b, h, q, 1)
    p = jnp.exp(s - m)
    num = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    den = jnp.sum(p, axis=-1, keepdims=True)  # (b, h, q, 1)
    return num, den, m


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Exact attention over a sequence sharded along ``axis_name``.

    Args:
      q, k, v: (batch, seq_shard, heads, head_dim) — the local sequence
        block of each chip.  Must be called inside ``shard_map`` with the
        sequence axis bound to ``axis_name``.
      causal: apply a causal mask consistent with the *global* sequence
        order (shard r holds positions [r*S, (r+1)*S)).
    Returns:
      (batch, seq_shard, heads, head_dim) attention output for the local
      queries, numerically identical to full attention over the gathered
      sequence.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b, s_q, h, d = q.shape
    s_k = k.shape[1]

    neg = jnp.asarray(jnp.finfo(q.dtype).min, q.dtype)

    def causal_bias(kv_owner):
        """Bias for my query block attending kv_owner's key block."""
        # global positions: q_pos = my*s_q + i ; k_pos = kv_owner*s_k + j
        qi = my * s_q + jnp.arange(s_q)[:, None]
        kj = kv_owner * s_k + jnp.arange(s_k)[None, :]
        return jnp.where(qi >= kj, 0.0, neg).astype(q.dtype)[None, None]

    def body(carry, step):
        kb, vb, num, den, mx = carry
        owner = (my - step) % n  # whose block we currently hold
        bias = causal_bias(owner) if causal else None
        bnum, bden, bm = _block_attend(q, kb, vb, bias, scale)
        # online softmax merge
        new_m = jnp.maximum(mx, bm)
        corr_old = jnp.exp(mx - new_m)
        corr_new = jnp.exp(bm - new_m)
        # (b,h,q,1) -> (b,q,h,1) to broadcast against num's (b,q,h,d)
        num = num * jnp.swapaxes(corr_old, 1, 2) + bnum * jnp.swapaxes(
            corr_new, 1, 2
        )
        den = den * corr_old + bden * corr_new
        # rotate K/V to the next chip (overlaps with next iteration's
        # compute under XLA's async collective scheduling)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (kb, vb, num, den, new_m), None

    # Derive the initial carries from q (x*0 keeps the varying-manual-axes
    # marking that fresh zeros would lack — required by vma-checked
    # shard_map, whose scan demands carry-in/carry-out vma equality).
    num0 = q * 0
    col0 = jnp.swapaxes(q[..., :1] * 0, 1, 2)  # (b, h, s_q, 1), q's vma
    den0 = col0
    m0 = col0 + neg
    (_, _, num, den, _), _ = lax.scan(
        body, (k, v, num0, den0, m0), jnp.arange(n)
    )
    out = num / jnp.swapaxes(jnp.maximum(den, 1e-20), 1, 2)
    return out.astype(q.dtype)

"""Expert parallelism: Mixture-of-Experts over an ``expert`` mesh axis.

The reference has no MoE, but its differentiable ``alltoall``
(``chainermn/functions/collective_communication.py``, SURVEY.md section 2
#19 and the parallelism table: "EP: `alltoall` is the primitive it would
need") is exactly the dispatch/combine exchange expert parallelism is
built from.  This module is that capability, TPU-native:

* Experts are sharded across the chips of one mesh axis; each chip holds
  ``num_experts / axis_size`` expert parameter sets.
* A token's route is decided by a learned router (top-1 "Switch" or
  top-2 "GShard" style) with a static capacity — shapes stay fixed so the
  whole layer jits once; overflow tokens are dropped (standard MoE
  semantics) and flow through the residual connection.
* Dispatch and return are each ONE ``lax.all_to_all`` riding ICI; the
  expert compute between them is a batched matmul over
  ``(local_experts, axis_size * capacity, d)`` blocks — MXU-shaped.

Everything is differentiable end to end (all_to_all's transpose is
all_to_all in the reverse direction; XLA generates it), so the router
learns through the combine weights exactly as in GShard/Switch.
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def compute_capacity(tokens: int, num_experts: int, k: int,
                     capacity_factor: float) -> int:
    """Static per-expert queue length for ``tokens`` routed k ways."""
    return max(int(math.ceil(tokens * k * capacity_factor / num_experts)), 1)


class RoutePlan(NamedTuple):
    """Static-shape routing decision shared by both dispatch backends.

    chosen/gates/slot/keep are (tokens, k): expert index, re-normalized
    gate, queue position within that expert, and survives-capacity flag
    for each of a token's k routes; raw_routes is the (tokens,
    num_experts) pre-capacity indicator for the balance loss.
    """

    chosen: jnp.ndarray
    gates: jnp.ndarray
    slot: jnp.ndarray
    keep: jnp.ndarray
    raw_routes: jnp.ndarray


def route_plan(probs: jnp.ndarray, k: int, capacity: int) -> RoutePlan:
    """Top-k routing with static per-expert capacity.

    Queue positions count earlier claims on the same expert in
    route-major then token order (all first choices before all second
    choices — GShard's ordering, so a token's secondary route is dropped
    before any primary route).
    """
    t, e = probs.shape
    if k > e:
        raise ValueError(f"k ({k}) cannot exceed num_experts ({e})")
    # lax.top_k guarantees k distinct indices with values read from the
    # original row — no hand-rolled argmax-and-mask loop needed.
    gate_arr, chosen_arr = lax.top_k(probs, k)
    onehots = [
        jax.nn.one_hot(chosen_arr[:, j], e, dtype=jnp.int32)
        for j in range(k)
    ]
    gate_sum = jnp.sum(gate_arr, axis=1, keepdims=True) if k > 1 else None
    gates = (
        gate_arr / (gate_sum + 1e-9) if gate_sum is not None else gate_arr
    )
    slots, keeps = [], []
    prior = jnp.zeros((e,), jnp.int32)
    for oh in onehots:
        pos = jnp.cumsum(oh, axis=0) - oh  # earlier tokens, this route
        pos = pos + prior[None, :]  # plus all earlier routes
        prior = prior + jnp.sum(oh, axis=0)
        slot = jnp.sum(pos * oh, axis=-1)  # (tokens,)
        slots.append(slot)
        keeps.append(slot < capacity)
    raw_routes = sum(oh.astype(probs.dtype) for oh in onehots)
    return RoutePlan(
        chosen=chosen_arr,
        gates=gates.astype(probs.dtype),
        slot=jnp.stack(slots, axis=1),
        keep=jnp.stack(keeps, axis=1),
        raw_routes=raw_routes,
    )


def top_k_routing(
    probs: jnp.ndarray, k: int, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Build DENSE dispatch mask and combine weights from router
    probabilities — the einsum backend's (tokens, num_experts, capacity)
    tensors.  O(t*e*cap) memory; prefer :func:`route_plan` +
    :func:`scatter_dispatch` at scale.

    Returns:
      dispatch: (tokens, num_experts, capacity) one-hot {0,1} — token t
        occupies slot c of expert e's queue.
      combine: same shape, dispatch scaled by the (re-normalized) router
        probability of the chosen expert.
      raw_routes: (tokens, num_experts) pre-capacity route indicator (sum
        of the k choice one-hots) — feed this, not dispatch, to
        :func:`load_balancing_loss` so dropped claims still count.
    """
    plan = route_plan(probs, k, capacity)
    dispatch, combine = _dense_masks(plan, probs.shape[1], capacity,
                                     probs.dtype)
    return dispatch, combine, plan.raw_routes


def _dense_masks(plan: RoutePlan, e: int, capacity: int, dtype):
    t, k = plan.chosen.shape
    dispatch = jnp.zeros((t, e, capacity), dtype)
    combine = jnp.zeros((t, e, capacity), dtype)
    for j in range(k):
        oh = jax.nn.one_hot(plan.chosen[:, j], e, dtype=dtype)
        oh_slot = jax.nn.one_hot(plan.slot[:, j], capacity, dtype=dtype)
        d = oh[:, :, None] * oh_slot[:, None, :]
        keep = plan.keep[:, j].astype(dtype)
        dispatch = dispatch + d * keep[:, None, None]
        # gates are fp32; cast the per-route weight so the (t, e, cap)
        # combine tensor stays in the requested dtype (and CSEs with the
        # dispatch mask instead of silently promoting to fp32)
        w = (plan.gates[:, j].astype(dtype) * keep)
        combine = combine + d * w[:, None, None]
    return dispatch, combine


def scatter_dispatch(x: jnp.ndarray, plan: RoutePlan, num_experts: int,
                     capacity: int) -> jnp.ndarray:
    """Token rows into per-expert queues via scatter — O(t*k*d) work.

    The einsum backend builds the same (num_experts, capacity, d) queues
    as ``einsum('td,tec->ecd', x, dispatch)``, which costs
    t*e*cap*d FLOPs (a full matmul against a one-hot operand); at LM
    scale that rivals the model's own FLOPs.  Queue slots are unique by
    construction (the cumulative-position assignment), so this is a
    collision-free scatter.
    """
    t, d = x.shape
    k = plan.chosen.shape[1]
    dump = num_experts * capacity  # dropped routes land here
    dest = jnp.where(
        plan.keep, plan.chosen * capacity + plan.slot, dump
    )  # (t, k)
    queues = jnp.zeros((num_experts * capacity + 1, d), x.dtype)
    # route-major flattening pairs dest[:, j] with the token rows
    queues = queues.at[dest.T.reshape(-1)].set(
        jnp.tile(x, (k, 1)), mode="drop"
    )
    return queues[:-1].reshape(num_experts, capacity, d)


def gather_dispatch(x: jnp.ndarray, plan: RoutePlan, num_experts: int,
                    capacity: int) -> jnp.ndarray:
    """Token rows into queues via an id-scatter + row GATHER.

    :func:`scatter_dispatch` scatters t*k d-wide rows; here only t*k
    int32 token ids are scattered (into a (e*cap,) slot->token map) and
    the queue rows are then one contiguous gather — trading the
    random-access pattern from the wide write to the narrow one, which
    is the cheaper side on TPU.  Empty/dropped slots map to a zero pad
    row.  Numerics identical to both other backends.
    """
    t, d = x.shape
    k = plan.chosen.shape[1]
    dump = num_experts * capacity  # dropped routes land here
    dest = jnp.where(
        plan.keep, plan.chosen * capacity + plan.slot, dump
    )  # (t, k)
    ids = jnp.full((num_experts * capacity + 1,), t, jnp.int32)
    token_ids = jnp.tile(jnp.arange(t, dtype=jnp.int32), (k,))
    ids = ids.at[dest.T.reshape(-1)].set(token_ids, mode="drop")
    x_pad = jnp.concatenate([x, jnp.zeros((1, d), x.dtype)])
    return x_pad[ids[:-1]].reshape(num_experts, capacity, d)


def scatter_combine(out: jnp.ndarray, plan: RoutePlan,
                    capacity: int) -> jnp.ndarray:
    """Gather each token's surviving expert outputs and gate-sum them —
    the transpose of :func:`scatter_dispatch` (O(t*k*d))."""
    e, cap, d = out.shape
    flat = jnp.concatenate(
        [out.reshape(e * cap, d), jnp.zeros((1, d), out.dtype)]
    )
    dump = e * cap
    dest = jnp.where(plan.keep, plan.chosen * capacity + plan.slot, dump)
    y = jnp.zeros((plan.chosen.shape[0], d), out.dtype)
    for j in range(plan.chosen.shape[1]):
        rows = flat[dest[:, j]]
        w = (plan.gates[:, j] * plan.keep[:, j]).astype(out.dtype)
        y = y + rows * w[:, None]
    return y


def load_balancing_loss(probs: jnp.ndarray,
                        raw_routes: jnp.ndarray,
                        axes=None) -> jnp.ndarray:
    """Switch-style auxiliary loss: num_experts * <fraction routed to e> ·
    <mean router prob of e>, minimized at uniform routing.

    ``raw_routes`` must be the *pre-capacity* route indicator from
    :func:`top_k_routing`: counting only surviving dispatches would make a
    collapsed router score *better* once its queue overflows (dropped
    claims would vanish from the fraction).

    ``axes``: mesh axes to average the *statistics* (per-expert routed
    fraction and mean router probability) over before forming the
    product.  Averaging statistics — not per-slice losses — makes the
    result exactly the whole-population loss, i.e. invariant to how
    tokens are split across those axes (a mean of per-slice products
    would not be).  Token counts per shard must be equal (they are, on a
    mesh).  ``None`` computes the local-slice loss.
    """
    e = probs.shape[-1]
    routes_per_tok = jnp.sum(raw_routes) / raw_routes.shape[0]
    frac_raw = jnp.mean(raw_routes, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    if axes:
        routes_per_tok = lax.pmean(routes_per_tok, axes)
        frac_raw = lax.pmean(frac_raw, axes)
        mean_prob = lax.pmean(mean_prob, axes)
    frac = frac_raw / jnp.maximum(routes_per_tok, 1.0)
    return e * jnp.sum(frac * mean_prob)


def ep_flow_specs(axis_name: str) -> dict:
    """The MoE layer's sharding declaration for the analysis pass
    (``analysis.shardflow``): tokens arrive sharded over the expert
    axis (each chip routes its own rows), the router is replicated, and
    the stacked expert weights are sharded one block of
    ``num_experts / axis_size`` experts per chip.  Matches the operand
    layout ``expert_parallel_moe`` expects under shard_map — the
    dispatch/return ``all_to_all`` pair is the ONLY communication this
    layout requires, which is exactly what the ``ep_moe_layer`` budget
    pin and the implicit-collective attribution verify."""
    from jax.sharding import PartitionSpec as P

    return {
        "x": P(axis_name),
        "router_w": P(),
        "expert_w1": P(axis_name),
        "expert_w2": P(axis_name),
        "out": P(axis_name),
    }


def expert_parallel_moe(
    x: jnp.ndarray,
    router_w: jnp.ndarray,
    expert_fn: Callable[[jnp.ndarray], jnp.ndarray],
    axis_name: str,
    num_experts: int,
    *,
    k: int = 2,
    capacity_factor: float = 1.25,
    capacity: Optional[int] = None,
    aux_stat_axes=None,
    dispatch_impl: str = "auto",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One expert-parallel MoE layer.  Call inside ``shard_map``.

    Args:
      x: (tokens, d) this chip's tokens.
      router_w: (d, num_experts) router weights (replicated).
      expert_fn: (local_experts, n*capacity, d) -> same shape — applies
        this chip's experts to their gathered queues (vmapped MLP etc.).
      axis_name: mesh axis the experts are sharded over.
      num_experts: total experts; divisible by the axis size.
      aux_stat_axes: mesh axes over which the load-balancing *statistics*
        are averaged before forming the loss (see
        :func:`load_balancing_loss`).  Defaults to ``(axis_name,)``;
        pass every token-splitting axis (data/seq/expert) to make the
        aux loss exactly the global-batch value, invariant to mesh
        factorization.
      dispatch_impl: 'einsum' (dense one-hot masks, exact GShard
        formulation), 'scatter' (collision-free scatter/gather,
        O(t*k*d) instead of O(t*e*cap*d) — identical numerics), or
        'auto' (scatter once the dense masks would be large).
    Returns:
      (y, aux_loss): y (tokens, d) combined expert outputs (dropped tokens
      get zeros — add the residual outside); aux_loss the load-balancing
      scalar (identical on every chip of the stat axes).
    """
    n = lax.axis_size(axis_name)
    if num_experts % n:
        raise ValueError(
            f"num_experts ({num_experts}) must be divisible by the "
            f"'{axis_name}' axis size ({n})"
        )
    local_e = num_experts // n
    t, d = x.shape
    cap = capacity if capacity is not None else compute_capacity(
        t, num_experts, k, capacity_factor
    )

    probs = jax.nn.softmax(
        jnp.asarray(x, jnp.float32) @ jnp.asarray(router_w, jnp.float32),
        axis=-1,
    )
    plan = route_plan(probs, k, cap)
    stat_axes = (axis_name,) if aux_stat_axes is None else tuple(
        aux_stat_axes
    )
    aux = load_balancing_loss(probs, plan.raw_routes, axes=stat_axes)

    impl = resolve_dispatch_impl(dispatch_impl, t, num_experts, cap)
    # Local queues: (num_experts, cap, d)
    dispatched = dispatch_to_queues(x, plan, num_experts, cap, impl)
    # To expert owners: split expert dim over chips, gather token sources.
    # (n, local_e, cap, d) -all_to_all-> every chip: its experts' queues
    # from all chips, concatenated along a new source axis.
    dispatched = dispatched.reshape(n, local_e, cap, d)
    gathered = lax.all_to_all(dispatched, axis_name, split_axis=0,
                              concat_axis=0, tiled=False)
    # gathered: (n_src, local_e, cap, d) -> (local_e, n_src*cap, d)
    gathered = gathered.transpose(1, 0, 2, 3).reshape(local_e, n * cap, d)

    out = expert_fn(gathered)

    # Return trip: transpose the exchange.
    out = out.reshape(local_e, n, cap, d).transpose(1, 0, 2, 3)
    returned = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                              tiled=False)
    returned = returned.reshape(num_experts, cap, d)
    y = combine_from_queues(returned, plan, num_experts, cap, impl)
    return y.astype(x.dtype), aux


def resolve_dispatch_impl(impl: str, t: int, num_experts: int,
                          cap: int) -> str:
    """'auto' picks scatter once the dense one-hot dispatch would cost
    more than ~1M mask elements per feature (t*e*cap) — past that the
    einsum against a one-hot operand dominates the layer's FLOPs."""
    if impl == "auto":
        return "scatter" if t * num_experts * cap >= (1 << 20) else "einsum"
    if impl not in ("einsum", "scatter", "gather"):
        raise ValueError(
            f"dispatch_impl must be 'auto', 'einsum', 'scatter' or "
            f"'gather'; got {impl!r}"
        )
    return impl


def dispatch_to_queues(x: jnp.ndarray, plan: RoutePlan, num_experts: int,
                       capacity: int, impl: str) -> jnp.ndarray:
    """(tokens, d) -> (num_experts, capacity, d) queues via the resolved
    backend ('einsum' | 'scatter' — see :func:`resolve_dispatch_impl`)."""
    if impl == "einsum":
        dispatch, _ = _dense_masks(plan, num_experts, capacity, x.dtype)
        return jnp.einsum("td,tec->ecd", x, dispatch)
    if impl == "gather":
        return gather_dispatch(x, plan, num_experts, capacity)
    return scatter_dispatch(x, plan, num_experts, capacity)


def combine_from_queues(out: jnp.ndarray, plan: RoutePlan,
                        num_experts: int, capacity: int,
                        impl: str) -> jnp.ndarray:
    """(num_experts, capacity, d) expert outputs -> (tokens, d)
    gate-weighted combination, transpose of :func:`dispatch_to_queues`
    (the einsum branch's masks CSE with the dispatch side's)."""
    if impl == "einsum":
        _, combine = _dense_masks(plan, num_experts, capacity, out.dtype)
        return jnp.einsum("ecd,tec->td", out, combine)
    return scatter_combine(out, plan, capacity)


def mlp_experts(w1: jnp.ndarray, w2: jnp.ndarray,
                activation: Callable = jax.nn.gelu) -> Callable:
    """Build an ``expert_fn`` from per-chip expert MLP weights.

    w1: (local_experts, d, hidden); w2: (local_experts, hidden, d).
    The returned fn is one batched einsum pair — (experts, tokens, d) x
    (experts, d, h): MXU-tiled per expert.
    """

    def fn(x):
        h = activation(jnp.einsum("etd,edh->eth", x, w1.astype(x.dtype)))
        return jnp.einsum("eth,ehd->etd", h, w2.astype(x.dtype))

    return fn

"""Ulysses (all-to-all) sequence parallelism.

The reference's differentiable ``alltoall``
(chainermn/functions/collective_communication.py) is exactly the primitive
DeepSpeed-Ulysses builds on (SURVEY.md section 5.7); this module is that
modern capability: attention over a sequence sharded across chips, by
exchanging sequence-sharding for head-sharding around the attention core.

seq-sharded (b, S/n, H, d) --all_to_all--> head-sharded (b, S, H/n, d)
  -> exact local attention over the full sequence per head
  --all_to_all--> seq-sharded output.

Two all-to-alls per attention instead of ring steps; preferable when
head_count >= chip_count and the interconnect favors bulk transposes
(single ICI hop) over n-step rings.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
from jax import lax


def _default_attention(q, k, v, causal: bool, scale: float):
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        S_q, S_k = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((S_q, S_k), bool))
        s = jnp.where(mask[None, None], s, jnp.finfo(s.dtype).min)
    p = jnp.asarray(
        jnp.exp(s - jnp.max(s, axis=-1, keepdims=True)), s.dtype
    )
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    attention_fn: Optional[Callable] = None,
) -> jnp.ndarray:
    """Attention over a sequence sharded along ``axis_name``.

    Args:
      q, k, v: (batch, seq_shard, heads, head_dim) local blocks; ``heads``
        must be divisible by the axis size.  Call inside ``shard_map``.
      attention_fn: optional core ``(q, k, v, causal, scale) -> out`` run
        on full-sequence, head-sharded blocks (e.g. a Pallas flash kernel).
    Returns:
      (batch, seq_shard, heads, head_dim), numerically equal to full
      attention over the gathered sequence.
    """
    n = lax.axis_size(axis_name)
    b, s, h, d = q.shape
    if h % n:
        raise ValueError(f"heads ({h}) must be divisible by axis size ({n})")
    if scale is None:
        scale = d**-0.5

    def seq_to_heads(x):
        # (b, S/n, H, d) -> (b, S, H/n, d): split heads across chips,
        # gather sequence.
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    core = attention_fn or _default_attention
    out = core(qh, kh, vh, causal, scale)
    return heads_to_seq(out).astype(q.dtype)

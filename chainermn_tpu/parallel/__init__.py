from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .pipeline import gpipe  # noqa: F401
from .tensor_parallel import ColumnParallelDense, RowParallelDense  # noqa: F401

__all__ = [
    "ring_attention",
    "ulysses_attention",
    "gpipe",
    "ColumnParallelDense",
    "RowParallelDense",
]

from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .pipeline import (  # noqa: F401
    build_pipeline_train_step,
    gpipe,
    pipeline_flow_specs,
)
from .tensor_parallel import (  # noqa: F401
    ColumnParallelDense,
    RowParallelDense,
    VocabParallelEmbed,
    vocab_parallel_cross_entropy,
    megatron_param_specs,
    sharded_init,
    tp_flow_specs,
)
from .expert_parallel import (  # noqa: F401
    ep_flow_specs,
    expert_parallel_moe,
    mlp_experts,
    top_k_routing,
    route_plan,
    scatter_dispatch,
    gather_dispatch,
    scatter_combine,
    dispatch_to_queues,
    combine_from_queues,
    resolve_dispatch_impl,
    compute_capacity,
    load_balancing_loss,
)

__all__ = [
    "ring_attention",
    "ulysses_attention",
    "gpipe",
    "build_pipeline_train_step",
    "pipeline_flow_specs",
    "ColumnParallelDense",
    "RowParallelDense",
    "VocabParallelEmbed",
    "vocab_parallel_cross_entropy",
    "megatron_param_specs",
    "tp_flow_specs",
    "sharded_init",
    "ep_flow_specs",
    "expert_parallel_moe",
    "mlp_experts",
    "top_k_routing",
    "route_plan",
    "scatter_dispatch",
    "gather_dispatch",
    "scatter_combine",
    "dispatch_to_queues",
    "combine_from_queues",
    "resolve_dispatch_impl",
    "compute_capacity",
    "load_balancing_loss",
]

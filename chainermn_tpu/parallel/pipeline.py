"""Microbatched pipeline parallelism (GPipe schedule) over ICI.

Reference parity: ``MultiNodeChainList`` (chainermn/link.py) partitions a
model across ranks but runs stages strictly sequentially — a fill-drain
pipeline with no microbatching (SURVEY.md section 2, row PP).  This module
is the performance-tier upgrade: homogeneous stages, microbatched GPipe
schedule, expressed as one SPMD program (``shard_map`` over the 'pp' mesh
axis) with ``ppermute`` moving activations between neighbor stages.

Shape of the trick: every chip holds ONE stage's params.  At schedule tick
t, chip s processes microbatch (t - s) while its previous output rides the
ring to chip s+1 — a skewed ``lax.scan`` over t with static control flow
(ticks where a chip has no work compute on zeros and are masked out),
which is exactly how XLA wants a pipeline written: no host round-trips,
collectives overlapped with compute by the async scheduler.

Backward is generated: differentiating the scan yields the reverse
schedule with transposed ppermutes (the 1F1B-ish interleaving falls out of
XLA's scheduling rather than hand-written phases).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_flow_specs(axis_name: str) -> dict:
    """The pipeline step's sharding declaration for the analysis pass
    (``analysis.shardflow``): stacked stage params are sharded one
    stage per chip over the pipeline axis; the microbatch stream and
    targets are replicated (only stage 0 / the last stage consume
    them); the loss psum replicates the output.  This is the layout
    ``build_pipeline_train_step``'s shard_map declares — exporting it
    lets the sharding-flow pass (and its implicit-collective
    attribution) see the pipeline program without reverse-engineering
    the builder."""
    from jax.sharding import PartitionSpec as P

    return {
        "stage_params": P(axis_name),
        "x_microbatches": P(),
        "targets": P(),
        "out": P(),
    }


def gpipe(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x_microbatches: jnp.ndarray,
    axis_name: str,
    *,
    remat: bool = False,
) -> jnp.ndarray:
    """Run a homogeneous-stage pipeline under ``shard_map``.

    Args:
      stage_fn: ``(params, h) -> h`` — one pipeline stage (same structure
        on every chip; per-chip *values* differ).
      stage_params: this chip's stage parameters (shard_map-sharded over
        ``axis_name``).
      x_microbatches: (n_micro, micro_batch, ...) — the *input* microbatch
        stream; only stage 0 actually consumes it (other chips receive
        activations from their neighbor).
      axis_name: the pipeline mesh axis.
      remat: rematerialize each stage's forward during backward
        (``jax.checkpoint``).  The scan carries one activation per tick;
        with remat the saved residuals per tick shrink to the stage
        boundary values — the standard memory/FLOPs trade for deep
        pipelines.

    Returns:
      (n_micro, micro_batch, ...) — the final stage's outputs for every
      microbatch, valid on the LAST stage's chip (zeros elsewhere; callers
      typically ``functions.bcast`` or compute loss on the last stage and
      ``psum``).
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    n_stage = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    n_micro = x_microbatches.shape[0]
    total_ticks = n_micro + n_stage - 1
    h_shape = x_microbatches.shape[1:]

    fwd_perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]

    def tick(carry, t):
        incoming, outputs = carry
        # Stage 0 injects microbatch t (if any); others use the ring input.
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        my_mb = jnp.clip(t - me, 0, n_micro - 1)
        inject = lax.dynamic_index_in_dim(
            x_microbatches, mb_idx, keepdims=False
        )
        h_in = jnp.where(me == 0, inject, incoming)
        h_out = stage_fn(stage_params, h_in)
        # Valid iff this chip is working on a real microbatch this tick.
        valid = (t >= me) & (t - me < n_micro)
        h_out = jnp.where(valid, h_out, jnp.zeros_like(h_out))
        # Last stage records its output for microbatch (t - me).
        is_last = me == n_stage - 1
        record = jnp.where(valid & is_last, h_out, jnp.zeros_like(h_out))
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(valid & is_last, record,
                      lax.dynamic_index_in_dim(outputs, my_mb,
                                               keepdims=False)),
            my_mb, axis=0,
        )
        # Ship to the next stage.
        incoming = lax.ppermute(h_out, axis_name, fwd_perm)
        return (incoming, outputs), None

    incoming0 = jnp.zeros(h_shape, x_microbatches.dtype)
    outputs0 = jnp.zeros((n_micro,) + h_shape, x_microbatches.dtype)
    (_, outputs), _ = lax.scan(
        tick, (incoming0, outputs0), jnp.arange(total_ticks)
    )
    return outputs


def build_pipeline_train_step(
    comm,
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    loss_fn: Callable[[jnp.ndarray, Any], jnp.ndarray],
    optimizer,
    *,
    n_micro: int,
    remat: bool = True,
    donate: bool = True,
):
    """Build a jitted microbatched pipeline-parallel training step.

    The performance tier over ``MultiNodeChainList`` (which runs one
    stage at a time — reference fill-drain semantics): every chip holds
    one stage, ``n_micro`` microbatches stream through the GPipe
    schedule, the loss forms on the last stage and is ``psum``-broadcast,
    and the generated backward runs the transposed schedule.  One XLA
    program per step; no host round trips.

    Args:
      comm: a flat (single-axis) communicator; chip ``s`` = stage ``s``.
      stage_fn: ``(stage_params, h) -> h`` — one homogeneous stage.
      loss_fn: ``(outputs, targets) -> scalar`` where ``outputs`` is
        the last stage's ``(n_micro, micro_batch, ...)`` block.
      optimizer: a plain optax transformation.  Stage gradients are
        per-chip local (no cross-stage sync exists in pipeline
        parallelism), so multi-node wrappers are rejected — their psum
        would corrupt distinct stages' gradients.
      n_micro: microbatches per step; the bubble fraction is
        ``(n_stage - 1) / (n_micro + n_stage - 1)``.
      remat: rematerialize stage forwards in backward (memory tier).

    Layout: ``init_stage_params`` must produce a pytree whose leaves are
    stacked over stages (leading axis ``n_stage``); the returned
    ``step.place`` shards them one stage per chip.  ``step(params,
    opt_state, (x_micro, targets))`` expects ``x_micro`` of shape
    ``(n_micro, micro_batch, ...)`` and broadcast targets; both are
    replicated to every chip (only stage 0 consumes the inputs, only the
    last stage the targets).
    """
    import optax

    from jax.sharding import NamedSharding, PartitionSpec as P

    # late import to avoid a cycle (optimizers imports nothing from here)
    from ..optimizers import _MultiNodeOptimizer

    if isinstance(optimizer, _MultiNodeOptimizer):
        raise ValueError(
            "build_pipeline_train_step takes a plain optax optimizer: "
            "stage gradients are per-chip local and a multi-node "
            "wrapper's cross-chip psum would mix different stages' "
            "gradients"
        )
    if len(comm.axis_names) != 1:
        raise ValueError(
            "pipeline parallelism needs a flat (single-axis) "
            f"communicator; got axes {comm.axis_names}"
        )
    ax = comm.axis_names[0]
    n_stage = comm.size
    mesh = comm.mesh
    stage_sharding = NamedSharding(mesh, P(ax))
    rep = NamedSharding(mesh, P())

    def _squeeze_params(tree):
        return jax.tree_util.tree_map(lambda p: jnp.squeeze(p, 0), tree)

    def _unsqueeze_params(tree):
        return jax.tree_util.tree_map(lambda p: p[None], tree)

    def _squeeze_state(state):
        return optax.tree_map_params(
            optimizer, lambda s: jnp.squeeze(s, 0), state
        )

    def _unsqueeze_state(state):
        return optax.tree_map_params(optimizer, lambda s: s[None], state)

    def _state_specs(opt_state):
        return optax.tree_map_params(
            optimizer,
            lambda _s: P(ax),
            opt_state,
            transform_non_params=lambda _s: P(),
        )

    def _step(params, opt_state, batch):
        x_micro, targets = batch
        if x_micro.shape[0] != n_micro:
            raise ValueError(
                f"batch carries {x_micro.shape[0]} microbatches but the "
                f"step was built with n_micro={n_micro}; the schedule's "
                "bubble fraction depends on it — pass matching data"
            )
        local = _squeeze_params(params)

        def pipeline_loss(lp):
            y = gpipe(stage_fn, lp, x_micro, ax, remat=remat)
            l = loss_fn(y, targets)
            is_last = lax.axis_index(ax) == n_stage - 1
            # loss exists on the last stage; psum replicates it (and
            # routes cotangents back into the pipeline's backward)
            return lax.psum(jnp.where(is_last, l, 0.0), ax)

        loss, grads = jax.value_and_grad(pipeline_loss)(local)
        lstate = _squeeze_state(opt_state)
        updates, lstate = optimizer.update(grads, lstate, local)
        local = optax.apply_updates(local, updates)
        return (
            _unsqueeze_params(local),
            _unsqueeze_state(lstate),
            {"loss": loss},
        )

    compiled: dict = {}

    def _get(opt_state):
        key = jax.tree_util.tree_structure(opt_state)
        if key not in compiled:
            sspecs = _state_specs(opt_state)
            sharded = jax.shard_map(
                _step,
                mesh=mesh,
                in_specs=(P(ax), sspecs, (P(), P())),
                out_specs=(P(ax), sspecs, P()),
                check_vma=False,
            )
            compiled[key] = jax.jit(
                sharded, donate_argnums=(0, 1) if donate else ()
            )
        return compiled[key]

    def step(params, opt_state, batch):
        return _get(opt_state)(params, opt_state, batch)

    def place(params, opt_state=None, batch=None):
        out = [jax.device_put(params, stage_sharding)]
        if opt_state is not None:
            shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s),
                _state_specs(opt_state),
                is_leaf=lambda x: isinstance(x, P),
            )
            out.append(jax.device_put(opt_state, shardings))
        if batch is not None:
            out.append(jax.device_put(batch, rep))
        return out[0] if len(out) == 1 else tuple(out)

    step.place = place
    step.stage_sharding = stage_sharding
    step.n_stage = n_stage
    step.n_micro = n_micro
    return step

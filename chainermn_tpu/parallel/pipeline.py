"""Microbatched pipeline parallelism (GPipe schedule) over ICI.

Reference parity: ``MultiNodeChainList`` (chainermn/link.py) partitions a
model across ranks but runs stages strictly sequentially — a fill-drain
pipeline with no microbatching (SURVEY.md section 2, row PP).  This module
is the performance-tier upgrade: homogeneous stages, microbatched GPipe
schedule, expressed as one SPMD program (``shard_map`` over the 'pp' mesh
axis) with ``ppermute`` moving activations between neighbor stages.

Shape of the trick: every chip holds ONE stage's params.  At schedule tick
t, chip s processes microbatch (t - s) while its previous output rides the
ring to chip s+1 — a skewed ``lax.scan`` over t with static control flow
(ticks where a chip has no work compute on zeros and are masked out),
which is exactly how XLA wants a pipeline written: no host round-trips,
collectives overlapped with compute by the async scheduler.

Backward is generated: differentiating the scan yields the reverse
schedule with transposed ppermutes (the 1F1B-ish interleaving falls out of
XLA's scheduling rather than hand-written phases).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def gpipe(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x_microbatches: jnp.ndarray,
    axis_name: str,
) -> jnp.ndarray:
    """Run a homogeneous-stage pipeline under ``shard_map``.

    Args:
      stage_fn: ``(params, h) -> h`` — one pipeline stage (same structure
        on every chip; per-chip *values* differ).
      stage_params: this chip's stage parameters (shard_map-sharded over
        ``axis_name``).
      x_microbatches: (n_micro, micro_batch, ...) — the *input* microbatch
        stream; only stage 0 actually consumes it (other chips receive
        activations from their neighbor).
      axis_name: the pipeline mesh axis.

    Returns:
      (n_micro, micro_batch, ...) — the final stage's outputs for every
      microbatch, valid on the LAST stage's chip (zeros elsewhere; callers
      typically ``functions.bcast`` or compute loss on the last stage and
      ``psum``).
    """
    n_stage = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    n_micro = x_microbatches.shape[0]
    total_ticks = n_micro + n_stage - 1
    h_shape = x_microbatches.shape[1:]

    fwd_perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]

    def tick(carry, t):
        incoming, outputs = carry
        # Stage 0 injects microbatch t (if any); others use the ring input.
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        my_mb = jnp.clip(t - me, 0, n_micro - 1)
        inject = lax.dynamic_index_in_dim(
            x_microbatches, mb_idx, keepdims=False
        )
        h_in = jnp.where(me == 0, inject, incoming)
        h_out = stage_fn(stage_params, h_in)
        # Valid iff this chip is working on a real microbatch this tick.
        valid = (t >= me) & (t - me < n_micro)
        h_out = jnp.where(valid, h_out, jnp.zeros_like(h_out))
        # Last stage records its output for microbatch (t - me).
        is_last = me == n_stage - 1
        record = jnp.where(valid & is_last, h_out, jnp.zeros_like(h_out))
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(valid & is_last, record,
                      lax.dynamic_index_in_dim(outputs, my_mb,
                                               keepdims=False)),
            my_mb, axis=0,
        )
        # Ship to the next stage.
        incoming = lax.ppermute(h_out, axis_name, fwd_perm)
        return (incoming, outputs), None

    incoming0 = jnp.zeros(h_shape, x_microbatches.dtype)
    outputs0 = jnp.zeros((n_micro,) + h_shape, x_microbatches.dtype)
    (_, outputs), _ = lax.scan(
        tick, (incoming0, outputs0), jnp.arange(total_ticks)
    )
    return outputs

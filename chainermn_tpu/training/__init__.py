from .trainer import Trainer, Updater  # noqa: F401
from .triggers import IntervalTrigger, get_trigger  # noqa: F401
from . import extensions  # noqa: F401

__all__ = ["Trainer", "Updater", "IntervalTrigger", "get_trigger", "extensions"]

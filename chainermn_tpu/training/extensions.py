"""Standard trainer extensions: logging / reporting / throughput.

The reference relied on Chainer's ``LogReport``/``PrintReport``/
``ProgressBar`` with the documented convention that only rank 0 attaches
them (SURVEY.md section 5.5).  Here the equivalents are first-class, and the
rank-0 convention is built in: pass ``comm`` and each extension silences
itself on non-zero processes automatically.

``Throughput`` is the distributed-specific addition: it reports
samples/sec (global and per-chip) — the metric family the ImageNet example
printed and BASELINE.md targets.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, Optional, Sequence

import numpy as np


def _is_chief(comm) -> bool:
    return comm is None or comm.process_index == 0


class LogReport:
    """Accumulates observations; writes a JSON log (rank 0 only)."""

    priority = 150
    trigger = (1, "epoch")
    name = "log_report"

    def __init__(self, comm=None, filename: Optional[str] = "log.json",
                 out: str = "result", trigger=(1, "epoch")):
        self._comm = comm
        self._filename = filename
        self._out = out
        self.trigger = trigger
        self.log: list = []
        self._pending: Dict[str, list] = {}

    def observe(self, observation: Dict[str, Any]) -> None:
        for k, v in observation.items():
            try:
                self._pending.setdefault(k, []).append(float(v))
            except (TypeError, ValueError):
                pass

    def __call__(self, trainer):
        self.observe(trainer.observation)
        entry = {
            "iteration": trainer.iteration,
            "epoch": trainer.epoch,
            "elapsed_time": trainer.elapsed_time,
        }
        for k, vals in self._pending.items():
            entry[k] = float(np.mean(vals))
        self._pending.clear()
        self.log.append(entry)
        if self._filename and _is_chief(self._comm):
            os.makedirs(self._out, exist_ok=True)
            with open(os.path.join(self._out, self._filename), "w") as f:
                json.dump(self.log, f, indent=1)


class PrintReport:
    """Prints selected log entries as a table (rank 0 only)."""

    priority = 140
    trigger = (1, "epoch")
    name = "print_report"

    def __init__(self, entries: Sequence[str], log_report: LogReport,
                 comm=None, stream=None):
        self._entries = list(entries)
        self._log_report = log_report
        self._comm = comm
        self._stream = stream or sys.stdout
        self._header_printed = False

    def __call__(self, trainer):
        if not _is_chief(self._comm):
            return
        if not self._log_report.log:
            return
        if not self._header_printed:
            self._stream.write(
                "  ".join(f"{e:>14s}" for e in self._entries) + "\n"
            )
            self._header_printed = True
        last = self._log_report.log[-1]
        cells = []
        for e in self._entries:
            v = last.get(e)
            cells.append(
                f"{v:14.6g}" if isinstance(v, (int, float)) else f"{'':>14s}"
            )
        self._stream.write("  ".join(cells) + "\n")
        self._stream.flush()


class ProgressBar:
    """Lightweight iteration progress line (rank 0 only)."""

    priority = 130
    trigger = (1, "iteration")
    name = "progress_bar"

    def __init__(self, comm=None, update_interval: int = 50, stream=None):
        self._comm = comm
        self._interval = update_interval
        self._stream = stream or sys.stdout

    def __call__(self, trainer):
        if not _is_chief(self._comm):
            return
        if trainer.iteration % self._interval:
            return
        t = trainer.elapsed_time
        ips = trainer.iteration / t if t > 0 else 0.0
        self._stream.write(
            f"\riter {trainer.iteration}  epoch {trainer.epoch}  "
            f"{ips:.2f} it/s"
        )
        self._stream.flush()


class Profile:
    """Capture a ``jax.profiler`` trace over a window of iterations.

    SURVEY.md section 5.1: the reference shipped no in-package profiler
    (users fell back to Chainer hooks + nvprof); the TPU rebuild makes
    step-window tracing a first-class trainer extension.  The trace
    covers updates ``[start, stop)`` and lands in ``logdir`` in the
    TensorBoard profile-plugin format.  Extensions only run *between*
    updates, so the earliest capturable update is 2 (any ``start <= 2``
    opens the trace at the same point, after update 1):

        trainer.extend(T.Profile(start=10, stop=13, comm=comm))
        ...
        tensorboard --logdir profile/   # -> Profile tab: timeline,
                                        #    op stats, memory viewer

    Only the chief process traces by default (every process writes its
    own device's timeline under multi-controller when
    ``all_processes=True``).  See docs/performance.md for the workflow,
    including communication-overhead-by-subtraction with the ``dummy``
    communicator.
    """

    priority = 170  # before Throughput so the trace brackets real work
    trigger = (1, "iteration")
    name = "profile"

    def __init__(self, start: int = 10, stop: int = 13,
                 logdir: str = "profile", comm=None,
                 all_processes: bool = False):
        if stop <= start:
            raise ValueError(f"need start < stop, got [{start}, {stop})")
        self._start = start
        self._stop = stop
        self._logdir = logdir
        self._comm = comm
        self._all = all_processes
        self._active = False
        self.done = False

    def _should_trace(self) -> bool:
        return self._all or _is_chief(self._comm)

    def __call__(self, trainer):
        import jax

        if self.done or not self._should_trace():
            return
        # Extensions run AFTER the update increments trainer.iteration,
        # so to trace updates [start, stop) the trace must open once
        # update (start-1) has completed and close once update (stop-1)
        # has.  (The first traceable update is 2: the extension's first
        # chance to open the trace is after update 1.)
        if not self._active and trainer.iteration >= self._start - 1:
            jax.profiler.start_trace(self._logdir)
            self._active = True
        elif self._active and trainer.iteration >= self._stop - 1:
            # make async dispatches land inside the trace window
            for v in trainer.observation.values():
                try:
                    jax.block_until_ready(v)
                except Exception:
                    pass
            jax.profiler.stop_trace()
            self._active = False
            self.done = True

    def finalize(self, trainer=None):
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self.done = True


class Throughput:
    """Reports global and per-chip samples/sec into the observation."""

    priority = 160
    trigger = (1, "iteration")
    name = "throughput"

    def __init__(self, batch_size_global: int, comm=None, warmup: int = 2):
        self._bs = batch_size_global
        self._comm = comm
        self._warmup = warmup
        self._t0 = None
        self._count = 0

    def __call__(self, trainer):
        self._count += 1
        if self._count == self._warmup:
            self._t0 = time.monotonic()
            self._n0 = self._count
            return
        if self._t0 is None:
            return
        dt = time.monotonic() - self._t0
        n = self._count - self._n0
        if dt <= 0 or n <= 0:
            return
        sps = n * self._bs / dt
        trainer.observation["samples_per_sec"] = sps
        if self._comm is not None and self._comm.size:
            trainer.observation["samples_per_sec_per_chip"] = (
                sps / self._comm.size
            )

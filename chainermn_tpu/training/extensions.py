"""Standard trainer extensions: logging / reporting / throughput.

The reference relied on Chainer's ``LogReport``/``PrintReport``/
``ProgressBar`` with the documented convention that only rank 0 attaches
them (SURVEY.md section 5.5).  Here the equivalents are first-class, and the
rank-0 convention is built in: pass ``comm`` and each extension silences
itself on non-zero processes automatically.

``Throughput`` is the distributed-specific addition: it reports
samples/sec (global and per-chip) — the metric family the ImageNet example
printed and BASELINE.md targets.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, Optional, Sequence

import numpy as np


def _is_chief(comm) -> bool:
    return comm is None or comm.process_index == 0


class LogReport:
    """Accumulates observations; writes a JSON log (rank 0 only)."""

    priority = 150
    trigger = (1, "epoch")
    name = "log_report"

    def __init__(self, comm=None, filename: Optional[str] = "log.json",
                 out: str = "result", trigger=(1, "epoch")):
        self._comm = comm
        self._filename = filename
        self._out = out
        self.trigger = trigger
        self.log: list = []
        self._pending: Dict[str, list] = {}

    def observe(self, observation: Dict[str, Any]) -> None:
        for k, v in observation.items():
            try:
                self._pending.setdefault(k, []).append(float(v))
            except (TypeError, ValueError):
                pass

    def __call__(self, trainer):
        self.observe(trainer.observation)
        entry = {
            "iteration": trainer.iteration,
            "epoch": trainer.epoch,
            "elapsed_time": trainer.elapsed_time,
        }
        for k, vals in self._pending.items():
            entry[k] = float(np.mean(vals))
        self._pending.clear()
        self.log.append(entry)
        if self._filename and _is_chief(self._comm):
            os.makedirs(self._out, exist_ok=True)
            with open(os.path.join(self._out, self._filename), "w") as f:
                json.dump(self.log, f, indent=1)


class PrintReport:
    """Prints selected log entries as a table (rank 0 only)."""

    priority = 140
    trigger = (1, "epoch")
    name = "print_report"

    def __init__(self, entries: Sequence[str], log_report: LogReport,
                 comm=None, stream=None):
        self._entries = list(entries)
        self._log_report = log_report
        self._comm = comm
        self._stream = stream or sys.stdout
        self._header_printed = False

    def __call__(self, trainer):
        if not _is_chief(self._comm):
            return
        if not self._log_report.log:
            return
        if not self._header_printed:
            self._stream.write(
                "  ".join(f"{e:>14s}" for e in self._entries) + "\n"
            )
            self._header_printed = True
        last = self._log_report.log[-1]
        cells = []
        for e in self._entries:
            v = last.get(e)
            cells.append(
                f"{v:14.6g}" if isinstance(v, (int, float)) else f"{'':>14s}"
            )
        self._stream.write("  ".join(cells) + "\n")
        self._stream.flush()


class ProgressBar:
    """Lightweight iteration progress line (rank 0 only)."""

    priority = 130
    trigger = (1, "iteration")
    name = "progress_bar"

    def __init__(self, comm=None, update_interval: int = 50, stream=None):
        self._comm = comm
        self._interval = update_interval
        self._stream = stream or sys.stdout

    def __call__(self, trainer):
        if not _is_chief(self._comm):
            return
        if trainer.iteration % self._interval:
            return
        t = trainer.elapsed_time
        ips = trainer.iteration / t if t > 0 else 0.0
        self._stream.write(
            f"\riter {trainer.iteration}  epoch {trainer.epoch}  "
            f"{ips:.2f} it/s"
        )
        self._stream.flush()


class Throughput:
    """Reports global and per-chip samples/sec into the observation."""

    priority = 160
    trigger = (1, "iteration")
    name = "throughput"

    def __init__(self, batch_size_global: int, comm=None, warmup: int = 2):
        self._bs = batch_size_global
        self._comm = comm
        self._warmup = warmup
        self._t0 = None
        self._count = 0

    def __call__(self, trainer):
        self._count += 1
        if self._count == self._warmup:
            self._t0 = time.time()
            self._n0 = self._count
            return
        if self._t0 is None:
            return
        dt = time.time() - self._t0
        n = self._count - self._n0
        if dt <= 0 or n <= 0:
            return
        sps = n * self._bs / dt
        trainer.observation["samples_per_sec"] = sps
        if self._comm is not None and self._comm.size:
            trainer.observation["samples_per_sec_per_chip"] = (
                sps / self._comm.size
            )

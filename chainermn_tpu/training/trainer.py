"""Training loop: Updater + Trainer.

The reference has no trainer of its own — ChainerMN plugs into Chainer's
``Trainer``/``StandardUpdater`` (SURVEY.md section 3.2: ``trainer.run() ->
StandardUpdater.update_core -> optimizer.update``).  A standalone framework
needs the loop itself, so this module provides a minimal functional
equivalent: the Updater owns (params, opt_state, step_fn); the Trainer owns
the iteration/epoch bookkeeping, extensions, and reporting.

TPU-native properties: the per-iteration work is ONE jitted SPMD step (built
by ``optimizers.build_train_step``); the loop never blocks on device results
unless an extension asks for them (async dispatch keeps the TPU busy while
the host prepares the next batch).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

import jax

from .triggers import get_trigger


class Updater:
    """Owns the train state and applies one compiled step per iteration."""

    def __init__(self, iterator, step_fn: Callable, params, opt_state,
                 *, batch_sharding=None):
        self.iterator = iterator
        self.step_fn = step_fn
        self.params = params
        self.opt_state = opt_state
        self._explicit_sharding = batch_sharding is not None
        self.batch_sharding = batch_sharding or getattr(
            step_fn, "batch_sharding", None
        )
        self.last_metrics: Dict[str, Any] = {}

    @property
    def epoch(self) -> int:
        return getattr(self.iterator, "epoch", 0)

    @property
    def epoch_detail(self) -> float:
        return getattr(self.iterator, "epoch_detail", 0.0)

    def update(self) -> None:
        batch = next(self.iterator)
        place_batch = getattr(self.step_fn, "place_batch", None)
        # build_train_step exposes its own placement predicate; a batch
        # already laid out per the step's sharding (prefetch_to_device
        # output) must NOT be re-placed — in multi-process runs
        # make_array_from_process_local_data on a non-fully-addressable
        # global array crashes.  An explicit batch_sharding always goes
        # through device_put (a no-op when already right).
        is_placed = getattr(self.step_fn, "is_placed", None)
        if place_batch is not None and not self._explicit_sharding:
            if not (is_placed is not None and is_placed(batch)):
                batch = place_batch(batch)
        elif self.batch_sharding is not None:
            batch = jax.device_put(batch, self.batch_sharding)
        self.params, self.opt_state, self.last_metrics = self.step_fn(
            self.params, self.opt_state, batch
        )


class _ExtensionEntry:
    def __init__(self, ext, trigger, priority: int, name: str):
        self.ext = ext
        self.trigger = get_trigger(trigger)
        self.priority = priority
        self.name = name


class Trainer:
    """Runs the updater until a stop condition, firing extensions.

    Stop condition mirrors Chainer: ``stop_trigger=(n, 'epoch'|'iteration')``.
    Extension protocol: a callable ``ext(trainer)``; optional attributes
    ``trigger`` (default each epoch), ``priority``, ``initialize(trainer)``,
    ``finalize(trainer)``.
    """

    def __init__(self, updater: Updater, stop_trigger=(1, "epoch"),
                 out: str = "result"):
        self.updater = updater
        self.stop_n, self.stop_unit = stop_trigger
        self.out = out
        self.iteration = 0
        self.observation: Dict[str, Any] = {}
        self._extensions: list[_ExtensionEntry] = []
        self._start_time: Optional[float] = None

    # -- extension management -----------------------------------------
    def extend(self, ext, trigger=None, priority: Optional[int] = None,
               name: Optional[str] = None):
        trigger = trigger if trigger is not None else getattr(
            ext, "trigger", (1, "epoch")
        )
        priority = priority if priority is not None else getattr(
            ext, "priority", 100
        )
        name = name or getattr(ext, "name", None) or type(ext).__name__
        self._extensions.append(_ExtensionEntry(ext, trigger, priority, name))
        return self

    def get_extension(self, name: str):
        for e in self._extensions:
            if e.name == name:
                return e.ext
        raise KeyError(name)

    # -- loop ----------------------------------------------------------
    @property
    def epoch(self) -> int:
        return self.updater.epoch

    @property
    def elapsed_time(self) -> float:
        return time.time() - (self._start_time or time.time())

    def _stop(self) -> bool:
        if self.stop_unit == "iteration":
            return self.iteration >= self.stop_n
        return self.updater.epoch >= self.stop_n

    def run(self) -> None:
        self._start_time = time.time()
        for e in self._extensions:
            init = getattr(e.ext, "initialize", None)
            if init:
                init(self)
        exts = sorted(self._extensions, key=lambda e: -e.priority)
        while not self._stop():
            self.updater.update()
            self.iteration += 1
            self.observation = {
                k: v for k, v in (self.updater.last_metrics or {}).items()
            }
            for e in exts:
                if e.trigger(self):
                    e.ext(self)
        for e in self._extensions:
            fin = getattr(e.ext, "finalize", None)
            if fin:
                fin(self)

    # -- state (for checkpointing) -------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        return {
            "iteration": self.iteration,
            "iterator": self.updater.iterator.serialize()
            if hasattr(self.updater.iterator, "serialize") else None,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.iteration = state["iteration"]
        if state.get("iterator") and hasattr(self.updater.iterator, "restore"):
            self.updater.iterator.restore(state["iterator"])

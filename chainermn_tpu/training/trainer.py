"""Training loop: Updater + Trainer.

The reference has no trainer of its own — ChainerMN plugs into Chainer's
``Trainer``/``StandardUpdater`` (SURVEY.md section 3.2: ``trainer.run() ->
StandardUpdater.update_core -> optimizer.update``).  A standalone framework
needs the loop itself, so this module provides a minimal functional
equivalent: the Updater owns (params, opt_state, step_fn); the Trainer owns
the iteration/epoch bookkeeping, extensions, and reporting.

TPU-native properties: the per-iteration work is ONE jitted SPMD step (built
by ``optimizers.build_train_step``); the loop never blocks on device results
unless an extension asks for them (async dispatch keeps the TPU busy while
the host prepares the next batch).
"""

from __future__ import annotations

import time
import warnings
from typing import Any, Callable, Dict, Optional

import jax

from .triggers import get_trigger
from ..observability import timeline as _obs
from ..resilience import fault_injection as _fi
from ..resilience import log as _rlog
from ..resilience.errors import (
    ResilienceError,
    RestartBudgetExceededError,
    StepDivergedError,
)


class Updater:
    """Owns the train state and applies one compiled step per iteration."""

    def __init__(self, iterator, step_fn: Callable, params, opt_state,
                 *, batch_sharding=None):
        self.iterator = iterator
        self.step_fn = step_fn
        self.params = params
        self.opt_state = opt_state
        self._explicit_sharding = batch_sharding is not None
        self.batch_sharding = batch_sharding or getattr(
            step_fn, "batch_sharding", None
        )
        self.last_metrics: Dict[str, Any] = {}

    @property
    def epoch(self) -> int:
        return getattr(self.iterator, "epoch", 0)

    @property
    def epoch_detail(self) -> float:
        return getattr(self.iterator, "epoch_detail", 0.0)

    def update(self) -> None:
        # telemetry spans ("update" > "data.wait"/"compute.dispatch"):
        # the data-wait-vs-compute split of the step taxonomy; disabled
        # path is one `is None` check per span (docs/observability.md)
        with _obs.span("update"):
            # resilience site: a deterministic mid-run failure point for
            # exercising auto-resume (no-op — one None check — when no
            # injector is active)
            _fi.fire("trainer.update")
            with _obs.span("data.wait"):
                batch = next(self.iterator)
            with _obs.span("compute.dispatch"):
                place_batch = getattr(self.step_fn, "place_batch", None)
                # build_train_step exposes its own placement predicate;
                # a batch already laid out per the step's sharding
                # (prefetch_to_device output) must NOT be re-placed —
                # in multi-process runs
                # make_array_from_process_local_data on a
                # non-fully-addressable global array crashes.  An
                # explicit batch_sharding always goes through
                # device_put (a no-op when already right).
                is_placed = getattr(self.step_fn, "is_placed", None)
                if place_batch is not None and not self._explicit_sharding:
                    if not (is_placed is not None and is_placed(batch)):
                        batch = place_batch(batch)
                elif self.batch_sharding is not None:
                    batch = jax.device_put(batch, self.batch_sharding)
                self.params, self.opt_state, self.last_metrics = \
                    self.step_fn(self.params, self.opt_state, batch)
        self._observe_host_time()

    @staticmethod
    def _observe_host_time() -> None:
        """Derived rank-LOCAL metric: ``update.host`` = update minus
        its data.wait/compute.dispatch children — host time this rank
        spent NEITHER waiting for data NOR dispatching (injected
        faults, GC, host contention).  The straggler detector keys on
        it because lockstep SPMD *equalizes* wall-clock step time
        across ranks (healthy ranks block in the collective waiting
        for the slow one), so only rank-local phases can convict."""
        tel = _obs.active()
        if tel is None:
            return
        reg = tel.registry
        u = reg.histogram("update").last
        d = reg.histogram("data.wait").last
        c = reg.histogram("compute.dispatch").last
        if u is None or d is None or c is None:
            return
        reg.histogram("update.host").observe(max(u - d - c, 0.0))


class _ExtensionEntry:
    def __init__(self, ext, trigger, priority: int, name: str):
        self.ext = ext
        self.trigger = get_trigger(trigger)
        self.priority = priority
        self.name = name


class Trainer:
    """Runs the updater until a stop condition, firing extensions.

    Stop condition mirrors Chainer: ``stop_trigger=(n, 'epoch'|'iteration')``.
    Extension protocol: a callable ``ext(trainer)``; optional attributes
    ``trigger`` (default each epoch), ``priority``, ``initialize(trainer)``,
    ``finalize(trainer)``.
    """

    def __init__(self, updater: Updater, stop_trigger=(1, "epoch"),
                 out: str = "result"):
        self.updater = updater
        self.stop_n, self.stop_unit = stop_trigger
        self.out = out
        self.iteration = 0
        self.observation: Dict[str, Any] = {}
        self._extensions: list[_ExtensionEntry] = []
        self._start_time: Optional[float] = None
        # Structured record of every injected/observed fault, retry,
        # skipped step, and restart during run() — the assertion surface
        # for tests and reporting extensions.
        from ..resilience.log import ResilienceLog

        self.resilience_log = ResilienceLog()
        self.restarts = 0
        self._pending_guard = None  # deferred grads_finite read

    # -- extension management -----------------------------------------
    def extend(self, ext, trigger=None, priority: Optional[int] = None,
               name: Optional[str] = None):
        trigger = trigger if trigger is not None else getattr(
            ext, "trigger", (1, "epoch")
        )
        priority = priority if priority is not None else getattr(
            ext, "priority", 100
        )
        name = name or getattr(ext, "name", None) or type(ext).__name__
        self._extensions.append(_ExtensionEntry(ext, trigger, priority, name))
        return self

    def get_extension(self, name: str):
        for e in self._extensions:
            if e.name == name:
                return e.ext
        raise KeyError(name)

    # -- loop ----------------------------------------------------------
    @property
    def epoch(self) -> int:
        return self.updater.epoch

    @property
    def elapsed_time(self) -> float:
        now = time.monotonic()
        return now - (self._start_time or now)

    def _stop(self) -> bool:
        if self.stop_unit == "iteration":
            return self.iteration >= self.stop_n
        return self.updater.epoch >= self.stop_n

    def _check_step_guard(self) -> None:
        """Host side of the non-finite-step guard: the compiled step
        already skipped (or applied, under ``warn``) the update in
        cross-rank agreement; here the policy's host effect happens —
        record the event, warn, or abort.

        The flag is read one iteration LATE: materializing iteration
        i's ``grads_finite`` would otherwise block the host on step i
        every time, serializing the async-dispatch pipeline.  Deferring
        the read until after step i+1 is dispatched keeps the overlap;
        by then step i has (almost always) completed, so ``float()``
        returns without waiting.  The pending flag is flushed at loop
        end (``_flush_step_guard``), so no event is ever lost."""
        policy = getattr(self.updater.step_fn, "nonfinite_policy", None)
        if policy is None:
            return
        flag = (self.updater.last_metrics or {}).get("grads_finite")
        prev, self._pending_guard = (
            self._pending_guard,
            None if flag is None else (self.iteration, flag, policy),
        )
        if prev is not None:
            self._consume_guard(prev)

    def _flush_step_guard(self) -> None:
        prev, self._pending_guard = self._pending_guard, None
        if prev is not None:
            self._consume_guard(prev)

    def _consume_guard(self, pending) -> None:
        iteration, flag, policy = pending
        if float(flag) > 0.0:
            return
        self.resilience_log.record(
            "nonfinite_step", "trainer.update",
            iteration=iteration, policy=policy,
        )
        if policy == "abort":
            raise StepDivergedError(
                f"non-finite gradients at iteration {iteration} "
                "(policy 'abort'); all ranks agreed via the compiled "
                "pmin flag, so the abort is collective-safe",
                site="trainer.update",
            )
        if policy == "warn":
            warnings.warn(
                f"non-finite gradients at iteration {iteration} "
                "applied under policy 'warn'"
            )

    def _find_checkpointer(self):
        for e in self._extensions:
            if hasattr(e.ext, "restore_trainer"):
                return e.ext
        return None

    def _find_adaptive(self):
        from ..resilience.adaptive import AdaptiveExecution

        for e in self._extensions:
            if isinstance(e.ext, AdaptiveExecution):
                return e.ext
        return None

    def _auto_resume(self, error: ResilienceError) -> None:
        """Roll back to the newest common checkpoint (params, opt_state,
        iteration, iterator position).  Without a checkpointer extension
        the in-flight state is still consistent (the step is functional:
        an aborted update left params untouched), so training simply
        continues from the current iteration."""
        ckpt = self._find_checkpointer()
        step = ckpt.restore_trainer(self) if ckpt is not None else None
        self.resilience_log.record(
            "restart", error.site,
            restored_step=step, restarts=self.restarts,
            error=f"{type(error).__name__}: {error}",
        )

    def run(self, max_restarts: int = 0, adapt=None) -> None:
        """Run to the stop trigger.

        ``max_restarts``: auto-resume budget.  A *recoverable*
        :class:`ResilienceError` escaping an update (exhausted obj-store
        retries, an injected transient fault, a corrupted control-plane
        payload) rolls the trainer back to the newest common checkpoint
        (see :meth:`_auto_resume`) and continues, up to this many times;
        the budget and every restart are recorded on
        ``self.resilience_log``.  Exhaustion raises
        :class:`RestartBudgetExceededError` with the last failure
        chained; non-recoverable errors propagate immediately.

        ``adapt``: a :class:`~chainermn_tpu.resilience.adaptive.
        AdaptPolicy` (or ``AdaptiveExecution``) making this a
        straggler-adaptive run: the policy consumes the attached
        ``MetricsReport``'s convictions and rebalances/demotes per its
        hysteresis (docs/resilience.md "Self-healing runtime").  A
        demotion raises :class:`~chainermn_tpu.resilience.errors.
        DemotionRequiredError` on every rank together — recovery is the
        elastic N−1 restart, not an in-place resume.  With a
        :class:`~chainermn_tpu.resilience.adaptive.CapacityWatcher`
        attached (``adapt=AdaptiveExecution(policy, comm=...,
        watcher=..., hosts=[...])``), healed hosts publishing presence
        manifests are held under weight-0 probation and an agreed
        promotion raises :class:`~chainermn_tpu.resilience.errors.
        PromotionRequiredError` the same collective way — recovery is
        the elastic N+k restart from the decision snapshot
        (docs/resilience.md "Scale-up and re-admission").
        """
        if adapt is not None and self._find_adaptive() is None:
            from ..resilience.adaptive import (
                AdaptiveExecution,
                AdaptPolicy,
            )

            ext = (adapt if isinstance(adapt, AdaptiveExecution)
                   else AdaptiveExecution(adapt)
                   if isinstance(adapt, AdaptPolicy)
                   else None)
            if ext is None:
                raise TypeError(
                    f"adapt= wants an AdaptPolicy or AdaptiveExecution, "
                    f"got {type(adapt).__name__}"
                )
            self.extend(ext)
        self._start_time = time.monotonic()
        _rlog.attach(self.resilience_log)
        try:
            for e in self._extensions:
                init = getattr(e.ext, "initialize", None)
                if init:
                    init(self)
            exts = sorted(self._extensions, key=lambda e: -e.priority)
            self.restarts = 0
            while not self._stop():
                try:
                    # "step" span: one trainer iteration — update AND
                    # its extensions (a checkpoint stall is step time
                    # the operator pays; the sub-spans split it)
                    with _obs.span("step", iteration=self.iteration):
                        self.updater.update()
                        self.iteration += 1
                        self.observation = {
                            k: v
                            for k, v in (
                                self.updater.last_metrics or {}
                            ).items()
                        }
                        self._check_step_guard()
                        # extensions run INSIDE the recovery scope: a
                        # transient failure during e.g. the
                        # checkpointer's collective save is as
                        # recoverable as one during the update itself
                        for e in exts:
                            if e.trigger(self):
                                e.ext(self)
                except ResilienceError as err:
                    if not err.recoverable:
                        raise
                    if self.restarts >= max_restarts:
                        if self.restarts == 0:
                            # auto-resume never engaged (max_restarts=0):
                            # propagate the original, still-recoverable
                            # error unchanged so outer layers can apply
                            # their own policy to the true taxonomy
                            raise
                        raise RestartBudgetExceededError(
                            f"giving up after {self.restarts} restart(s) "
                            f"(max_restarts={max_restarts}); last failure: "
                            f"{type(err).__name__}: {err}",
                            site=err.site,
                            attempts=err.attempts,
                        ) from err
                    self.restarts += 1
                    # the restored state invalidates any deferred
                    # grads_finite read from the rolled-back step
                    self._pending_guard = None
                    self._auto_resume(err)
            self._flush_step_guard()
        finally:
            try:
                # finalize runs on error exits too: the async
                # checkpointer must drain its in-flight save (a
                # truncated snapshot outlives the exception) and a
                # MetricsReport that installed its own process-global
                # telemetry must uninstall it (leaking it would keep
                # recording — and serializing the observed wire — for
                # every later run in the process).  Each finalize is
                # isolated: one raising must neither mask the run's
                # own exception nor skip the remaining extensions'
                # cleanup.
                errs = []
                for e in self._extensions:
                    fin = getattr(e.ext, "finalize", None)
                    if fin:
                        try:
                            fin(self)
                        except Exception as fe:  # noqa: BLE001
                            errs.append((e.name, fe))
                            self.resilience_log.record(
                                "finalize_error", "trainer.run",
                                extension=e.name,
                                error=f"{type(fe).__name__}: {fe}",
                            )
                import sys as _sys

                if errs and _sys.exc_info()[0] is None:
                    # clean run: a finalize failure must not vanish
                    raise errs[0][1]
                # erroring run: the run's own exception wins; the
                # finalize failures are on the resilience log (and,
                # merged, in the timeline)
            finally:
                # one merged stream: the run's faults/retries/restarts
                # land in the active timeline at their recorded
                # monotonic positions (idempotent — emit shares event
                # objects, so an additional explicit merge cannot
                # duplicate)
                tel = _obs.active()
                if tel is not None:
                    tel.timeline.merge_resilience(self.resilience_log)
                _rlog.detach(self.resilience_log)

    # -- elastic restart mode (resilience.elastic) ---------------------
    @classmethod
    def run_elastic(cls, build, *, communicator_name: str = "tpu",
                    devices=None, max_restarts: int = 0,
                    comm_kwargs: Optional[Dict[str, Any]] = None,
                    peer_store=None) -> "Trainer":
        """Elastic restart: re-form the world from the surviving ranks,
        rebuild the trainer in it, resume THROUGH the checkpoint
        resharder, and run.

        ``build(comm) -> Trainer`` constructs the new world's trainer
        (model, optimizer, compiled step, iterators, extensions —
        including a checkpointer pointed at the shared snapshot root).
        The newest common checkpoint is restored via
        ``restore_trainer``: a world-size mismatch in its manifest
        routes the state through ``resilience.elastic.reshard_state``
        (ZeRO blocks re-partitioned bit-identically, per-rank residuals
        dropped, iterator cursors rescaled).  The agreement stack
        re-arms by construction — the fresh optimizer's ``init``
        re-exchanges the wire ``plan_hash`` and the fresh compiled
        step's first multi-process dispatch re-runs ``trace_agreement``
        for the NEW program (both are keyed per program variant; see
        ``elastic.reestablish_agreements`` to force them explicitly).
        Returns the trainer after ``run(max_restarts=...)``.

        The path is direction-agnostic: the same resharder serves a
        world that SHRANK (preemption, demotion) and one that GREW (a
        promoted host joining after probation — the ``N+k`` restart a
        :class:`~chainermn_tpu.resilience.errors.
        PromotionRequiredError` asks for; growth floors the iterator
        cursor, re-visiting a sample rather than skipping one).

        ``peer_store``: a :class:`~chainermn_tpu.resilience.peer_ckpt.
        PeerCheckpointStore` adds the in-memory tier to step election —
        the store rebinds its ring to the re-formed world (dropping
        orphaned replicas), the peer and FS tiers each vote their
        newest common step, and the PEER tier is preferred when its
        step is at least as new (RAM restore, no FS read).  A broken
        ring or an older peer step falls back to the FS cold tier; the
        recorded ``elastic_restart`` event carries ``tier`` so the
        fleet report prices which path recovery took.
        """
        from ..resilience import elastic as _elastic

        comm = _elastic.reform_world(
            communicator_name, devices=devices, **(comm_kwargs or {})
        )
        trainer = build(comm)
        ckpt = trainer._find_checkpointer()
        restored = None
        tier = None
        if peer_store is not None:
            peer_store.rebind(comm)
            peer_step = peer_store.newest_common_step()
            fs_step = (ckpt.newest_common_step()
                       if ckpt is not None else None)
            if peer_step is not None and (
                fs_step is None or peer_step >= fs_step
            ):
                restored = peer_store.restore_trainer(trainer)
                if restored is not None:
                    tier = "peer"
        if restored is None and ckpt is not None:
            restored = ckpt.restore_trainer(trainer)
            if restored is not None:
                tier = "fs"
        resized = (peer_store.last_resize
                   if tier == "peer" and peer_store is not None
                   else getattr(ckpt, "last_resize", None))
        trainer.resilience_log.record(
            "elastic_restart", "trainer.run_elastic",
            restored_step=restored, world=comm.size,
            resized=resized, tier=tier,
        )
        trainer.run(max_restarts=max_restarts)
        return trainer

    # -- state (for checkpointing) -------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        out = {
            "iteration": self.iteration,
            "iterator": self.updater.iterator.serialize()
            if hasattr(self.updater.iterator, "serialize") else None,
        }
        adaptive = self._find_adaptive()
        if adaptive is not None:
            # one JSON-string leaf: scalar-shaped, so it survives the
            # elastic resharder verbatim across any N→M (the POLICY
            # decides what a world change resets — its per-process
            # maps — at the first observe() in the new world)
            import json as _json

            out["adaptive"] = _json.dumps(adaptive.policy.state_dict())
        return out

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.iteration = state["iteration"]
        if state.get("iterator") and hasattr(self.updater.iterator, "restore"):
            self.updater.iterator.restore(state["iterator"])
        adaptive = self._find_adaptive()
        raw = state.get("adaptive")
        if adaptive is not None and raw is not None:
            import json as _json

            try:
                doc = _json.loads(str(raw))
                if not isinstance(doc, dict):
                    raise TypeError(
                        f"adaptive state decoded to "
                        f"{type(doc).__name__}, not an object"
                    )
                adaptive.policy.load_state_dict(doc)
            except (ValueError, TypeError, KeyError,
                    AttributeError) as e:
                warnings.warn(
                    f"could not restore adaptive policy state "
                    f"({type(e).__name__}: {e}); hysteresis starts fresh"
                )

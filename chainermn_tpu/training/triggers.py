"""Trigger objects deciding when trainer extensions fire.

The reference delegated this to Chainer's trainer
(``trainer.extend(ext, trigger=(1, 'epoch'))``); this framework carries its
own minimal implementation so the multi-node extensions (evaluator,
checkpointer, reports) have the same ergonomics.
"""

from __future__ import annotations


class IntervalTrigger:
    def __init__(self, period: int, unit: str = "epoch"):
        if unit not in ("epoch", "iteration"):
            raise ValueError(f"unit must be epoch|iteration, got {unit!r}")
        self.period = period
        self.unit = unit
        self._last_fired = 0

    def __call__(self, trainer) -> bool:
        if self.unit == "iteration":
            count = trainer.iteration
        else:
            count = trainer.epoch
        if count - self._last_fired >= self.period:
            self._last_fired = count
            return True
        return False

    def state(self):
        return {"last_fired": self._last_fired}

    def restore(self, state):
        self._last_fired = state["last_fired"]


def get_trigger(trigger) -> IntervalTrigger:
    if isinstance(trigger, IntervalTrigger):
        return trigger
    if trigger is None:
        return IntervalTrigger(1, "epoch")
    period, unit = trigger
    return IntervalTrigger(period, unit)

from .point_to_point import send, recv, exchange, pseudo_connect  # noqa: F401
from .collectives import (  # noqa: F401
    all_gather,
    all_to_all,
    bcast,
    gather,
    scatter,
    reduce_scatter,
    psum,
    pmean,
    pmax,
    pmin,
    ppermute,
)

__all__ = [
    "send", "recv", "exchange", "pseudo_connect",
    "all_gather", "all_to_all", "bcast", "gather", "scatter",
    "reduce_scatter", "psum", "pmean", "pmax", "pmin", "ppermute",
]
